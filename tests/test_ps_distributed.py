"""Parameter-server distributed training tests.

Mirrors the reference's localhost pattern (test_dist_base.py: N pservers +
N trainers on 127.0.0.1, loss/param parity vs local training, SURVEY.md
§4.6) — here pservers/trainers are threads sharing nothing but the C++ RPC
transport, and parity is exact: sync-PS SGD over 2 trainers with mean
aggregation must equal local SGD on the concatenated batch.
"""

import threading

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.initializer import Constant


from dist_utils import free_ports as _free_ports  # noqa: E402


def _build(lr=0.1):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.data("y", shape=[1])
        pred = fluid.layers.fc(
            x, 1,
            param_attr=fluid.ParamAttr(initializer=Constant(0.1)),
            bias_attr=fluid.ParamAttr(initializer=Constant(0.0)))
        diff = fluid.layers.elementwise_sub(pred, y)
        loss = fluid.layers.reduce_mean(
            fluid.layers.elementwise_mul(diff, diff))
        fluid.optimizer.SGD(lr).minimize(loss)
    return main, startup, loss


def _make_data(steps, bs, seed):
    rng = np.random.RandomState(seed)
    w = np.array([[1.0], [-2.0], [0.5], [3.0]], "f")
    xs = rng.rand(steps, bs, 4).astype("f")
    ys = xs @ w + 0.1
    return xs, ys.astype("f")


def test_transpile_structure():
    main, startup, loss = _build()
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, startup_program=startup,
                pservers="127.0.0.1:7164,127.0.0.1:7165", trainers=2)
    tp = t.get_trainer_program()
    assert not any("sgd" == op.type for op in tp.global_block().ops)
    meta = tp._ps_trainer
    assert set(meta["param_to_ep"].values()) == {
        "127.0.0.1:7164", "127.0.0.1:7165"}  # 2 params spread over 2 servers
    for ep in ("127.0.0.1:7164", "127.0.0.1:7165"):
        sprog, sstart = t.get_pserver_programs(ep)
        assert sprog.global_block().ops[0].type == "listen_and_serv"
        opt_ops = sprog._ps_server["optimize_program"].global_block().ops
        assert any(op.type == "sgd" for op in opt_ops)
        assert len(sprog._ps_server["params"]) == 1
        assert len(sstart.global_block().ops) >= 1


def test_ps_training_matches_local():
    steps, bs = 8, 8
    eps = ["127.0.0.1:%d" % p for p in _free_ports(2)]
    xs, ys = _make_data(steps, 2 * bs, seed=7)

    # ---- local baseline on the full batch ---------------------------------
    main_l, startup_l, loss_l = _build()
    exe_l = fluid.Executor(fluid.CPUPlace())
    scope_l = fluid.Scope()
    with fluid.scope_guard(scope_l):
        exe_l.run(startup_l)
        for i in range(steps):
            exe_l.run(main_l, feed={"x": xs[i], "y": ys[i]},
                      fetch_list=[loss_l])
        params_local = {
            p.name: np.asarray(scope_l.find_var(p.name).get_tensor().numpy())
            for p in main_l.global_block().all_parameters()
        }

    # ---- distributed: 2 pservers + 2 trainers -----------------------------
    main, startup, loss = _build()
    pserver_threads = []
    pserver_errs = []

    def run_pserver(ep):
        try:
            t = fluid.DistributeTranspiler()
            t.transpile(trainer_id=0, program=main, startup_program=startup,
                        pservers=",".join(eps), trainers=2)
            prog, sprog = t.get_pserver_programs(ep)
            exe = fluid.Executor(fluid.CPUPlace())
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe.run(sprog)
                exe.run(prog, scope=scope)
        except Exception as e:  # pragma: no cover
            pserver_errs.append(e)

    for ep in eps:
        th = threading.Thread(target=run_pserver, args=(ep,), daemon=True)
        th.start()
        pserver_threads.append(th)

    trainer_params = [None, None]
    trainer_errs = []

    def run_trainer(tid):
        try:
            t = fluid.DistributeTranspiler()
            t.transpile(trainer_id=tid, program=main,
                        startup_program=startup, pservers=",".join(eps),
                        trainers=2)
            tp = t.get_trainer_program()
            exe = fluid.Executor(fluid.CPUPlace())
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe.run(startup)
                half = slice(tid * bs, (tid + 1) * bs)
                for i in range(steps):
                    exe.run(tp, feed={"x": xs[i][half], "y": ys[i][half]},
                            fetch_list=[], scope=scope)
                trainer_params[tid] = {
                    p: np.asarray(scope.find_var(p).get_tensor().numpy())
                    for p in tp._ps_trainer["param_to_ep"]
                }
                scope._ps_comm.complete()
        except Exception as e:  # pragma: no cover
            trainer_errs.append(e)

    tthreads = [threading.Thread(target=run_trainer, args=(i,), daemon=True)
                for i in range(2)]
    for th in tthreads:
        th.start()
    for th in tthreads:
        th.join(timeout=120)
    for th in pserver_threads:
        th.join(timeout=30)
    assert not trainer_errs, trainer_errs
    assert not pserver_errs, pserver_errs
    assert trainer_params[0] is not None and trainer_params[1] is not None

    # both trainers hold identical params (sync PS), equal to local
    # training.  Param names differ between the two program builds (global
    # unique-name counter), so match positionally: sort by shape-then-name
    # (w is (4,1), b is (1,)).
    local_sorted = [params_local[k] for k in sorted(
        params_local, key=lambda n: (len(params_local[n].shape), n))]
    t0 = trainer_params[0]
    t0_sorted = [t0[k] for k in sorted(
        t0, key=lambda n: (len(t0[n].shape), n))]
    t1 = trainer_params[1]
    t1_sorted = [t1[k] for k in sorted(
        t1, key=lambda n: (len(t1[n].shape), n))]
    for a, b, c in zip(local_sorted, t0_sorted, t1_sorted):
        np.testing.assert_allclose(b, a, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(c, b, rtol=1e-6)


def test_async_ps_converges():
    """Async mode (no barriers, per-arrival updates): must converge on the
    linear task and shut down cleanly (reference AsyncCommunicator path)."""
    steps, bs = 40, 8
    eps = ["127.0.0.1:%d" % p for p in _free_ports(2)]
    xs, ys = _make_data(steps, 2 * bs, seed=11)
    main, startup, loss = _build(lr=0.02)
    errs = []

    def run_pserver(ep):
        try:
            t = fluid.DistributeTranspiler()
            t.transpile(trainer_id=0, program=main, startup_program=startup,
                        pservers=",".join(eps), trainers=2, sync_mode=False)
            prog, sprog = t.get_pserver_programs(ep)
            exe = fluid.Executor(fluid.CPUPlace())
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe.run(sprog)
                exe.run(prog, scope=scope)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    for ep in eps:
        threading.Thread(target=run_pserver, args=(ep,), daemon=True).start()

    final = [None, None]

    def run_trainer(tid):
        try:
            t = fluid.DistributeTranspiler()
            t.transpile(trainer_id=tid, program=main,
                        startup_program=startup, pservers=",".join(eps),
                        trainers=2, sync_mode=False)
            tp = t.get_trainer_program()
            assert tp._ps_trainer["sync"] is False
            exe = fluid.Executor(fluid.CPUPlace())
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe.run(startup)
                # eval program: same forward, NO _ps_trainer metadata, so
                # an eval run neither sends grads nor trains on the batch
                eval_prog = tp.clone()
                if hasattr(eval_prog, "_ps_trainer"):
                    del eval_prog._ps_trainer

                def eval_loss():
                    lv = eval_prog.global_block().var(loss.name)
                    ev, = exe.run(eval_prog, feed={"x": xs[0][half],
                                                   "y": ys[0][half]},
                                  fetch_list=[lv], scope=scope)
                    return float(np.asarray(ev).ravel()[0])

                half = slice(tid * bs, (tid + 1) * bs)
                first = eval_loss()
                import time as _time

                for i in range(steps):
                    exe.run(tp, feed={"x": xs[i][half], "y": ys[i][half]},
                            fetch_list=[], scope=scope)
                    # async has no staleness bound: pace the trainer so the
                    # server's (jit-compiling) update loop can keep up —
                    # otherwise all 40 steps can finish against the initial
                    # params, which is legal async behavior but untestable
                    _time.sleep(0.02)
                final[tid] = (first, eval_loss())
                scope._ps_comm.complete()
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=run_trainer, args=(i,), daemon=True)
          for i in range(2)]
    for th in ts:
        th.start()
    for th in ts:
        th.join(timeout=120)
    assert not errs, errs
    # eval loss on the fixed batch must drop well below its initial value
    for pair in final:
        assert pair is not None, final
        first, last = pair
        assert last < 0.75 * first, final


def test_async_eviction_reclaims_replay_state():
    """Async-mode eviction (ps.py run_async __evict__ handler): a trainer
    that stops heartbeating past FLAGS_worker_hb_timeout gets its replay-
    filter entry and liveness slot reclaimed, so a frame reusing its old
    (nonce, seq) tag is fresh again and applies.  A raw RpcClient plays the
    trainer so the dedupe tag is fully controlled."""
    import time

    from paddle_tpu.distributed import ps as ps_mod
    from paddle_tpu.native.rpc import RpcClient

    old_to = fluid.flags.flag("worker_hb_timeout")
    fluid.flags.set_flags({"FLAGS_worker_hb_timeout": 1.0})
    errs = []
    client = None
    try:
        ep = "127.0.0.1:%d" % _free_ports(1)[0]
        main, startup, loss = _build(lr=0.5)
        t = fluid.DistributeTranspiler()
        t.transpile(trainer_id=0, program=main, startup_program=startup,
                    pservers=ep, trainers=1, sync_mode=False)
        prog, sprog = t.get_pserver_programs(ep)
        grad_map = prog._ps_server["grad_map"]

        def run_pserver():
            try:
                exe = fluid.Executor(fluid.CPUPlace())
                scope = fluid.Scope()
                with fluid.scope_guard(scope):
                    exe.run(sprog)
                    exe.run(prog, scope=scope)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        th = threading.Thread(target=run_pserver, daemon=True)
        th.start()

        gname = next(iter(grad_map))
        pname = grad_map[gname]
        shape = tuple(main.global_block().var(pname).shape)
        g = np.ones(shape, "float32")
        pkey = ps_mod._vkey(pname, -1)
        client = RpcClient(ep)

        def wait_param(differs_from, timeout=20.0):
            deadline = time.time() + timeout
            while time.time() < deadline:
                cur = client.get_var(pkey)
                if not np.array_equal(cur, differs_from):
                    return cur
                time.sleep(0.05)
            return client.get_var(pkey)

        v0 = client.get_var(pkey)
        # heartbeat registers liveness, then one tagged grad applies
        hb = np.asarray([0], np.int64)
        client.send_var(ps_mod._HB_PREFIX + "0", hb)
        tag = "%s%s0:123:0" % (gname, ps_mod._SEQ_SEP)
        client.send_var(tag, g)
        v1 = wait_param(v0)
        assert not np.array_equal(v1, v0)

        # replayed frame (same tag, live trainer): at-most-once filter
        # drops it — the param must NOT move again
        client.send_var(tag, g)
        time.sleep(0.6)
        np.testing.assert_array_equal(client.get_var(pkey), v1)

        # go silent: no more heartbeats.  The checker thread evicts after
        # the 1s timeout, reclaiming the (tid 0) replay entry; from then
        # on the SAME tag is a fresh frame and applies.
        applied = False
        deadline = time.time() + 20.0
        v_prev = client.get_var(pkey)
        while time.time() < deadline:
            client.send_var(tag, g)
            time.sleep(0.4)
            cur = client.get_var(pkey)
            if not np.array_equal(cur, v_prev):
                applied = True
                break
        assert applied, "evicted trainer's tag never became fresh again"

        client.complete()
        th.join(timeout=30)
        assert not errs, errs
    finally:
        if client is not None:
            try:
                client.close()
            except Exception:
                pass
        fluid.flags.set_flags({"FLAGS_worker_hb_timeout": old_to})


def test_geo_sgd_converges():
    """Geo-SGD: local training + periodic delta pushes; both trainers'
    params drift toward each other through the server merge and the task
    converges (reference geo_sgd_transpiler.py semantics)."""
    steps, bs, K = 24, 8, 4
    eps = ["127.0.0.1:%d" % p for p in _free_ports(1)]
    xs, ys = _make_data(steps, 2 * bs, seed=21)
    main, startup, loss = _build(lr=0.05)
    cfg = fluid.DistributeTranspilerConfig()
    cfg.geo_sgd_mode = True
    cfg.geo_sgd_need_push_nums = K
    errs = []

    def run_pserver(ep):
        try:
            t = fluid.DistributeTranspiler(config=cfg)
            t.transpile(trainer_id=0, program=main, startup_program=startup,
                        pservers=",".join(eps), trainers=2)
            prog, sprog = t.get_pserver_programs(ep)
            exe = fluid.Executor(fluid.CPUPlace())
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe.run(sprog)
                exe.run(prog, scope=scope)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threading.Thread(target=run_pserver, args=(eps[0],), daemon=True).start()
    final = [None, None]

    def run_trainer(tid):
        try:
            t = fluid.DistributeTranspiler(config=cfg)
            t.transpile(trainer_id=tid, program=main,
                        startup_program=startup, pservers=",".join(eps),
                        trainers=2)
            tp = t.get_trainer_program()
            # geo keeps the optimizer in the trainer program
            assert any(op.type == "sgd"
                       for op in tp.global_block().ops)
            exe = fluid.Executor(fluid.CPUPlace())
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                exe.run(startup)
                half = slice(tid * bs, (tid + 1) * bs)
                # fixed-batch eval through a non-PS clone (no sends, no
                # local update) — per-batch losses are too noisy to gate on
                eval_prog = tp.clone(for_test=True)
                if hasattr(eval_prog, "_ps_trainer"):
                    del eval_prog._ps_trainer

                def eval_loss():
                    lv = eval_prog.global_block().var(loss.name)
                    ev, = exe.run(eval_prog, feed={"x": xs[0][half],
                                                   "y": ys[0][half]},
                                  fetch_list=[lv], scope=scope)
                    return float(np.asarray(ev).ravel()[0])

                first = eval_loss()
                for i in range(steps):
                    exe.run(tp, feed={"x": xs[i][half], "y": ys[i][half]},
                            fetch_list=[], scope=scope)
                final[tid] = (first, eval_loss())
                scope._ps_comm.complete()
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=run_trainer, args=(i,), daemon=True)
          for i in range(2)]
    for th in ts:
        th.start()
    for th in ts:
        th.join(timeout=120)
    assert not errs, errs
    for pair in final:
        assert pair is not None, final
        first, last = pair
        assert last < 0.6 * first, final
