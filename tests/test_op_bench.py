"""Config-driven op micro-bench harness test (op_tester.cc parity)."""

import json
import os
import subprocess
import sys


def test_op_bench_runs(tmp_path):
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
    import op_bench

    r = op_bench.bench_op({
        "op_type": "matmul",
        "inputs": {"X": {"dims": [8, 16]}, "Y": {"dims": [16, 4]}},
        "repeat": 3, "warmup": 1,
    }, device="cpu")
    assert r["op_type"] == "matmul"
    assert r["mean_ms"] > 0
    assert r["min_ms"] <= r["p50_ms"]

    # the CLI path: natural/zeros initializers + multiple configs
    cfg = [{"op_type": "relu",
            "inputs": {"X": {"dims": [4, 4], "initializer": "natural"}},
            "repeat": 2, "warmup": 1},
           {"op_type": "scale",
            "inputs": {"X": {"dims": [4], "initializer": "zeros"}},
            "attrs": {"scale": 2.0}, "repeat": 2, "warmup": 1}]
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps(cfg))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "op_bench.py"),
         str(p), "--device", "cpu"],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr
    lines = [json.loads(l) for l in out.stdout.strip().splitlines()]
    assert [l["op_type"] for l in lines] == ["relu", "scale"]
