"""Profiler, runtime flags, and metric accumulator tests
(reference: test_profiler.py, test_metrics.py patterns)."""

import json

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import metrics, profiler


def _tiny_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.fc(x, 3)
        loss = fluid.layers.reduce_mean(y)
    return main, startup, loss


def test_profiler_records_and_exports(tmp_path):
    main, startup, loss = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    path = str(tmp_path / "trace.json")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        profiler.reset_profiler()
        with profiler.profiler("All", "total", path):
            for _ in range(3):
                exe.run(main, feed={"x": np.ones((2, 4), "f")},
                        fetch_list=[loss])
    with open(path) as f:
        trace = json.load(f)
    runs = [e for e in trace["traceEvents"] if e["name"] == "Executor::Run"]
    assert len(runs) == 3
    assert all(e["dur"] >= 0 for e in runs)
    # disabled afterwards: no new events
    n = len(trace["traceEvents"])
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
    profiler.save_chrome_trace(path)
    with open(path) as f:
        assert len(json.load(f)["traceEvents"]) == n


def test_profiler_dygraph_events():
    from paddle_tpu import dygraph

    profiler.reset_profiler()
    with dygraph.guard():
        profiler.start_profiler()
        a = dygraph.to_variable(np.ones((2, 2), "f"))
        b = fluid.layers.elementwise_add(a, a)
        profiler.stop_profiler()
    assert any(e[0] == "elementwise_add" for e in profiler._events)


def test_check_nan_inf_flag_static():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[2])
        y = fluid.layers.elementwise_div(x, fluid.layers.scale(x, scale=0.0))
    exe = fluid.Executor(fluid.CPUPlace())
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            with pytest.raises(RuntimeError, match="NaN/Inf"):
                exe.run(main, feed={"x": np.ones((1, 2), "f")},
                        fetch_list=[y])
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})


def test_flags_get_set_roundtrip():
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    assert fluid.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"] is True
    fluid.set_flags({"check_nan_inf": False})  # short name accepted
    assert fluid.get_flags(["check_nan_inf"])["FLAGS_check_nan_inf"] is False
    # inert flags accepted without error
    fluid.set_flags({"FLAGS_eager_delete_tensor_gb": 1.5})
    assert fluid.get_flags("FLAGS_eager_delete_tensor_gb")[
        "FLAGS_eager_delete_tensor_gb"] == 1.5


# -- metrics -----------------------------------------------------------------


def test_precision_recall():
    p, r = metrics.Precision(), metrics.Recall()
    preds = np.array([1, 1, 0, 1, 0])
    labels = np.array([1, 0, 0, 1, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    assert p.eval() == pytest.approx(2 / 3)
    assert r.eval() == pytest.approx(2 / 3)
    p.reset()
    assert p.eval() == 0.0


def test_accuracy_weighted():
    acc = metrics.Accuracy()
    acc.update(0.5, weight=10)
    acc.update(1.0, weight=10)
    assert acc.eval() == pytest.approx(0.75)
    with pytest.raises(ValueError):
        acc.update(0.5, weight=-1)


def test_chunk_evaluator():
    ce = metrics.ChunkEvaluator()
    ce.update(10, 8, 6)
    precision, recall, f1 = ce.eval()
    assert precision == pytest.approx(0.6)
    assert recall == pytest.approx(0.75)
    assert f1 == pytest.approx(2 * 0.6 * 0.75 / 1.35)


def test_edit_distance():
    ed = metrics.EditDistance()
    ed.update(np.array([0.0, 2.0, 1.0]), 3)
    avg, err = ed.eval()
    assert avg == pytest.approx(1.0)
    assert err == pytest.approx(2 / 3)


def test_auc_matches_sklearn_style_reference():
    rng = np.random.RandomState(0)
    labels = rng.randint(0, 2, 200)
    # informative scores
    scores = np.clip(labels * 0.6 + rng.rand(200) * 0.5, 0, 1)
    auc = metrics.Auc()
    auc.update(scores, labels)
    got = auc.eval()

    # exact AUC by rank statistic
    pos = scores[labels == 1]
    neg = scores[labels == 0]
    exact = np.mean([(p > n) + 0.5 * (p == n) for p in pos for n in neg])
    assert got == pytest.approx(exact, abs=2e-3)


def test_composite_metric():
    cm = metrics.CompositeMetric()
    cm.add_metric(metrics.Precision())
    cm.add_metric(metrics.Recall())
    cm.update(np.array([1, 0]), np.array([1, 1]))
    p, r = cm.eval()
    assert p == 1.0 and r == 0.5
