"""Golden + gradient tests for NN ops (mirrors reference test_conv2d_op.py,
test_pool2d_op.py, test_batch_norm_op.py, test_layer_norm_op.py,
test_dropout_op.py, test_lookup_table_op.py,
test_softmax_with_cross_entropy_op.py)."""

import numpy as np
import pytest

from op_test import OpTest


def _rand(*shape, seed=None):
    return np.random.RandomState(seed or (sum(shape) + 7)).uniform(
        -1, 1, shape
    ).astype("float32")


def _conv2d_ref(x, w, stride, pad):
    n, c, h, wd = x.shape
    oc, ic, kh, kw = w.shape
    sh, sw = stride
    ph, pw = pad
    xp = np.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    oh = (h + 2 * ph - kh) // sh + 1
    ow = (wd + 2 * pw - kw) // sw + 1
    out = np.zeros((n, oc, oh, ow), "float32")
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw]
            out[:, :, i, j] = np.einsum("nchw,ochw->no", patch, w)
    return out


class TestConv2dOp(OpTest):
    op_type = "conv2d"
    # on-chip grad-check config (tests/test_tpu_tier_ops.py)
    tpu_grad = {"inputs_to_check": ["Input", "Filter"],
                "max_elements": 64}
    atol = 1e-4

    def setup_method(self, m):
        x = _rand(2, 3, 8, 8)
        w = _rand(4, 3, 3, 3)
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [2, 2], "paddings": [1, 1],
                      "dilations": [1, 1], "groups": 1}
        self.outputs = {"Output": _conv2d_ref(x, w, (2, 2), (1, 1))}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["Input", "Filter"], output_names="Output",
                        max_elements=64, max_relative_error=0.02)


class TestDepthwiseConv(OpTest):
    op_type = "depthwise_conv2d"
    atol = 1e-4

    def setup_method(self, m):
        x = _rand(1, 4, 6, 6)
        w = _rand(4, 1, 3, 3)
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [1, 1], "paddings": [1, 1],
                      "dilations": [1, 1], "groups": 4}
        # reference: per-channel conv
        out = np.zeros((1, 4, 6, 6), "float32")
        xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
        for ch in range(4):
            for i in range(6):
                for j in range(6):
                    out[0, ch, i, j] = (
                        xp[0, ch, i:i + 3, j:j + 3] * w[ch, 0]
                    ).sum()
        self.outputs = {"Output": out}

    def test_output(self):
        self.check_output()


class TestPool2dMax(OpTest):
    op_type = "pool2d"

    def setup_method(self, m):
        x = _rand(2, 3, 6, 6)
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "max", "ksize": [2, 2],
                      "strides": [2, 2], "paddings": [0, 0]}
        out = x.reshape(2, 3, 3, 2, 3, 2).max(axis=(3, 5))
        self.outputs = {"Out": out}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X"], max_elements=64)


class TestPool2dAvg(OpTest):
    op_type = "pool2d"

    def setup_method(self, m):
        x = _rand(2, 3, 6, 6)
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "avg", "ksize": [2, 2],
                      "strides": [2, 2], "paddings": [0, 0]}
        out = x.reshape(2, 3, 3, 2, 3, 2).mean(axis=(3, 5))
        self.outputs = {"Out": out}

    def test_output(self):
        self.check_output()


class TestPool2dGlobal(OpTest):
    op_type = "pool2d"

    def setup_method(self, m):
        x = _rand(2, 3, 5, 5)
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "avg", "ksize": [1, 1],
                      "strides": [1, 1], "paddings": [0, 0],
                      "global_pooling": True}
        self.outputs = {"Out": x.mean(axis=(2, 3), keepdims=True)}

    def test_output(self):
        self.check_output()


def _bn_ref(x, scale, bias, eps):
    m = x.mean(axis=(0, 2, 3))
    v = x.var(axis=(0, 2, 3))
    xh = (x - m.reshape(1, -1, 1, 1)) / np.sqrt(v + eps).reshape(1, -1, 1, 1)
    return xh * scale.reshape(1, -1, 1, 1) + bias.reshape(1, -1, 1, 1), m, v


class TestBatchNormTrain(OpTest):
    op_type = "batch_norm"
    atol = 1e-4

    def setup_method(self, m):
        x = _rand(4, 3, 5, 5)
        scale, bias = _rand(3, seed=1), _rand(3, seed=2)
        mean = np.zeros(3, "float32")
        var = np.ones(3, "float32")
        eps = 1e-5
        mom = 0.9
        y, bm, bv = _bn_ref(x, scale, bias, eps)
        self.inputs = {"X": x, "Scale": scale, "Bias": bias,
                       "Mean": mean, "Variance": var}
        self.attrs = {"momentum": mom, "epsilon": eps, "is_test": False}
        self.outputs = {
            "Y": [("y", y)],
            "MeanOut": [("mean_out", mom * mean + (1 - mom) * bm)],
            "VarianceOut": [("var_out", mom * var + (1 - mom) * bv)],
            "SavedMean": [("saved_mean", bm)],
            "SavedVariance": [("saved_var", 1.0 / np.sqrt(bv + eps))],
            "ReserveSpace": [("rs", None)],
        }

    def test_output(self):
        self.check_output()


class TestBatchNormInfer(OpTest):
    op_type = "batch_norm"
    atol = 1e-4

    def setup_method(self, m):
        x = _rand(4, 3, 5, 5)
        scale, bias = _rand(3, seed=1), _rand(3, seed=2)
        mean = _rand(3, seed=3) * 0.1
        var = np.abs(_rand(3, seed=4)) + 0.5
        eps = 1e-5
        xh = (x - mean.reshape(1, -1, 1, 1)) / np.sqrt(
            var + eps).reshape(1, -1, 1, 1)
        y = xh * scale.reshape(1, -1, 1, 1) + bias.reshape(1, -1, 1, 1)
        self.inputs = {"X": x, "Scale": scale, "Bias": bias,
                       "Mean": mean, "Variance": var}
        self.attrs = {"momentum": 0.9, "epsilon": eps, "is_test": True}
        self.outputs = {"Y": [("y", y)]}

    def test_output(self):
        self.check_output(no_check_set=("MeanOut", "VarianceOut",
                                        "SavedMean", "SavedVariance",
                                        "ReserveSpace"))


class TestLayerNorm(OpTest):
    op_type = "layer_norm"
    atol = 1e-4
    tpu_grad = {"inputs_to_check": ["X", "Scale", "Bias"],
                "output_names": ["y"], "max_elements": 48}

    def setup_method(self, m):
        x = _rand(4, 6)
        scale, bias = _rand(6, seed=5), _rand(6, seed=6)
        eps = 1e-5
        mu = x.mean(axis=1, keepdims=True)
        var = x.var(axis=1, keepdims=True)
        y = (x - mu) / np.sqrt(var + eps) * scale + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"epsilon": eps, "begin_norm_axis": 1}
        self.outputs = {
            "Y": [("y", y)],
            "Mean": [("mean", mu.ravel())],
            "Variance": [("var", var.ravel())],
        }

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X", "Scale", "Bias"], output_names=["y"],
                        max_elements=48, max_relative_error=0.02)


class TestDropoutTestMode(OpTest):
    op_type = "dropout"

    def setup_method(self, m):
        x = _rand(4, 8)
        self.inputs = {"X": x}
        self.attrs = {"dropout_prob": 0.3, "is_test": True,
                      "dropout_implementation": "upscale_in_train"}
        self.outputs = {"Out": x, "Mask": None}

    def test_output(self):
        self.check_output(no_check_set=("Mask",))


class TestDropoutTestModeDowngrade(OpTest):
    """Regression (ADVICE round 5): the downgrade_in_infer is_test path
    must scale by the NOMINAL (1-p), not the 256-quantized realized keep
    prob — imported reference models expect exact inference parity."""

    op_type = "dropout"

    def setup_method(self, m):
        x = _rand(4, 8)
        self.inputs = {"X": x}
        self.attrs = {"dropout_prob": 0.3, "is_test": True,
                      "dropout_implementation": "downgrade_in_infer"}
        self.outputs = {"Out": x * np.float32(1.0 - 0.3), "Mask": None}

    def test_output(self):
        self.check_output(no_check_set=("Mask",))


def test_dropout_train_statistics():
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1000])
        y = fluid.layers.dropout(x, 0.4,
                                 dropout_implementation="upscale_in_train")
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        out, = exe.run(main, feed={"x": np.ones((8, 1000), "float32")},
                       fetch_list=[y])
    drop_rate = (np.asarray(out) == 0).mean()
    assert abs(drop_rate - 0.4) < 0.03
    kept = np.asarray(out)[np.asarray(out) != 0]
    # the byte-threshold draw keeps with probability round(0.6*256)/256 and
    # upscales by exactly that realized probability (ops/common.py
    # bernoulli_bytes), so E[out] = x holds exactly under the quantized draw
    from paddle_tpu.ops.common import realized_keep_prob

    q = realized_keep_prob(0.6)
    assert abs(q - 0.6) <= 1 / 512 + 1e-12
    np.testing.assert_allclose(kept, 1 / q, rtol=1e-5)


class TestLookupTableV2(OpTest):
    op_type = "lookup_table_v2"

    def setup_method(self, m):
        w = _rand(10, 4)
        ids = np.array([[1, 3], [7, 0]], "int64")
        self.inputs = {"W": w, "Ids": ids}
        self.attrs = {"padding_idx": -1}
        self.outputs = {"Out": w[ids]}

    def test_output(self):
        self.check_output()


class TestLookupTablePadding(OpTest):
    op_type = "lookup_table_v2"

    def setup_method(self, m):
        w = _rand(10, 4)
        ids = np.array([[1, 2], [2, 5]], "int64")
        out = w[ids].copy()
        out[ids == 2] = 0.0
        self.inputs = {"W": w, "Ids": ids}
        self.attrs = {"padding_idx": 2}
        self.outputs = {"Out": out}

    def test_output(self):
        self.check_output()


class TestSoftmaxWithCE(OpTest):
    op_type = "softmax_with_cross_entropy"

    def setup_method(self, m):
        logits = _rand(5, 7)
        label = np.random.RandomState(3).randint(0, 7, (5, 1)).astype("int64")
        z = logits - logits.max(axis=1, keepdims=True)
        sm = np.exp(z) / np.exp(z).sum(axis=1, keepdims=True)
        loss = -np.log(sm[np.arange(5), label.ravel()]).reshape(5, 1)
        self.inputs = {"Logits": logits, "Label": label}
        self.outputs = {"Softmax": [("sm", sm)], "Loss": [("loss", loss)]}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["Logits"], output_names=["loss"], max_elements=35)


class TestSoftmaxWithCESoftLabel(OpTest):
    op_type = "softmax_with_cross_entropy"

    def setup_method(self, m):
        logits = _rand(4, 6)
        lab = np.abs(_rand(4, 6, seed=9)) + 0.01
        lab = lab / lab.sum(axis=1, keepdims=True)
        z = logits - logits.max(axis=1, keepdims=True)
        sm = np.exp(z) / np.exp(z).sum(axis=1, keepdims=True)
        loss = -(lab * np.log(sm)).sum(axis=1, keepdims=True)
        self.inputs = {"Logits": logits, "Label": lab.astype("float32")}
        self.attrs = {"soft_label": True}
        self.outputs = {"Softmax": [("sm", sm)], "Loss": [("loss", loss)]}

    def test_output(self):
        self.check_output()


class TestCrossEntropy(OpTest):
    op_type = "cross_entropy"

    def setup_method(self, m):
        p = np.abs(_rand(4, 5)) + 0.05
        p = p / p.sum(axis=1, keepdims=True)
        label = np.random.RandomState(5).randint(0, 5, (4, 1)).astype("int64")
        loss = -np.log(p[np.arange(4), label.ravel()]).reshape(4, 1)
        self.inputs = {"X": p.astype("float32"), "Label": label}
        self.outputs = {"Y": loss.astype("float32")}

    def test_output(self):
        self.check_output()


class TestGroupNorm(OpTest):
    op_type = "group_norm"
    atol = 1e-4

    def setup_method(self, m):
        x = _rand(2, 4, 3, 3)
        scale, bias = _rand(4, seed=11), _rand(4, seed=12)
        eps = 1e-5
        r = x.reshape(2, 2, 2, 3, 3)
        mu = r.mean(axis=(2, 3, 4), keepdims=True)
        var = r.var(axis=(2, 3, 4), keepdims=True)
        y = ((r - mu) / np.sqrt(var + eps)).reshape(2, 4, 3, 3)
        y = y * scale.reshape(1, 4, 1, 1) + bias.reshape(1, 4, 1, 1)
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"epsilon": eps, "groups": 2}
        self.outputs = {
            "Y": [("y", y)],
            "Mean": [("mean", mu.reshape(2, 2))],
            "Variance": [("var", var.reshape(2, 2))],
        }

    def test_output(self):
        self.check_output()
