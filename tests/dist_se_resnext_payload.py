"""dist_se_resnext-analog payload (reference dist_se_resnext.py): one
SE-ResNeXt bottleneck block (cardinality-8 grouped conv + squeeze-excite
gate) + classifier head, trained sync-PS across 2 pservers x 2 trainers.
BN running stats stay trainer-local (reference behavior: only parameters
ride the PS; stats are saved from trainer 0)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models.se_resnext import bottleneck_block

STEPS = 4
BS = 4  # per trainer


def build(merge_k=1):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 55
    startup.random_seed = 55
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[8, 8, 8])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        x = bottleneck_block(img, filters=8, stride=1, cardinality=8)
        pool = fluid.layers.pool2d(x, pool_type="avg",
                                   global_pooling=True)
        pool = fluid.layers.reshape(pool, shape=[0, int(pool.shape[1])])
        logits = fluid.layers.fc(pool, 4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        opt = fluid.optimizer.SGD(0.05)
        if merge_k > 1:
            # the EXACT local equivalent of k sync-PS trainers: each
            # trainer normalizes BN over its OWN shard, grads averaged —
            # locally that is k grad-merged shard sub-steps (BN stats per
            # shard), not one full-batch step
            opt = fluid.optimizer.GradientMergeOptimizer(
                opt, k_steps=merge_k, avg=True)
        opt.minimize(loss)
    return main, startup, loss


def make_data(n_trainers):
    rng = np.random.RandomState(321)
    out = []
    for _ in range(STEPS):
        xs = rng.rand(n_trainers * BS, 8, 8, 8).astype("f")
        ys = rng.randint(0, 4, (n_trainers * BS, 1)).astype("int64")
        out.append((xs, ys))
    return out


def _dump(scope, program):
    for p in sorted(program.global_block().all_parameters(),
                    key=lambda v: v.name):
        v = np.asarray(scope.find_var(p.name).get_tensor().numpy())
        print("param:%s:%.8f" % (p.name, float(np.abs(v).sum())),
              flush=True)


def run_local():
    main, startup, loss = build(merge_k=2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for xs, ys in make_data(2):
            for half in (slice(0, BS), slice(BS, 2 * BS)):
                lo, = exe.run(main,
                              feed={"img": xs[half], "label": ys[half]},
                              fetch_list=[loss])
                print("loss:%.8f"
                      % float(np.asarray(lo).reshape(-1)[0]), flush=True)
        _dump(scope, main)


def run_pserver():
    eps = os.environ["PADDLE_PSERVER_ENDPOINTS"]
    cur = os.environ["PADDLE_CURRENT_ENDPOINT"]
    n = int(os.environ["PADDLE_TRAINERS_NUM"])
    main, startup, loss = build()
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, startup_program=startup,
                pservers=eps, trainers=n)
    prog, sprog = t.get_pserver_programs(cur)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(sprog)
        print("pserver:ready", flush=True)
        exe.run(prog, scope=scope)
    print("pserver:done", flush=True)


def run_trainer():
    eps = os.environ["PADDLE_PSERVER_ENDPOINTS"]
    tid = int(os.environ["PADDLE_TRAINER_ID"])
    n = int(os.environ["PADDLE_TRAINERS_NUM"])
    main, startup, loss = build()
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=tid, program=main, startup_program=startup,
                pservers=eps, trainers=n)
    tp = t.get_trainer_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        half = slice(tid * BS, (tid + 1) * BS)
        for xs, ys in make_data(n):
            lo, = exe.run(tp, feed={"img": xs[half], "label": ys[half]},
                          fetch_list=[loss], scope=scope)
            print("loss:%.8f" % float(np.asarray(lo).reshape(-1)[0]),
                  flush=True)
        _dump(scope, main)
        scope._ps_comm.complete()


if __name__ == "__main__":
    role = os.environ.get("PADDLE_TRAINING_ROLE", "LOCAL")
    if role == "PSERVER":
        run_pserver()
    elif role == "TRAINER":
        run_trainer()
    else:
        run_local()
