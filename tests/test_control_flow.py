"""Control flow: while (unrolled + lax.while_loop), tensor arrays,
conditional_block/Switch, StaticRNN (lax.scan) incl. gradients.

Parity model: reference unittests test_while_op.py, test_array_read_write.py,
test_switch.py, test_recurrent_op.py.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers


def _run(main, startup, feed, fetch_list):
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        return exe.run(main, feed=feed, fetch_list=fetch_list)


def test_while_concrete_counter_sums():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = layers.fill_constant(shape=[1], dtype="int64", value=0)
        limit = layers.fill_constant(shape=[1], dtype="int64", value=10)
        total = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
        x = layers.data("x", shape=[10], append_batch_size=False)
        cond = layers.less_than(i, limit)
        w = layers.While(cond)
        with w.block():
            xi = layers.gather(x, i)
            layers.assign(layers.elementwise_add(total, xi), total)
            layers.increment(i, value=1, in_place=True)
            layers.less_than(i, limit, cond=cond)
    xs = np.arange(10).astype("float32")
    (out,) = _run(main, startup, {"x": xs}, [total])
    assert np.allclose(out, xs.sum())


def test_while_traced_condition_lax_loop():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        n = layers.data("n", shape=[1], dtype="int64", append_batch_size=False)
        i = layers.zeros(shape=[1], dtype="int64")
        i = layers.elementwise_add(i, layers.zeros(shape=[1], dtype="int64"))
        acc = layers.data("acc0", shape=[1], append_batch_size=False)
        cond = layers.less_than(i, n)  # traced: n is fed
        w = layers.While(cond)
        with w.block():
            layers.assign(layers.elementwise_add(acc, acc), acc)  # acc *= 2
            layers.increment(i, value=1, in_place=True)
            layers.less_than(i, n, cond=cond)
    (out,) = _run(main, startup,
                  {"n": np.array([5], "int64"), "acc0": np.array([1.0], "float32")},
                  [acc])
    assert np.allclose(out, 32.0)


def test_array_write_read_length():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3], append_batch_size=False)
        i0 = layers.fill_constant(shape=[1], dtype="int64", value=0)
        i1 = layers.fill_constant(shape=[1], dtype="int64", value=1)
        arr = layers.array_write(x, i0)
        y = layers.elementwise_add(x, x)
        layers.array_write(y, i1, array=arr)
        n = layers.array_length(arr)
        r0 = layers.array_read(arr, i0)
        r1 = layers.array_read(arr, i1)
    xs = np.array([1.0, 2.0, 3.0], "float32")
    n_v, r0_v, r1_v = _run(main, startup, {"x": xs}, [n, r0, r1])
    assert int(n_v) == 2
    assert np.allclose(r0_v, xs)
    assert np.allclose(r1_v, 2 * xs)


def test_switch_concrete():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        lr = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
        step = layers.fill_constant(shape=[1], dtype="float32", value=7.0)
        boundary = layers.fill_constant(shape=[1], dtype="float32", value=5.0)
        sw = layers.Switch()
        with sw.case(layers.less_than(step, boundary)):
            layers.assign(layers.fill_constant(shape=[1], dtype="float32",
                                               value=0.1), lr)
        with sw.default():
            layers.assign(layers.fill_constant(shape=[1], dtype="float32",
                                               value=0.01), lr)
    (out,) = _run(main, startup, {}, [lr])
    assert np.allclose(out, 0.01)


def test_conditional_block_traced_pred():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], append_batch_size=False)
        flag = layers.data("flag", shape=[1], dtype="float32",
                           append_batch_size=False)
        out = layers.fill_constant(shape=[4], dtype="float32", value=-1.0)
        out = layers.elementwise_add(out, layers.zeros([4], "float32"))
        pred = layers.greater_than(flag, layers.zeros([1], "float32"))
        sw = layers.Switch()
        with sw.case(pred):
            layers.assign(layers.elementwise_mul(x, x), out)
    xs = np.array([1, 2, 3, 4], "float32")
    (o1,) = _run(main, startup, {"x": xs, "flag": np.array([1.0], "float32")}, [out])
    assert np.allclose(o1, xs * xs)
    (o0,) = _run(main, startup, {"x": xs, "flag": np.array([-1.0], "float32")}, [out])
    assert np.allclose(o0, -np.ones(4, "float32"))


def test_static_rnn_forward():
    T, B, D = 5, 2, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[T, B, D], append_batch_size=False)
        h0 = layers.data("h0", shape=[B, D], append_batch_size=False)
        rnn = layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)
            h_prev = rnn.memory(init=h0)
            h = layers.elementwise_add(x_t, h_prev)
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()
    xs = np.random.RandomState(0).randn(T, B, D).astype("float32")
    h0v = np.zeros((B, D), "float32")
    (o,) = _run(main, startup, {"x": xs, "h0": h0v}, [out])
    assert o.shape == (T, B, D)
    assert np.allclose(o, np.cumsum(xs, axis=0), atol=1e-5)


def test_static_rnn_trains():
    """Gradient flows through lax.scan: train weights of a tiny RNN."""
    T, B, D, H = 4, 8, 3, 5
    rng = np.random.RandomState(1)
    xs = rng.randn(T, B, D).astype("float32")
    ys = rng.randn(B, 1).astype("float32")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[T, B, D], append_batch_size=False)
        y = layers.data("y", shape=[B, 1], append_batch_size=False)
        h0 = layers.fill_constant(shape=[B, H], dtype="float32", value=0.0)
        rnn = layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)
            h_prev = rnn.memory(init=h0)
            z = layers.fc(input=x_t, size=H, act=None, name="rnn_fc")
            h = layers.tanh(layers.elementwise_add(z, h_prev))
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()
        last = layers.slice(out, axes=[0], starts=[T - 1], ends=[T])
        last = layers.reshape(last, [B, H])
        pred = layers.fc(input=last, size=1, act=None)
        loss = layers.reduce_mean(layers.square(pred - y))
        opt = fluid.optimizer.SGDOptimizer(learning_rate=0.1)
        opt.minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(15):
            (lv,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
            losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.7, losses


def test_ifelse_merge():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[1], append_batch_size=False)
        b = layers.data("b", shape=[1], append_batch_size=False)
        pred = layers.less_than(a, b)
        ie = layers.IfElse(pred)
        with ie.true_block():
            ie.output(layers.elementwise_add(a, b))
        with ie.false_block():
            ie.output(layers.elementwise_sub(a, b))
        (out,) = ie()
    (o,) = _run(main, startup,
                {"a": np.array([1.0], "float32"), "b": np.array([2.0], "float32")},
                [out])
    assert np.allclose(o, 3.0)


def test_ifelse_concrete_pred():
    """Concrete predicate: only the taken branch runs; shared slots still
    produce the right output (regression: untaken-branch KeyError)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.fill_constant(shape=[1], dtype="float32", value=1.0)
        b = layers.fill_constant(shape=[1], dtype="float32", value=2.0)
        pred = layers.less_than(a, b)
        ie = layers.IfElse(pred)
        with ie.true_block():
            ie.output(layers.elementwise_add(a, b))
        with ie.false_block():
            ie.output(layers.elementwise_sub(a, b))
        (out,) = ie()
        out = layers.scale(out, scale=1.0)
    (o,) = _run(main, startup, {}, [out])
    assert np.allclose(o, 3.0)
