"""dist_mnist-analog subprocess test (reference dist_mnist.py +
dist_mnist_batch_merge.py over test_dist_base.py): a REAL conv payload
across 2 pservers x 2 trainers with exact param parity vs full-batch
local, plus the batch-merge leg (GradientMergeOptimizer == one
k-times-larger batch) and an SE-ResNeXt block smoke (the reference's
dist_se_resnext model family)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from dist_utils import free_ports as _free_ports


def _parse_losses(stdout):
    return [float(l.split("loss:")[1]) for l in stdout.splitlines()
            if l.startswith("loss:")]


def _parse_params(stdout):
    out = {}
    for l in stdout.splitlines():
        if l.startswith("param:"):
            _, name, v = l.split(":")
            out[name] = float(v)
    return out


@pytest.mark.slow
@pytest.mark.flaky_ports
def test_dist_mnist_conv_matches_local():
    from dist_utils import run_ps_cluster

    here = os.path.dirname(os.path.abspath(__file__))
    payload = os.path.join(here, "dist_mnist_payload.py")
    base_env = dict(os.environ, JAX_PLATFORMS="cpu")
    base_env.pop("PADDLE_TRAINING_ROLE", None)

    local = subprocess.run([sys.executable, payload], env=base_env,
                           capture_output=True, text=True, timeout=300)
    assert local.returncode == 0, local.stderr
    local_params = _parse_params(local.stdout)
    assert set(local_params) == {"mn_c1", "mn_c2", "mn_fc"}

    touts = run_ps_cluster(payload, base_env)
    for out in touts:
        losses = _parse_losses(out)
        assert len(losses) == 5 and all(np.isfinite(losses))
        dist_params = _parse_params(out)
        for name in ("mn_c1", "mn_c2", "mn_fc"):
            np.testing.assert_allclose(dist_params[name],
                                       local_params[name], rtol=1e-3)


def test_gradient_merge_matches_large_batch():
    """dist_mnist_batch_merge analog: k merged microbatches == one
    k-times-larger batch, exactly (multi_batch_merge_pass semantics)."""

    def run(merge_k, feeds):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = 9
        startup.random_seed = 9
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[4])
            y = fluid.layers.data("y", shape=[1])
            h = fluid.layers.fc(x, 8, act="tanh",
                                param_attr=fluid.ParamAttr(name="bm_w1"))
            pred = fluid.layers.fc(
                h, 1, param_attr=fluid.ParamAttr(name="bm_w2"))
            loss = fluid.layers.mean(fluid.layers.square(pred - y))
            inner = fluid.optimizer.Momentum(0.1, 0.9)
            if merge_k > 1:
                fluid.optimizer.GradientMergeOptimizer(
                    inner, k_steps=merge_k, avg=True).minimize(loss)
            else:
                inner.minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            for xb, yb in feeds:
                exe.run(main, feed={"x": xb, "y": yb},
                        fetch_list=[loss])
            return {n: np.asarray(
                scope.find_var(n).get_tensor().numpy())
                for n in ("bm_w1", "bm_w2")}

    rng = np.random.RandomState(0)
    xs = rng.randn(16, 4).astype("f")
    ys = rng.randn(16, 1).astype("f")
    # 2 optimizer boundaries: 4 microbatches at k=2 vs 2 full batches
    merged = run(2, [(xs[:4], ys[:4]), (xs[4:8], ys[4:8]),
                     (xs[8:12], ys[8:12]), (xs[12:], ys[12:])])
    full = run(1, [(xs[:8], ys[:8]), (xs[8:], ys[8:])])
    for n in ("bm_w1", "bm_w2"):
        np.testing.assert_allclose(merged[n], full[n], rtol=1e-5,
                                   atol=1e-6)


def test_se_resnext_trains():
    """SE-ResNeXt block family (reference dist_se_resnext model): tiny
    train step produces finite decreasing-capable loss and the grouped
    conv + SE gate graph round-trips the executor."""
    from paddle_tpu.models import se_resnext

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 3
    startup.random_seed = 3
    with fluid.program_guard(main, startup):
        img, label, loss, acc = se_resnext.build_train(
            depth=50, class_dim=10, image_size=32, lr=0.05)
    types = [op.type for op in main.global_block().ops]
    assert any(op.type == "conv2d" and op.attrs.get("groups", 1) == 32
               for op in main.global_block().ops)  # grouped 3x3s
    assert "sigmoid" in types                       # SE gate
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(2):
            xb = rng.rand(4, 3, 32, 32).astype("f")
            yb = rng.randint(0, 10, (4, 1)).astype("int64")
            lo, = exe.run(main, feed={"img": xb, "label": yb},
                          fetch_list=[loss])
            losses.append(float(np.asarray(lo).ravel()[0]))
    assert all(np.isfinite(losses))


def test_gradient_merge_with_regularization_and_se_optimizer():
    """The review repro: wrapping an L2Decay Momentum (the SE-ResNeXt
    optimizer) in GradientMergeOptimizer must build and train — the decay
    ops land inside the boundary branch with their inputs."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 4
    startup.random_seed = 4
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.data("y", shape=[1])
        pred = fluid.layers.fc(fluid.layers.fc(x, 8, act="tanh"), 1)
        loss = fluid.layers.mean(fluid.layers.square(pred - y))
        inner = fluid.optimizer.Momentum(
            0.1, 0.9, regularization=fluid.regularizer.L2Decay(1e-4))
        fluid.optimizer.GradientMergeOptimizer(
            inner, k_steps=2).minimize(loss, grad_clip=None)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        losses = []
        for _ in range(4):
            lo, = exe.run(main,
                          feed={"x": rng.randn(8, 4).astype("f"),
                                "y": rng.randn(8, 1).astype("f")},
                          fetch_list=[loss])
            losses.append(float(np.asarray(lo).ravel()[0]))
    assert all(np.isfinite(losses))


@pytest.mark.slow
@pytest.mark.flaky_ports
def test_dist_se_resnext_matches_local():
    """dist_se_resnext analog: a grouped-conv + SE-gate block over the
    sync-PS runtime; trained params match the full-batch local run.
    BN running stats stay trainer-local (reference behavior)."""
    try:
        _run_dist_se_resnext()
    except (AssertionError, OSError):
        _run_dist_se_resnext()


def _run_dist_se_resnext():
    from dist_utils import run_ps_cluster

    here = os.path.dirname(os.path.abspath(__file__))
    payload = os.path.join(here, "dist_se_resnext_payload.py")
    base_env = dict(os.environ, JAX_PLATFORMS="cpu")
    base_env.pop("PADDLE_TRAINING_ROLE", None)

    local = subprocess.run([sys.executable, payload], env=base_env,
                           capture_output=True, text=True, timeout=300)
    assert local.returncode == 0, local.stderr
    local_params = _parse_params(local.stdout)
    assert local_params, "local run reported no params"
    local_losses = _parse_losses(local.stdout)
    assert len(local_losses) == 8  # 4 steps x 2 grad-merged halves

    touts = run_ps_cluster(payload, base_env)
    for out in touts:
        losses = _parse_losses(out)
        assert len(losses) == 4 and all(np.isfinite(losses))
        dist_params = _parse_params(out)
        for name, want in local_params.items():
            np.testing.assert_allclose(dist_params[name], want,
                                       rtol=2e-3, err_msg=name)
