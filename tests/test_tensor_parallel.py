"""Tensor-parallel numeric parity (VERDICT r3 item 2).

The TP path threads ("model",)-axis PartitionSpecs through BERT's qkv/ffn
weights (models/bert.py) and runs under jit+GSPMD over a (data, model) mesh.
These tests assert the sharded run matches the plain single-device run
numerically — loss, gradients' effect (updated params), and optimizer state
— over multiple steps, so a dropped psum / wrong-axis sharding cannot pass
silently.  Reference pattern: parallel_executor_test_base.py
(same-model two-config parity)."""

import numpy as np
import pytest

import jax
import paddle_tpu as fluid
from paddle_tpu.models import bert

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8 or jax.devices()[0].platform != "cpu",
    reason="needs the 8-device virtual CPU mesh")


CFG = dict(vocab_size=512, hidden=64, layers=2, heads=4, ffn=128, max_pos=32)


def _feeds(cfg, batch, seq, steps, n_mask=4):
    rng = np.random.RandomState(0)
    out = []
    for _ in range(steps):
        out.append({
            "src_ids": rng.randint(0, cfg.vocab_size,
                                   (batch, seq, 1)).astype("int64"),
            "pos_ids": np.tile(np.arange(seq).reshape(1, seq, 1),
                               (batch, 1, 1)).astype("int64"),
            "sent_ids": np.zeros((batch, seq, 1), "int64"),
            "input_mask": np.ones((batch, seq, 1), "float32"),
            "mask_pos": rng.randint(0, batch * seq, (n_mask,)).astype("int64"),
            "mask_label": rng.randint(0, cfg.vocab_size,
                                      (n_mask, 1)).astype("int64"),
        })
    return out


def _build(cfg, seq, use_tp, dropout):
    cfg = bert.BertConfig(dropout=dropout, **cfg)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 42
    startup.random_seed = 42
    with fluid.program_guard(main, startup):
        _, loss = bert.build_pretrain(cfg, seq_len=seq, lr=1e-3,
                                      use_tp=use_tp)
    return cfg, main, startup, loss


def _watched_params(main):
    """qkv (column-sharded), ffn2 (row-sharded: the psum-critical matmul),
    and a replicated non-TP param."""
    names = [n for n in (v.name for v in main.list_vars())
             if n.endswith("_q_w") or n.endswith("_ffn2_w")]
    names.append("word_emb")
    assert len(names) >= 3
    return sorted(names)


def _run(cfg, main, startup, loss, feeds, mesh=None):
    exe = fluid.Executor(fluid.CPUPlace())
    prog = main
    if mesh is not None:
        prog = fluid.CompiledProgram(main)._with_mesh(mesh, data_axis="data")
    losses, params, shardings = [], {}, {}
    watched = _watched_params(main)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for f in feeds:
            out, = exe.run(prog, feed=f, fetch_list=[loss])
            losses.append(float(np.asarray(out).reshape(-1)[0]))
        scope = fluid.global_scope()
        for n in watched:
            params[n] = np.asarray(scope.find_var(n).get_tensor().numpy())
        # grab one Adam accumulator for optimizer-state parity
        moments = [v.name for v in main.list_vars()
                   if "moment" in v.name and v.persistable]
        if moments:
            params[moments[0]] = np.asarray(
                scope.find_var(moments[0]).get_tensor().numpy())
        if mesh is not None:
            shardings = {n: scope.find_var(n).get_tensor().get()
                         for n in watched}
    return losses, params, shardings


def _mesh(dp, tp):
    from jax.sharding import Mesh

    devices = np.array(jax.devices()[:dp * tp]).reshape(dp, tp)
    return Mesh(devices, ("data", "model"))


@pytest.mark.parametrize("dropout", [0.0, 0.1])
def test_tp2_matches_single_device(dropout):
    """dp=1 x tp=2 vs plain single-device: pure tensor parallelism."""
    seq, batch, steps = 16, 4, 3
    cfg, main, startup, loss = _build(CFG, seq, use_tp=True, dropout=dropout)
    feeds = _feeds(cfg, batch, seq, steps)

    plain_losses, plain_params, _ = _run(cfg, main, startup, loss, feeds)
    tp_losses, tp_params, tp_arrays = _run(cfg, main, startup, loss, feeds,
                                           mesh=_mesh(1, 2))

    np.testing.assert_allclose(tp_losses, plain_losses, rtol=2e-5, atol=1e-6)
    for n in plain_params:
        np.testing.assert_allclose(
            tp_params[n], plain_params[n], rtol=2e-4, atol=1e-5,
            err_msg="param %s diverged under tp=2" % n)
    # the TP run must actually have sharded the annotated params over
    # "model" (2 distinct single-param shards), not silently replicated
    q_names = [n for n in tp_arrays if n.endswith("_q_w")]
    for n in q_names:
        arr = tp_arrays[n]
        assert isinstance(arr, jax.Array)
        shard_shapes = {s.data.shape for s in arr.addressable_shards}
        full = arr.shape
        assert shard_shapes == {(full[0], full[1] // 2)}, (
            "expected %s column-sharded 2-way, got shards %s"
            % (n, shard_shapes))


def test_dp4_tp2_dropout_stream_aligned():
    """The dropout mask stream IS aligned under the 4x2 mesh:
    jax_threefry_partitionable (enabled at package import) derives each
    shard's random block from global element offsets, so the sharded
    forward draws the same mask as the plain program.  Steps 0-1 of the
    dp4xtp2 trajectory match the single-device run to float tolerance —
    a regression in the stream (e.g. losing the partitionable flag)
    breaks step 0 immediately."""
    seq, batch, steps = 16, 8, 2
    cfg, main, startup, loss = _build(CFG, seq, use_tp=True, dropout=0.1)
    feeds = _feeds(cfg, batch, seq, steps)

    plain_losses, _, _ = _run(cfg, main, startup, loss, feeds)
    tp_losses, _, _ = _run(cfg, main, startup, loss, feeds,
                           mesh=_mesh(4, 2))
    np.testing.assert_allclose(tp_losses, plain_losses, rtol=2e-5,
                               atol=1e-6)


def test_dp4_tp2_matches_single_device():
    """The dryrun topology (dp=4 x tp=2) with dropout on: batch sharded over
    data, weights over model, still numerically the plain program.

    Without FLAGS_deterministic_reduction the 3-step trajectory drifts ~1%
    rel at step 2: GSPMD picks shard-shape-dependent kernels (Eigen gemm
    tiling, fused-adam FMA grouping) that reassociate f32 sums relative to
    the single-device program, and Adam's rsqrt amplifies the last-ulp
    deltas into a visible loss gap two steps later.  Deterministic mode
    pins every mesh-path operand to a replicated layout and skips the
    flat-buffer optimizer fusion, so both programs reduce in the same
    order — the trajectories below are bitwise identical, and the params
    still live sharded in the scope (checked on the q weights)."""
    seq, batch, steps = 16, 8, 3
    cfg, main, startup, loss = _build(CFG, seq, use_tp=True, dropout=0.1)
    feeds = _feeds(cfg, batch, seq, steps)

    fluid.set_flags({"FLAGS_deterministic_reduction": True})
    try:
        plain_losses, plain_params, _ = _run(cfg, main, startup, loss, feeds)
        tp_losses, tp_params, tp_arrays = _run(cfg, main, startup, loss,
                                               feeds, mesh=_mesh(4, 2))
    finally:
        fluid.set_flags({"FLAGS_deterministic_reduction": False})
    np.testing.assert_allclose(tp_losses, plain_losses, rtol=2e-5, atol=1e-6)
    for n in plain_params:
        np.testing.assert_allclose(
            tp_params[n], plain_params[n], rtol=2e-4, atol=1e-5,
            err_msg="param %s diverged under dp=4 tp=2" % n)
    # deterministic mode must not silently de-shard storage: the annotated
    # weights still live column-sharded over "model" in the scope
    for n in (n for n in tp_arrays if n.endswith("_q_w")):
        arr = tp_arrays[n]
        shard_shapes = {s.data.shape for s in arr.addressable_shards}
        assert shard_shapes == {(arr.shape[0], arr.shape[1] // 2)}, (
            n, shard_shapes)


def test_tp_sharding_specs_present():
    """grep-able guarantee: use_tp=True threads model-axis specs into the
    qkv/out/ffn weights and leaves everything else replicated."""
    cfg, main, startup, loss = _build(CFG, 16, use_tp=True, dropout=0.0)
    specs = {v.name: getattr(v, "sharding", None)
             for v in main.list_vars()}
    col = [n for n in specs if n.endswith(("_q_w", "_k_w", "_v_w",
                                           "_ffn1_w"))]
    row = [n for n in specs if n.endswith(("_out_w", "_ffn2_w"))]
    assert col and row
    for n in col:
        assert tuple(specs[n]) == (None, "model"), (n, specs[n])
    for n in row:
        assert tuple(specs[n]) == ("model", None), (n, specs[n])
    assert specs["word_emb"] is None
