"""Numeric golden tests for the coverage-tail op families the round-2
verdict flagged as registered-but-unverified: the fusion family (each
fusion_* checked against its unfused composition, the reference's own test
contract — test_fusion_gru_op.py etc.), cudnn_lstm, the quant tail vs
numpy quantizers (test_fake_quantize_op.py), detection metrics vs numpy
references (test_detection_map_op.py, test_multiclass_nms_op.py), the
sequence tail, the PS/LoD helpers, and assorted singletons
(average_accumulates, depthwise_conv2d_transpose, fill/size dtypes)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def run_op(op_type, inputs, attrs, out_slots):
    """Run one op.  `inputs`: slot -> array | list[(name, arr)].
    `out_slots`: slot -> 1 (single) | N (duplicable, N outputs).
    Returns a dict slot -> array | [arrays]."""
    from paddle_tpu.framework import convert_np_dtype_to_dtype_

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        block = main.global_block()
        in_map, feed = {}, {}
        for slot, val in inputs.items():
            entries = val if isinstance(val, list) else [
                ("in_" + slot, val)]
            names = []
            for nm, arr in entries:
                block.create_var(
                    name=nm, shape=arr.shape,
                    dtype=convert_np_dtype_to_dtype_(arr.dtype))
                feed[nm] = arr
                names.append(nm)
            in_map[slot] = names
        out_map, fetch = {}, []
        for slot, n in out_slots.items():
            names = ["out_%s_%d" % (slot, i) for i in range(n)]
            for nm in names:
                block.create_var(name=nm)
            out_map[slot] = names
            fetch.extend(names)
        block.append_op(type=op_type, inputs=in_map, outputs=out_map,
                        attrs=attrs)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        res = exe.run(main, feed=feed, fetch_list=fetch)
    res = [np.asarray(r) for r in res]
    out, i = {}, 0
    for slot, n in out_slots.items():
        out[slot] = res[i] if n == 1 else res[i:i + n]
        i += n
    return out


def _sigmoid(v):
    return 1.0 / (1.0 + np.exp(-v))


def _np_lstm(proj, wh, h0=None, c0=None, reverse=False):
    """proj [B,T,4D] pre-activations; gate order i,f,cand,o."""
    B, T, D4 = proj.shape
    D = D4 // 4
    h = np.zeros((B, D), proj.dtype) if h0 is None else h0.copy()
    c = np.zeros((B, D), proj.dtype) if c0 is None else c0.copy()
    hs = np.zeros((B, T, D), proj.dtype)
    cs = np.zeros((B, T, D), proj.dtype)
    ts = range(T - 1, -1, -1) if reverse else range(T)
    for t in ts:
        g = proj[:, t] + h @ wh
        i, f = _sigmoid(g[:, :D]), _sigmoid(g[:, D:2 * D])
        cand = np.tanh(g[:, 2 * D:3 * D])
        o = _sigmoid(g[:, 3 * D:])
        c = f * c + i * cand
        h = o * np.tanh(c)
        hs[:, t], cs[:, t] = h, c
    return hs, cs


def _np_gru(proj, wh, h0=None, origin_mode=False, reverse=False):
    B, T, D3 = proj.shape
    D = D3 // 3
    h = np.zeros((B, D), proj.dtype) if h0 is None else h0.copy()
    hs = np.zeros((B, T, D), proj.dtype)
    ts = range(T - 1, -1, -1) if reverse else range(T)
    for t in ts:
        ur = proj[:, t, :2 * D] + h @ wh[:, :2 * D]
        u, r = _sigmoid(ur[:, :D]), _sigmoid(ur[:, D:])
        c = np.tanh(proj[:, t, 2 * D:] + (r * h) @ wh[:, 2 * D:])
        h = ((1 - u) * h + u * c) if origin_mode else (u * h + (1 - u) * c)
        hs[:, t] = h
    return hs


# -- fused RNN family --------------------------------------------------------


class TestFusionRNNFamily:
    def test_fusion_gru_vs_numpy(self):
        rng = np.random.RandomState(0)
        B, T, F, D = 2, 5, 6, 4
        x = rng.uniform(-1, 1, (B, T, F)).astype("f")
        wx = rng.uniform(-0.5, 0.5, (F, 3 * D)).astype("f")
        wh = rng.uniform(-0.5, 0.5, (D, 3 * D)).astype("f")
        b = rng.uniform(-0.2, 0.2, (1, 3 * D)).astype("f")
        out = run_op("fusion_gru",
                     {"X": x, "WeightX": wx, "WeightH": wh, "Bias": b},
                     {}, {"Hidden": 1})
        want = _np_gru(x @ wx + b.reshape(1, 1, -1), wh)
        np.testing.assert_allclose(out["Hidden"], want, rtol=1e-5,
                                   atol=1e-6)

    def test_fusion_gru_reverse_origin_mode(self):
        rng = np.random.RandomState(1)
        B, T, F, D = 2, 4, 3, 3
        x = rng.uniform(-1, 1, (B, T, F)).astype("f")
        wx = rng.uniform(-0.5, 0.5, (F, 3 * D)).astype("f")
        wh = rng.uniform(-0.5, 0.5, (D, 3 * D)).astype("f")
        out = run_op("fusion_gru", {"X": x, "WeightX": wx, "WeightH": wh},
                     {"is_reverse": True, "origin_mode": True},
                     {"Hidden": 1})
        want = _np_gru(x @ wx, wh, origin_mode=True, reverse=True)
        np.testing.assert_allclose(out["Hidden"], want, rtol=1e-5,
                                   atol=1e-6)

    def test_fusion_lstm_vs_numpy(self):
        rng = np.random.RandomState(2)
        B, T, F, D = 2, 5, 6, 4
        x = rng.uniform(-1, 1, (B, T, F)).astype("f")
        wx = rng.uniform(-0.5, 0.5, (F, 4 * D)).astype("f")
        wh = rng.uniform(-0.5, 0.5, (D, 4 * D)).astype("f")
        b = rng.uniform(-0.2, 0.2, (1, 4 * D)).astype("f")
        h0 = rng.uniform(-0.5, 0.5, (B, D)).astype("f")
        c0 = rng.uniform(-0.5, 0.5, (B, D)).astype("f")
        out = run_op("fusion_lstm",
                     {"X": x, "WeightX": wx, "WeightH": wh, "Bias": b,
                      "H0": h0, "C0": c0}, {}, {"Hidden": 1, "Cell": 1})
        want_h, want_c = _np_lstm(x @ wx + b.reshape(1, 1, -1), wh, h0, c0)
        np.testing.assert_allclose(out["Hidden"], want_h, rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(out["Cell"], want_c, rtol=1e-5,
                                   atol=1e-6)

    def test_fusion_lstm_equals_unfused_composition(self):
        """fusion_lstm == mul + lstm (reference test_fusion_lstm_op.py
        contract: fused output equals the composed ops)."""
        rng = np.random.RandomState(3)
        B, T, F, D = 2, 4, 5, 3
        x = rng.uniform(-1, 1, (B, T, F)).astype("f")
        wx = rng.uniform(-0.5, 0.5, (F, 4 * D)).astype("f")
        wh = rng.uniform(-0.5, 0.5, (D, 4 * D)).astype("f")
        fused = run_op("fusion_lstm",
                       {"X": x, "WeightX": wx, "WeightH": wh}, {},
                       {"Hidden": 1})
        proj = (x.reshape(-1, F) @ wx).reshape(B, T, 4 * D)
        unfused = run_op("lstm", {"Input": proj, "Weight": wh},
                         {"use_peepholes": False}, {"Hidden": 1})
        np.testing.assert_allclose(fused["Hidden"], unfused["Hidden"],
                                   rtol=1e-5, atol=1e-6)

    def test_fused_embedding_fc_lstm(self):
        rng = np.random.RandomState(4)
        B, T, V, D = 2, 4, 11, 3
        ids = rng.randint(0, V, (B, T)).astype("i8")
        emb = rng.uniform(-0.5, 0.5, (V, 4 * D)).astype("f")
        wh = rng.uniform(-0.5, 0.5, (D, 4 * D)).astype("f")
        b = rng.uniform(-0.2, 0.2, (1, 4 * D)).astype("f")
        out = run_op("fused_embedding_fc_lstm",
                     {"Ids": ids, "Embeddings": emb, "WeightH": wh,
                      "Bias": b}, {}, {"Hidden": 1, "Cell": 1})
        proj = emb[ids] + b.reshape(1, 1, -1)
        want_h, want_c = _np_lstm(proj.astype("f"), wh)
        np.testing.assert_allclose(out["Hidden"], want_h, rtol=1e-5,
                                   atol=1e-6)

    def test_cudnn_lstm_packed_blob(self):
        """2-layer unidirectional stacked LSTM over the cuDNN flat weight
        layout [Wx | Wh | b_x | b_h] per layer (cudnn_lstm_op.cu)."""
        rng = np.random.RandomState(5)
        B, T, F, D = 2, 4, 5, 3
        x = rng.uniform(-1, 1, (B, T, F)).astype("f")
        blob, params = [], []
        fin = F
        for _layer in range(2):
            wx = rng.uniform(-0.5, 0.5, (fin, 4 * D)).astype("f")
            wh = rng.uniform(-0.5, 0.5, (D, 4 * D)).astype("f")
            bx = rng.uniform(-0.2, 0.2, (4 * D,)).astype("f")
            bh = rng.uniform(-0.2, 0.2, (4 * D,)).astype("f")
            blob += [wx.ravel(), wh.ravel(), bx, bh]
            params.append((wx, wh, bx + bh))
            fin = D
        w = np.concatenate(blob)
        out = run_op("cudnn_lstm", {"Input": x, "W": w},
                     {"hidden_size": D, "num_layers": 2},
                     {"Out": 1, "last_h": 1, "last_c": 1})
        cur = x
        for wx, wh, b in params:
            proj = cur @ wx + b.reshape(1, 1, -1)
            cur, cs = _np_lstm(proj.astype("f"), wh)
        np.testing.assert_allclose(out["Out"], cur, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(out["last_h"][-1], cur[:, -1],
                                   rtol=1e-5, atol=1e-5)

    def test_cudnn_lstm_bidirectional(self):
        rng = np.random.RandomState(6)
        B, T, F, D = 2, 3, 4, 2
        x = rng.uniform(-1, 1, (B, T, F)).astype("f")
        blob, params = [], []
        for _d in range(2):
            wx = rng.uniform(-0.5, 0.5, (F, 4 * D)).astype("f")
            wh = rng.uniform(-0.5, 0.5, (D, 4 * D)).astype("f")
            bx = rng.uniform(-0.2, 0.2, (4 * D,)).astype("f")
            bh = rng.uniform(-0.2, 0.2, (4 * D,)).astype("f")
            blob += [wx.ravel(), wh.ravel(), bx, bh]
            params.append((wx, wh, bx + bh))
        out = run_op("cudnn_lstm",
                     {"Input": x, "W": np.concatenate(blob)},
                     {"hidden_size": D, "num_layers": 1,
                      "is_bidirec": True}, {"Out": 1})
        fwd, _ = _np_lstm((x @ params[0][0]
                           + params[0][2].reshape(1, 1, -1)).astype("f"),
                          params[0][1])
        bwd, _ = _np_lstm((x @ params[1][0]
                           + params[1][2].reshape(1, 1, -1)).astype("f"),
                          params[1][1], reverse=True)
        want = np.concatenate([fwd, bwd], axis=-1)
        np.testing.assert_allclose(out["Out"], want, rtol=1e-5, atol=1e-5)


# -- fusion (non-RNN) family -------------------------------------------------


class TestFusionOps:
    def test_fusion_seqconv_eltadd_relu_vs_composition(self):
        rng = np.random.RandomState(7)
        B, T, D, M, ctx_len = 2, 6, 4, 5, 3
        x = rng.uniform(-1, 1, (B, T, D)).astype("f")
        filt = rng.uniform(-0.5, 0.5, (ctx_len * D, M)).astype("f")
        bias = rng.uniform(-0.2, 0.2, (M,)).astype("f")
        fused = run_op("fusion_seqconv_eltadd_relu",
                       {"X": x, "Filter": filt, "Bias": bias},
                       {"contextLength": ctx_len, "contextStart": -1},
                       {"Out": 1})
        seqconv = run_op("sequence_conv", {"X": x, "Filter": filt},
                         {"contextLength": ctx_len, "contextStart": -1},
                         {"Out": 1})
        want = np.maximum(seqconv["Out"] + bias.reshape(1, 1, -1), 0.0)
        np.testing.assert_allclose(fused["Out"], want, rtol=1e-5,
                                   atol=1e-6)

    def test_fusion_seqexpand_concat_fc(self):
        rng = np.random.RandomState(8)
        B, T, D0, D1, M = 2, 4, 3, 2, 5
        seq = rng.uniform(-1, 1, (B, T, D0)).astype("f")
        side = rng.uniform(-1, 1, (B, D1)).astype("f")
        w = rng.uniform(-0.5, 0.5, (D0 + D1, M)).astype("f")
        b = rng.uniform(-0.2, 0.2, (M,)).astype("f")
        out = run_op("fusion_seqexpand_concat_fc",
                     {"X": [("seq", seq), ("side", side)],
                      "FCWeight": w, "FCBias": b},
                     {"fc_activation": "relu"}, {"Out": 1})
        expanded = np.broadcast_to(side[:, None], (B, T, D1))
        cat = np.concatenate([seq, expanded], axis=-1)
        want = np.maximum(cat @ w + b.reshape(1, 1, -1), 0.0)
        np.testing.assert_allclose(out["Out"], want, rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("pooltype", ["SUM", "AVERAGE", "SQRT"])
    def test_fusion_seqpool_concat(self, pooltype):
        rng = np.random.RandomState(9)
        B, T = 3, 5
        xs = [rng.uniform(-1, 1, (B, T, d)).astype("f") for d in (2, 4)]
        out = run_op("fusion_seqpool_concat",
                     {"X": [("x0", xs[0]), ("x1", xs[1])]},
                     {"pooltype": pooltype}, {"Out": 1})
        pools = []
        for x in xs:
            if pooltype == "SUM":
                pools.append(x.sum(1))
            elif pooltype == "AVERAGE":
                pools.append(x.mean(1))
            else:
                pools.append(x.sum(1) / np.sqrt(T))
        want = np.concatenate(pools, axis=-1)
        np.testing.assert_allclose(out["Out"], want, rtol=1e-5, atol=1e-6)

    def test_fusion_seqpool_cvm_concat(self):
        rng = np.random.RandomState(10)
        B, T, D = 2, 4, 5
        xs = [np.abs(rng.uniform(0, 2, (B, T, D))).astype("f")
              for _ in range(2)]
        cvm_in = np.ones((B, 2), "f")
        out = run_op("fusion_seqpool_cvm_concat",
                     {"X": [("x0", xs[0]), ("x1", xs[1])], "CVM": cvm_in},
                     {"pooltype": "SUM", "use_cvm": True}, {"Out": 1})
        pools = []
        for x in xs:
            v = x.sum(1)
            c0 = np.log(v[:, :1] + 1)
            c1 = np.log(v[:, 1:2] + 1) - c0
            pools.append(np.concatenate([c0, c1, v[:, 2:]], axis=1))
        want = np.concatenate(pools, axis=-1)
        np.testing.assert_allclose(out["Out"], want, rtol=1e-5, atol=1e-6)

    def test_fusion_transpose_flatten_concat(self):
        rng = np.random.RandomState(11)
        xs = [rng.uniform(-1, 1, (2, 3, 4)).astype("f") for _ in range(2)]
        out = run_op("fusion_transpose_flatten_concat",
                     {"X": [("x0", xs[0]), ("x1", xs[1])]},
                     {"trans_axis": [0, 2, 1], "flatten_axis": 1,
                      "concat_axis": 1}, {"Out": 1})
        flat = [np.transpose(x, (0, 2, 1)).reshape(2, -1) for x in xs]
        np.testing.assert_allclose(out["Out"],
                                   np.concatenate(flat, axis=1),
                                   rtol=1e-5, atol=1e-6)

    def test_conv2d_fusion_vs_composition(self):
        rng = np.random.RandomState(12)
        x = rng.uniform(-1, 1, (2, 3, 8, 8)).astype("f")
        w = rng.uniform(-0.5, 0.5, (4, 3, 3, 3)).astype("f")
        bias = rng.uniform(-0.2, 0.2, (4,)).astype("f")
        conv = run_op("conv2d", {"Input": x, "Filter": w},
                      {"strides": [1, 1], "paddings": [1, 1]},
                      {"Output": 1})["Output"]
        residual = rng.uniform(-1, 1, conv.shape).astype("f")
        fused = run_op("conv2d_fusion",
                       {"Input": x, "Filter": w, "Bias": bias,
                        "ResidualData": residual},
                       {"strides": [1, 1], "paddings": [1, 1],
                        "activation": "relu"}, {"Output": 1})
        want = np.maximum(conv + bias.reshape(1, -1, 1, 1) + residual, 0.0)
        np.testing.assert_allclose(fused["Output"], want, rtol=1e-5,
                                   atol=1e-5)

    def test_conv2d_inception_fusion_vs_composition(self):
        rng = np.random.RandomState(13)
        C = 3
        x = rng.uniform(-1, 1, (2, C, 6, 6)).astype("f")
        ws = [rng.uniform(-0.5, 0.5, (2, C, k, k)).astype("f")
              for k in (1, 3)]
        bs = [rng.uniform(-0.2, 0.2, (2,)).astype("f") for _ in range(2)]
        fused = run_op(
            "conv2d_inception_fusion",
            {"Input": x, "Filter": [("w0", ws[0]), ("w1", ws[1])],
             "Bias": [("b0", bs[0]), ("b1", bs[1])]},
            {"pooling_type": "max", "activation": "relu"},
            {"Output": 1, "TempOutput": 2})
        branches = []
        for w, b in zip(ws, bs):
            k = w.shape[2]
            o = run_op("conv2d", {"Input": x, "Filter": w},
                       {"strides": [1, 1], "paddings": [k // 2, k // 2]},
                       {"Output": 1})["Output"]
            branches.append(np.maximum(o + b.reshape(1, -1, 1, 1), 0.0))
        pool = run_op("pool2d", {"X": x},
                      {"pooling_type": "max", "ksize": [3, 3],
                       "strides": [1, 1], "paddings": [1, 1]},
                      {"Out": 1})["Out"]
        want = np.concatenate(branches + [pool], axis=1)
        np.testing.assert_allclose(fused["Output"], want, rtol=1e-5,
                                   atol=1e-5)

    def test_fused_elemwise_activation(self):
        rng = np.random.RandomState(14)
        x = rng.uniform(-1, 1, (3, 4)).astype("f")
        y = rng.uniform(-1, 1, (3, 4)).astype("f")
        out = run_op("fused_elemwise_activation", {"X": x, "Y": y},
                     {"functor_list": ["relu", "elementwise_add"]},
                     {"Out": 1, "IntermediateOut": 1})
        np.testing.assert_allclose(out["Out"], np.maximum(x + y, 0.0),
                                   rtol=1e-5)
        np.testing.assert_allclose(out["IntermediateOut"], x + y,
                                   rtol=1e-5)
        out2 = run_op("fused_elemwise_activation", {"X": x, "Y": y},
                      {"functor_list": ["elementwise_add", "scale"],
                       "scale": 2.0}, {"Out": 1})
        np.testing.assert_allclose(out2["Out"], x + 2.0 * y, rtol=1e-5)

    def test_fusion_repeated_fc_relu_all_layers_relu(self):
        """The fused kernel applies fc+bias+relu to every layer including
        the last (fusion_repeated_fc_relu_op.cc:118-139)."""
        rng = np.random.RandomState(15)
        x = rng.uniform(-1, 1, (3, 4)).astype("f")
        w1 = rng.uniform(-0.5, 0.5, (4, 5)).astype("f")
        b1 = rng.uniform(-0.2, 0.2, (5,)).astype("f")
        w2 = rng.uniform(-0.5, 0.5, (5, 2)).astype("f")
        b2 = rng.uniform(-0.2, 0.2, (2,)).astype("f")
        out = run_op("fusion_repeated_fc_relu",
                     {"X": x, "W": [("w1", w1), ("w2", w2)],
                      "Bias": [("b1", b1), ("b2", b2)]}, {},
                     {"ReluOut": 1, "Out": 1})
        h1 = np.maximum(x @ w1 + b1, 0.0)
        want = np.maximum(h1 @ w2 + b2, 0.0)
        np.testing.assert_allclose(out["Out"], want, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(out["ReluOut"], h1, rtol=1e-5,
                                   atol=1e-6)


# -- quantization tail -------------------------------------------------------


def _np_quant_dequant(x, scale, bits=8):
    bnt = (1 << (bits - 1)) - 1
    s = max(float(scale), 1e-8)
    return np.round(np.clip(x / s, -1.0, 1.0) * bnt) * s / bnt


class TestQuantTail:
    def test_fake_quantize_range_abs_max_train_window(self):
        rng = np.random.RandomState(16)
        x = rng.uniform(-2, 2, (4, 5)).astype("f")
        window = 4
        in_scale = np.asarray([0.5], "f")
        in_scales = np.asarray([0.5, 3.0, 0.1, 0.2], "f")
        it = np.asarray([5], "i8")  # slot 5 % 4 == 1 -> overwrites the 3.0
        out = run_op("fake_quantize_range_abs_max",
                     {"X": x, "InScale": in_scale, "InScales": in_scales,
                      "Iter": it},
                     {"window_size": window, "bit_length": 8},
                     {"Out": 1, "OutScale": 1, "OutScales": 1})
        cur = np.abs(x).max()
        hist = in_scales.copy()
        hist[1] = cur
        scale = hist.max()
        np.testing.assert_allclose(out["OutScale"], [scale], rtol=1e-6)
        np.testing.assert_allclose(out["OutScales"], hist, rtol=1e-6)
        np.testing.assert_allclose(out["Out"],
                                   _np_quant_dequant(x, scale), rtol=1e-5,
                                   atol=1e-6)

    def test_fake_quantize_range_abs_max_test_mode(self):
        rng = np.random.RandomState(17)
        x = rng.uniform(-2, 2, (3, 3)).astype("f")
        in_scale = np.asarray([1.5], "f")
        out = run_op("fake_quantize_range_abs_max",
                     {"X": x, "InScale": in_scale},
                     {"is_test": True, "bit_length": 8},
                     {"Out": 1, "OutScale": 1})
        np.testing.assert_allclose(out["Out"], _np_quant_dequant(x, 1.5),
                                   rtol=1e-5, atol=1e-6)

    def test_fake_quantize_dequantize_moving_average(self):
        rng = np.random.RandomState(18)
        x = rng.uniform(-2, 2, (4, 4)).astype("f")
        in_scale = np.asarray([0.7], "f")
        in_accum = np.asarray([1.2], "f")
        in_state = np.asarray([2.0], "f")
        out = run_op("fake_quantize_dequantize_moving_average_abs_max",
                     {"X": x, "InScale": in_scale, "InAccum": in_accum,
                      "InState": in_state}, {"moving_rate": 0.9},
                     {"Out": 1, "OutScale": 1, "OutAccum": 1,
                      "OutState": 1})
        cur = np.abs(x).max()
        state = 0.9 * 2.0 + 1.0
        accum = 0.9 * 1.2 + cur
        scale = accum / state
        np.testing.assert_allclose(out["OutState"], [state], rtol=1e-6)
        np.testing.assert_allclose(out["OutAccum"], [accum], rtol=1e-6)
        np.testing.assert_allclose(out["OutScale"], [scale], rtol=1e-6)
        np.testing.assert_allclose(out["Out"],
                                   _np_quant_dequant(x, scale), rtol=1e-5,
                                   atol=1e-6)

    def test_fake_channel_wise_dequantize_max_abs(self):
        rng = np.random.RandomState(19)
        x = rng.randint(-127, 128, (3, 4)).astype("f")
        scales = np.asarray([0.5, 1.0, 2.0], "f")
        out = run_op("fake_channel_wise_dequantize_max_abs",
                     {"X": x, "Scales": [("s0", scales)]},
                     {"quant_bits": [8], "quant_axis": 0}, {"Out": 1})
        want = x * scales.reshape(3, 1) / 127.0
        np.testing.assert_allclose(out["Out"], want, rtol=1e-6)

    def test_fake_channel_wise_dequantize_two_scales(self):
        rng = np.random.RandomState(20)
        x = rng.randint(-127, 128, (2, 3)).astype("f")
        s0 = np.asarray([0.5, 2.0], "f")
        s1 = np.asarray([3.0], "f")
        out = run_op("fake_channel_wise_dequantize_max_abs",
                     {"X": x, "Scales": [("s0", s0), ("s1", s1)]},
                     {"quant_bits": [8, 8], "quant_axis": 0}, {"Out": 1})
        want = x * s0.reshape(2, 1) / 127.0 * 3.0 / 127.0
        np.testing.assert_allclose(out["Out"], want, rtol=1e-6)

    def test_requantize(self):
        x = np.asarray([[-100, 0, 50], [127, -128, 10]], np.int8)
        out = run_op("requantize", {"Input": x},
                     {"Scale_in": 2.0, "Scale_out": 4.0}, {"Output": 1})
        want = np.clip(np.round(x.astype("f") * 2.0), -128, 127)
        np.testing.assert_array_equal(out["Output"],
                                      want.astype(np.int8))


# -- detection metrics -------------------------------------------------------


class TestDetectionMetrics:
    def test_mine_hard_examples(self):
        """SSD hard-negative mining vs a numpy replica: top
        neg_pos_ratio*num_pos negatives by loss per row."""
        cls_loss = np.asarray([[0.1, 0.9, 0.5, 0.3, 0.8],
                               [0.2, 0.1, 0.7, 0.4, 0.6]], "f")
        match = np.asarray([[0, -1, -1, -1, -1],
                            [1, 2, -1, -1, -1]], np.int32)
        dist = np.zeros_like(cls_loss)
        out = run_op("mine_hard_examples",
                     {"ClsLoss": cls_loss, "MatchIndices": match,
                      "MatchDist": dist},
                     {"neg_pos_ratio": 2.0},
                     {"NegIndices": 1, "UpdatedMatchIndices": 1})
        # row 0: 1 pos -> 2 negs: the two highest-loss negatives (idx 1, 4)
        np.testing.assert_array_equal(out["NegIndices"][0],
                                      [0, 1, 0, 0, 1])
        # row 1: 2 pos -> up to 4 negs: all 3 negatives selected
        np.testing.assert_array_equal(out["NegIndices"][1],
                                      [0, 0, 1, 1, 1])
        np.testing.assert_array_equal(out["UpdatedMatchIndices"], match)

    def _np_map(self, det, label, class_num, thresh=0.5,
                ap_type="integral"):
        """Greedy per-class mAP reference (detection_map_op.h semantics,
        5-col labels, background 0)."""
        aps = []
        for c in range(1, class_num):
            gt_idx = [i for i in range(len(label)) if label[i, 0] == c]
            order = np.argsort(-det[:, 1])
            used = set()
            tps, fps = [], []
            for d in order:
                if det[d, 0] != c:
                    continue
                best, bj = 0.0, -1
                for j in gt_idx:
                    if j in used:
                        continue
                    a, b = det[d, 2:6], label[j, 1:5]
                    ix = max(0, min(a[2], b[2]) - max(a[0], b[0]))
                    iy = max(0, min(a[3], b[3]) - max(a[1], b[1]))
                    inter = ix * iy
                    u = ((a[2] - a[0]) * (a[3] - a[1])
                         + (b[2] - b[0]) * (b[3] - b[1]) - inter)
                    v = inter / max(u, 1e-10)
                    if v > best:
                        best, bj = v, j
                if best >= thresh:
                    used.add(bj)
                    tps.append(1.0)
                    fps.append(0.0)
                else:
                    tps.append(0.0)
                    fps.append(1.0)
            npos = len(gt_idx)
            if npos == 0:
                continue
            ctp = np.cumsum(tps) if tps else np.zeros(1)
            cfp = np.cumsum(fps) if fps else np.zeros(1)
            recall = ctp / npos
            prec = ctp / np.maximum(ctp + cfp, 1e-10)
            prev = np.concatenate([[0.0], recall[:-1]])
            aps.append(np.sum((recall - prev) * prec))
        return np.mean(aps) if aps else 0.0

    def test_detection_map_perfect(self):
        det = np.asarray([[1, 0.9, 0.1, 0.1, 0.4, 0.4],
                          [2, 0.8, 0.5, 0.5, 0.9, 0.9]], "f")
        label = np.asarray([[1, 0.1, 0.1, 0.4, 0.4],
                            [2, 0.5, 0.5, 0.9, 0.9]], "f")
        out = run_op("detection_map", {"DetectRes": det, "Label": label},
                     {"class_num": 3}, {"MAP": 1})
        np.testing.assert_allclose(out["MAP"], [1.0], atol=1e-6)

    def test_detection_map_greedy_dedup(self):
        """Two detections on one gt: only the higher-scoring one is TP
        (greedy per-gt dedup, unlike independent matching)."""
        det = np.asarray([[1, 0.9, 0.1, 0.1, 0.4, 0.4],
                          [1, 0.8, 0.12, 0.1, 0.42, 0.4],
                          [1, 0.7, 0.5, 0.5, 0.9, 0.9]], "f")
        label = np.asarray([[1, 0.1, 0.1, 0.4, 0.4],
                            [1, 0.5, 0.5, 0.9, 0.9]], "f")
        out = run_op("detection_map", {"DetectRes": det, "Label": label},
                     {"class_num": 2}, {"MAP": 1})
        want = self._np_map(det, label, 2)
        np.testing.assert_allclose(out["MAP"], [want], rtol=1e-5)

    def test_detection_map_multiclass_vs_numpy(self):
        rng = np.random.RandomState(21)
        n_det, n_gt, n_cls = 12, 6, 4
        boxes = rng.uniform(0, 1, (n_det, 2, 2))
        det = np.zeros((n_det, 6), "f")
        det[:, 0] = rng.randint(1, n_cls, n_det)
        det[:, 1] = rng.uniform(0.1, 1.0, n_det)
        det[:, 2:4] = boxes.min(1)
        det[:, 4:6] = boxes.min(1) + rng.uniform(0.1, 0.5, (n_det, 2))
        gb = rng.uniform(0, 1, (n_gt, 2, 2))
        label = np.zeros((n_gt, 5), "f")
        label[:, 0] = rng.randint(1, n_cls, n_gt)
        label[:, 1:3] = gb.min(1)
        label[:, 3:5] = gb.min(1) + rng.uniform(0.1, 0.5, (n_gt, 2))
        # overlap some detections exactly with gts so TPs exist
        det[:n_gt, 2:6] = label[:, 1:5]
        det[:n_gt, 0] = label[:, 0]
        out = run_op("detection_map", {"DetectRes": det, "Label": label},
                     {"class_num": n_cls}, {"MAP": 1})
        want = self._np_map(det, label, n_cls)
        np.testing.assert_allclose(out["MAP"], [want], rtol=1e-5)

    def test_multiclass_nms2_suppression(self):
        # 3 boxes: two heavily overlapping (one suppressed), one distinct
        bboxes = np.asarray([[[0.1, 0.1, 0.4, 0.4],
                              [0.11, 0.1, 0.41, 0.4],
                              [0.6, 0.6, 0.9, 0.9]]], "f")
        scores = np.asarray([[[0.0, 0.0, 0.0],
                              [0.9, 0.8, 0.7]]], "f")  # class 1 scores
        out = run_op("multiclass_nms2",
                     {"BBoxes": bboxes, "Scores": scores},
                     {"background_label": 0, "score_threshold": 0.1,
                      "nms_threshold": 0.5, "keep_top_k": 8,
                      "nms_top_k": 8}, {"Out": 1, "Index": 1})
        res = np.asarray(out["Out"]).reshape(-1, 6)
        kept = res[res[:, 0] >= 0]  # drop class=-1 padding rows
        assert kept.shape[0] == 2
        # the two kept boxes are the 0.9 and the 0.7 ones
        np.testing.assert_allclose(sorted(kept[:, 1], reverse=True),
                                   [0.9, 0.7], atol=1e-6)
        assert out["Index"].shape[-1] == 1


# -- sequence tail -----------------------------------------------------------


class TestSequenceTail:
    def test_sequence_reshape(self):
        rng = np.random.RandomState(22)
        x = rng.uniform(-1, 1, (2, 4, 6)).astype("f")
        out = run_op("sequence_reshape", {"X": x}, {"new_dim": 8},
                     {"Out": 1})
        np.testing.assert_allclose(out["Out"], x.reshape(2, 3, 8))

    def test_sequence_scatter(self):
        x = np.zeros((2, 6), "f")
        ids = np.asarray([[1, 3, 1], [0, 5, 2]], np.int32)
        upd = np.asarray([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]], "f")
        out = run_op("sequence_scatter",
                     {"X": x, "Ids": ids, "Updates": upd}, {}, {"Out": 1})
        want = np.zeros((2, 6), "f")
        for b in range(2):
            for t in range(3):
                want[b, ids[b, t]] += upd[b, t]
        np.testing.assert_allclose(out["Out"], want)

    def test_sequence_topk_avg_pooling(self):
        rng = np.random.RandomState(23)
        B, C, L = 2, 3, 7
        x = rng.uniform(-1, 1, (B, C, L)).astype("f")
        out = run_op("sequence_topk_avg_pooling", {"X": x},
                     {"topks": [1, 3], "channel_num": C}, {"Out": 1})
        srt = np.sort(x.reshape(B, C, L), axis=-1)[..., ::-1]
        want = np.stack([srt[..., :1].mean(-1), srt[..., :3].mean(-1)],
                        axis=-1).reshape(B, -1)
        np.testing.assert_allclose(out["Out"], want, rtol=1e-5, atol=1e-6)

    def test_match_matrix_tensor(self):
        rng = np.random.RandomState(24)
        B, Tx, Ty, D1, D2, dim_t = 2, 3, 4, 5, 6, 2
        x = rng.uniform(-1, 1, (B, Tx, D1)).astype("f")
        y = rng.uniform(-1, 1, (B, Ty, D2)).astype("f")
        w = rng.uniform(-0.5, 0.5, (D1, dim_t, D2)).astype("f")
        out = run_op("match_matrix_tensor",
                     {"X": x, "Y": y, "W": w.reshape(D1, -1)},
                     {"dim_t": dim_t}, {"Out": 1, "Tmp": 1})
        want = np.einsum("bid,dte,bje->btij", x, w, y).reshape(B, -1)
        np.testing.assert_allclose(out["Out"], want, rtol=1e-4, atol=1e-5)

    def test_merge_lod_tensor_infer(self):
        """merge_lod_tensor_infer: inference variant of the IfElse merge —
        rows routed by mask (split_lod_tensor_op.cc counterpart)."""
        rng = np.random.RandomState(30)
        t = rng.uniform(-1, 1, (4, 3)).astype("f")
        f = rng.uniform(-1, 1, (4, 3)).astype("f")
        mask = np.asarray([[1], [0], [1], [0]], np.int32)
        out = run_op("merge_lod_tensor_infer",
                     {"Mask": mask, "InTrue": t, "InFalse": f},
                     {"level": 0}, {"Out": 1})["Out"]
        want = np.where(mask.astype(bool), t, f)
        np.testing.assert_allclose(out, want)

    def test_lod_reset_passthrough_and_max_sequence_len(self):
        rng = np.random.RandomState(25)
        x = rng.uniform(-1, 1, (3, 4)).astype("f")
        out = run_op("lod_reset", {"X": x}, {"target_lod": [0, 2, 3]},
                     {"Out": 1})
        np.testing.assert_allclose(out["Out"], x)
        lens = np.asarray([2, 5, 3], np.int64)
        xs = np.zeros((3, 6, 2), "f")
        table = run_op("lod_rank_table", {"X": xs, "Length": lens}, {},
                       {"Out": 1})["Out"]
        msl = run_op("max_sequence_len", {"RankTable": table}, {},
                     {"Out": 1})["Out"]
        assert int(msl) == 5


# -- PS / selected-rows helpers ----------------------------------------------


class TestPSHelpers:
    def test_split_ids_merge_ids_roundtrip(self):
        ids = np.asarray([3, 7, 2, 8, 5, 0], np.int64)
        n = 2
        split = run_op("split_ids", {"Ids": [("ids", ids)]}, {},
                       {"Out": n})["Out"]
        # shard k owns ids with id % n == k; others marked -1
        for k in range(n):
            mine = ids[ids % n == k]
            got = split[k][split[k] >= 0]
            assert set(got.tolist()) == set(mine.tolist())
        # merge back: each shard's table rows keyed by its Rows list
        V, D = 10, 4
        table = np.arange(V * D, dtype=np.float32).reshape(V, D)
        rows = [np.where(split[k] >= 0, split[k], 0).astype(np.int64)
                for k in range(n)]
        xs = [table[rows[k]] for k in range(n)]
        merged = run_op(
            "merge_ids",
            {"Ids": [("mi", ids)],
             "Rows": [("r0", rows[0]), ("r1", rows[1])],
             "X": [("x0", xs[0]), ("x1", xs[1])]}, {},
            {"Out": 1})["Out"]
        np.testing.assert_allclose(merged, table[ids])

    def test_split_byref(self):
        rng = np.random.RandomState(26)
        x = rng.uniform(-1, 1, (7, 3)).astype("f")
        out = run_op("split_byref", {"X": x}, {"sections": [3, 4]},
                     {"Out": 2})["Out"]
        np.testing.assert_allclose(out[0], x[:3])
        np.testing.assert_allclose(out[1], x[3:])

    def test_lookup_sparse_table(self):
        rng = np.random.RandomState(27)
        w = rng.uniform(-1, 1, (9, 4)).astype("f")
        ids = np.asarray([[1, 3], [8, 0]], np.int64)
        out = run_op("lookup_sparse_table", {"W": w, "Ids": ids}, {},
                     {"Out": 1})["Out"]
        np.testing.assert_allclose(out, w[ids])

    def test_merge_and_split_selected_rows(self):
        rng = np.random.RandomState(28)
        x = rng.uniform(-1, 1, (6, 3)).astype("f")
        merged = run_op("merge_selected_rows", {"X": x}, {},
                        {"Out": 1})["Out"]
        np.testing.assert_allclose(merged, x)
        parts = run_op("split_selected_rows", {"X": x},
                       {"height_sections": [2, 4]}, {"Out": 2})["Out"]
        np.testing.assert_allclose(parts[0], x[:2])
        np.testing.assert_allclose(parts[1], x[2:])


# -- singletons --------------------------------------------------------------


class TestTailSingletons:
    def test_average_accumulates_no_roll(self):
        p = np.ones((2, 2), "f")
        s1 = np.full((2, 2), 3.0, "f")
        s2 = np.zeros((2, 2), "f")
        s3 = np.zeros((2, 2), "f")
        na = np.asarray([2], np.int64)
        ona = np.asarray([0], np.int64)
        nu = np.asarray([2], np.int64)
        out = run_op("average_accumulates",
                     {"param": p, "in_sum_1": s1, "in_sum_2": s2,
                      "in_sum_3": s3, "in_num_accumulates": na,
                      "in_old_num_accumulates": ona, "in_num_updates": nu},
                     {"average_window": 0.0, "max_average_window": 100,
                      "min_average_window": 10},
                     {"out_sum_1": 1, "out_num_accumulates": 1,
                      "out_num_updates": 1})
        # below min window: accumulate param into sum_1, counters advance
        np.testing.assert_allclose(out["out_sum_1"], s1 + p)
        assert int(out["out_num_accumulates"]) == 3
        assert int(out["out_num_updates"]) == 3

    def test_average_accumulates_roll(self):
        p = np.ones((2,), "f")
        s1 = np.full((2,), 5.0, "f")
        s2 = np.full((2,), 7.0, "f")
        s3 = np.zeros((2,), "f")
        na = np.asarray([9], np.int64)
        ona = np.asarray([0], np.int64)
        nu = np.asarray([9], np.int64)
        out = run_op("average_accumulates",
                     {"param": p, "in_sum_1": s1, "in_sum_2": s2,
                      "in_sum_3": s3, "in_num_accumulates": na,
                      "in_old_num_accumulates": ona, "in_num_updates": nu},
                     {"average_window": 0.0, "max_average_window": 5,
                      "min_average_window": 5},
                     {"out_sum_1": 1, "out_sum_2": 1, "out_sum_3": 1,
                      "out_num_accumulates": 1,
                      "out_old_num_accumulates": 1})
        # window full: sum_1 -> sum_2, sum_2 -> sum_3, sum_1 resets
        np.testing.assert_allclose(out["out_sum_1"], np.zeros(2))
        np.testing.assert_allclose(out["out_sum_2"], s1 + p)
        np.testing.assert_allclose(out["out_sum_3"], s2)
        assert int(out["out_num_accumulates"]) == 0
        assert int(out["out_old_num_accumulates"]) == 10

    def test_depthwise_conv2d_transpose_vs_per_channel(self):
        rng = np.random.RandomState(29)
        C = 3
        x = rng.uniform(-1, 1, (2, C, 5, 5)).astype("f")
        w = rng.uniform(-0.5, 0.5, (C, 1, 3, 3)).astype("f")
        fused = run_op("depthwise_conv2d_transpose",
                       {"Input": x, "Filter": w},
                       {"strides": [2, 2], "paddings": [1, 1],
                        "groups": C}, {"Output": 1})["Output"]
        chans = []
        for c in range(C):
            o = run_op("conv2d_transpose",
                       {"Input": x[:, c:c + 1], "Filter": w[c:c + 1]},
                       {"strides": [2, 2], "paddings": [1, 1]},
                       {"Output": 1})["Output"]
            chans.append(o)
        want = np.concatenate(chans, axis=1)
        np.testing.assert_allclose(fused, want, rtol=1e-5, atol=1e-5)

    def test_gaussian_random_batch_size_like(self):
        x = np.zeros((64, 3), "f")
        out = run_op("gaussian_random_batch_size_like", {"Input": x},
                     {"shape": [1, 256], "mean": 2.0, "std": 0.5},
                     {"Out": 1})["Out"]
        assert out.shape == (64, 256)
        assert abs(out.mean() - 2.0) < 0.05
        assert abs(out.std() - 0.5) < 0.05

    def test_fill_zeros_like2(self):
        x = np.ones((3, 4), "f")
        out = run_op("fill_zeros_like2", {"X": x}, {}, {"Out": 1})["Out"]
        np.testing.assert_array_equal(out, np.zeros((3, 4), "f"))
        assert out.dtype == np.float32

    def test_fill_and_size(self):
        out = run_op("fill", {},
                     {"value": [1.0, 2.0, 3.0, 4.0], "shape": [2, 2],
                      "dtype": 5}, {"Out": 1})["Out"]
        np.testing.assert_allclose(out, [[1.0, 2.0], [3.0, 4.0]])
        assert out.dtype == np.float32
        # int dtype round-trips (dtype 2 = int32)
        outi = run_op("fill", {},
                      {"value": [1, 2], "shape": [2], "dtype": 2},
                      {"Out": 1})["Out"]
        assert outi.dtype == np.int32
        x = np.zeros((3, 5), "f")
        n = run_op("size", {"Input": x}, {}, {"Out": 1})["Out"]
        assert int(n) == 15
        assert n.dtype in (np.int32, np.int64)
