"""CTR-style distributed payload (reference: dist_ctr.py + dist_save_load.py):
a sparse PS-hosted embedding (DistributedEmbedding over the sparse-table
RPC runtime) feeding dense fc layers trained through the dense-PS
transpiler — 2 pservers x 2 trainers as real processes, per-step losses on
stdout, final params saved for the harness's save/load round-trip check.

Determinism contract for exact trainer-vs-local parity:
- each trainer touches a DISJOINT id space (ids ≡ trainer parity mod 2),
  so sparse pulls never race the other trainer's pushes;
- sparse push grads are scaled 1/n_trainers (the sync-mode grad scale the
  dense transpiler applies), with plain-SGD server rows so updates
  commute;
- the dense half barriers per step through the sync-PS program.
The local baseline runs the full batch against in-process sparse servers
with the SAME shard seeds, so lazily-initialized rows are bit-identical.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.distributed.sparse_table import (DistributedEmbedding,
                                                 SparseTableClient,
                                                 SparseTableServer)

STEPS = 6
BS = 8           # per trainer
DIM = 8          # embedding dim
VOCAB = 64
MAX_ROWS = 16    # static unique-rows bound per batch
N_TRAINERS = 2


def build(demb):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 321
    startup.random_seed = 321
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", shape=[1], dtype="int64")
        dense_x = fluid.layers.data("dense_x", shape=[4])
        y = fluid.layers.data("y", shape=[1])
        emb = demb.lookup(ids, batch_ids_max=MAX_ROWS)
        feat = fluid.layers.concat([emb, dense_x], axis=1)
        h = fluid.layers.fc(feat, 16, act="relu",
                            param_attr=fluid.ParamAttr(name="ctr_w1"))
        pred = fluid.layers.fc(h, 1,
                               param_attr=fluid.ParamAttr(name="ctr_w2"))
        loss = fluid.layers.mean(fluid.layers.square(pred - y))
        fluid.optimizer.SGD(0.05).minimize(loss)
    return main, startup, loss


def make_data():
    """Global batches; trainer t consumes rows [t*BS:(t+1)*BS].  Ids are
    disjoint by trainer parity (trainer 0: even ids, trainer 1: odd)."""
    rng = np.random.RandomState(11)
    batches = []
    for _ in range(STEPS):
        ids = np.zeros((N_TRAINERS * BS, 1), np.int64)
        for t in range(N_TRAINERS):
            ids[t * BS:(t + 1) * BS, 0] = (
                rng.randint(0, VOCAB // 2, BS) * 2 + t)
        dense = rng.randn(N_TRAINERS * BS, 4).astype("f")
        yb = rng.randn(N_TRAINERS * BS, 1).astype("f")
        batches.append((ids, dense, yb))
    return batches


def sparse_endpoints():
    return os.environ["SPARSE_TABLE_ENDPOINTS"].split(",")


def _train_loop(exe, prog, scope, demb, loss, batches, lo_slice,
                grad_scale):
    with fluid.scope_guard(scope):
        for ids, dense, yb in batches:
            ids_t = ids[lo_slice]
            feed, info = demb.prepare_feed(ids_t.reshape(-1))
            outs = exe.run(
                prog,
                feed={"ids": ids_t, "dense_x": dense[lo_slice],
                      "y": yb[lo_slice], **feed},
                fetch_list=[loss, demb.grad_var(prog)], scope=scope)
            demb.push_grads(
                info, np.asarray(outs[1]) * grad_scale)
            print("loss:%.8f" % float(np.asarray(outs[0]).reshape(-1)[0]),
                  flush=True)


def _dump_state(scope, demb, client, touched_ids, save_dir=None,
                main=None, exe=None):
    with fluid.scope_guard(scope):
        for pname in ("ctr_w1", "ctr_w2"):
            v = np.asarray(scope.find_var(pname).get_tensor().numpy())
            print("param:%s:%.8f" % (pname, float(np.abs(v).sum())),
                  flush=True)
        rows = client.pull(np.asarray(sorted(touched_ids), np.int64))
        print("sparse_rows:%.8f" % float(np.abs(rows).sum()), flush=True)
        if save_dir and main is not None:
            fluid.io.save_persistables(exe, save_dir, main_program=main)
            print("saved:%s" % save_dir, flush=True)


def run_local():
    # in-process sparse servers with the same per-shard seeds as the
    # subprocess run (seed = shard index)
    servers = [SparseTableServer(0, dim=DIM, optimizer="sgd", lr=0.05,
                                 seed=s) for s in range(2)]
    for s in servers:
        s.start_thread()
    client = SparseTableClient("ctr_emb",
                               ["127.0.0.1:%d" % s.port for s in servers])
    demb = DistributedEmbedding("ctr_emb", dim=DIM, client=client)
    main, startup, loss = build(demb)
    batches = make_data()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    _train_loop(exe, main, scope, demb, loss, batches, slice(None), 1.0)
    touched = set(int(v) for b in batches for v in b[0].ravel())
    _dump_state(scope, demb, client, touched,
                save_dir=os.environ.get("CTR_SAVE_DIR"), main=main,
                exe=exe)
    client.complete()
    for s in servers:
        s.shutdown()


def run_pserver():
    """Dense pserver + one sparse-table shard in the same process (the
    reference pserver hosts both dense blocks and sparse tables)."""
    eps = os.environ["PADDLE_PSERVER_ENDPOINTS"]
    cur = os.environ["PADDLE_CURRENT_ENDPOINT"]
    shard = int(os.environ["SPARSE_SHARD_ID"])
    sparse_port = int(sparse_endpoints()[shard].split(":")[1])
    sserver = SparseTableServer(sparse_port, dim=DIM, optimizer="sgd",
                                lr=0.05, seed=shard)
    sserver.start_thread()
    demb = DistributedEmbedding("ctr_emb", dim=DIM)
    main, startup, loss = build(demb)
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, startup_program=startup,
                pservers=eps, trainers=N_TRAINERS)
    prog, sprog = t.get_pserver_programs(cur)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(sprog)
        print("pserver:ready", flush=True)
        exe.run(prog, scope=scope)
    # dense program returning means every trainer sent COMPLETE; only now
    # is the sparse shard safe to stop (a trainer-side sparse COMPLETE
    # would kill the shard while the other trainer still pulls)
    sserver.shutdown()
    print("pserver:done", flush=True)


def run_trainer():
    eps = os.environ["PADDLE_PSERVER_ENDPOINTS"]
    tid = int(os.environ["PADDLE_TRAINER_ID"])
    client = SparseTableClient("ctr_emb", sparse_endpoints())
    demb = DistributedEmbedding("ctr_emb", dim=DIM, client=client)
    main, startup, loss = build(demb)
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=tid, program=main, startup_program=startup,
                pservers=eps, trainers=N_TRAINERS)
    tp = t.get_trainer_program()
    batches = make_data()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
    half = slice(tid * BS, (tid + 1) * BS)
    _train_loop(exe, tp, scope, demb, loss, batches, half,
                1.0 / N_TRAINERS)
    touched = set(int(v) for b in batches for v in b[0].ravel())
    save_dir = os.environ.get("CTR_SAVE_DIR") if tid == 0 else None
    _dump_state(scope, demb, client, touched, save_dir=save_dir,
                main=main, exe=exe)
    # no sparse COMPLETE from trainers (see run_pserver); dense COMPLETE
    # coordinates shutdown for both planes
    with fluid.scope_guard(scope):
        scope._ps_comm.complete()


if __name__ == "__main__":
    role = os.environ.get("PADDLE_TRAINING_ROLE", "LOCAL")
    if role == "PSERVER":
        run_pserver()
    elif role == "TRAINER":
        run_trainer()
    else:
        run_local()
