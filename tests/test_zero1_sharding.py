"""ZeRO-1 weight-update sharding + quantized all-reduce (the
FLAGS_collective_mode=zero1 / FLAGS_allreduce_dtype path), on the virtual
8-device CPU mesh:

  * f32 sharded training is BITWISE identical to replicated GradAllReduce
    (same psum-family reduce then fold — op order matches at every world),
  * int8 / bf16 quantized exchange stays within tolerance on BERT-shaped
    gradients, at ~0.25x / ~0.5x the f32 wire bytes,
  * each replica materializes only ~1/nranks of the optimizer slots
    (memory_audit's per-replica accounting),
  * DL006 catches seeded structural defects (double-owned shard, drifted
    dequant scale) with the right rule id + op index.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import flags
from paddle_tpu.transpiler.collective import (GradAllReduce,
                                              ShardedGradAllReduce)

NRANKS = 8


@pytest.fixture(autouse=True)
def _restore_flags():
    keep = {k: flags.flag(k) for k in ("collective_mode", "allreduce_dtype",
                                       "allreduce_quant_bucket")}
    yield
    flags.set_flags({"FLAGS_" + k: v for k, v in keep.items()})


def _build(hidden=32, in_dim=16):
    with fluid.unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[in_dim])
            y = fluid.layers.data("y", shape=[1])
            h = fluid.layers.fc(x, hidden, act="relu",
                                param_attr=fluid.ParamAttr(name="zw1"),
                                bias_attr=fluid.ParamAttr(name="zb1"))
            pred = fluid.layers.fc(h, 1,
                                   param_attr=fluid.ParamAttr(name="zw2"),
                                   bias_attr=fluid.ParamAttr(name="zb2"))
            loss = fluid.layers.mean(fluid.layers.square(pred - y))
            fluid.optimizer.Adam(0.01).minimize(loss)
    return main, startup, loss


def _transpile(cls, main, startup, dtype="f32"):
    flags.set_flags({"FLAGS_allreduce_dtype": dtype})
    eps = ["local:%d" % i for i in range(NRANKS)]
    cls().transpile(startup_program=startup, main_program=main, rank=0,
                    endpoints=eps, current_endpoint=eps[0], wait_port=False)


def _train(cls, dtype="f32", hidden=32, steps=5, keep_scope=False):
    """Transpile + run; returns (losses, {param: np}, main[, scope])."""
    from paddle_tpu.core import analysis

    main, startup, loss = _build(hidden=hidden)
    _transpile(cls, main, startup, dtype=dtype)
    rep = analysis.verify_program(main, feed_names=["x", "y"],
                                  fetch_names=[loss.name],
                                  expected_nranks=NRANKS)
    assert not rep.errors, rep.format()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    losses, params = [], {}
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            xb = rng.randn(16, 16).astype(np.float32)
            yb = rng.randn(16, 1).astype(np.float32)
            lv, = exe.run(main, feed={"x": xb, "y": yb},
                          fetch_list=[loss.name])
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        for v in main.global_block().all_parameters():
            params[v.name] = np.asarray(scope.var(v.name).get_tensor().get())
    if keep_scope:
        return losses, params, main, scope
    return losses, params, main


# --- (a) f32 bitwise parity -------------------------------------------------


def test_f32_sharded_bitwise_matches_replicated():
    la, pa, ma = _train(GradAllReduce)
    lb, pb, mb = _train(ShardedGradAllReduce)
    assert la == lb, (la, lb)
    for name in pa:
        assert np.array_equal(pa[name], pb[name]), name
    meta = mb._collective_meta
    assert meta["mode"] == "zero1" and meta["nranks"] == NRANKS
    tab = meta["zero1_shards"]
    # 2D weights and the 32-wide bias shard; the scalar output bias can't
    assert tab["zw1"]["sharded"] and tab["zw2"]["sharded"]
    assert tab["zb1"]["sharded"] and not tab["zb2"]["sharded"]
    assert tab["zw1"]["rows_per_rank"] == 16 // NRANKS
    # f32 RS+AG moves exactly what a ring allreduce does
    assert meta["wire_bytes_per_step"] == \
        ma._collective_meta["wire_bytes_per_step"]


def test_flag_selects_the_transpiler():
    from paddle_tpu.transpiler.collective import select_grad_transpiler

    flags.set_flags({"FLAGS_collective_mode": "zero1"})
    assert isinstance(select_grad_transpiler(), ShardedGradAllReduce)
    flags.set_flags({"FLAGS_collective_mode": "allreduce"})
    assert isinstance(select_grad_transpiler(), GradAllReduce)
    flags.set_flags({"FLAGS_collective_mode": "bogus"})
    with pytest.raises(ValueError):
        select_grad_transpiler()


# --- (b) quantized exchange: tolerance + wire bytes -------------------------


def test_quantized_exchange_tolerance_and_wire_bytes():
    # BERT-shaped: 768-wide hidden, grads (16,768) / (768,) / (768,1)
    lf, pf, mf = _train(ShardedGradAllReduce, dtype="f32", hidden=768,
                        steps=3)
    l8, p8, m8 = _train(ShardedGradAllReduce, dtype="int8", hidden=768,
                        steps=3)
    lb, pb, mb = _train(ShardedGradAllReduce, dtype="bf16", hidden=768,
                        steps=3)
    assert all(np.isfinite(l) for l in l8 + lb)

    def rel(p):
        num = sum(float(np.linalg.norm(p[n] - pf[n])) ** 2
                  for n in pf) ** 0.5
        den = sum(float(np.linalg.norm(pf[n])) ** 2 for n in pf) ** 0.5
        return num / den

    assert rel(p8) < 0.05, rel(p8)   # int8: few-% drift after 3 steps
    assert rel(pb) < 0.02, rel(pb)   # bf16 keeps ~8 mantissa bits

    wf = mf._collective_meta["wire_bytes_per_step"]
    w8 = m8._collective_meta["wire_bytes_per_step"]
    wb = mb._collective_meta["wire_bytes_per_step"]
    assert w8 / wf <= 0.35, (w8, wf)     # acceptance budget
    assert wb / wf <= 0.60, (wb, wf)
    assert m8._collective_meta["allreduce_dtype"] == "int8"


def test_replicated_quantized_allreduce_wire_budget():
    _, pf, mf = _train(GradAllReduce, dtype="f32", hidden=768, steps=2)
    _, p8, m8 = _train(GradAllReduce, dtype="int8", hidden=768, steps=2)
    ratio = (m8._collective_meta["wire_bytes_per_step"]
             / mf._collective_meta["wire_bytes_per_step"])
    assert ratio <= 0.35, ratio


# --- (c) optimizer-state HBM per replica ------------------------------------


def test_optimizer_slots_are_sharded_per_replica():
    from paddle_tpu.core.memory_audit import _nbytes, _nbytes_replica

    _, _, main, scope = _train(ShardedGradAllReduce, keep_scope=True)
    blk = main.global_block()
    slot_names = []
    for op in blk.ops:
        # the executor's FuseOptimizerOpsPass may have batched the adams
        if op.type in ("adam", "fused_adam"):
            slot_names += op.input("Moment1") + op.input("Moment2")
    assert slot_names
    full = per_replica = 0
    sharded_slots = 0
    with fluid.scope_guard(scope):
        for n in slot_names:
            arr = scope.var(n).get_tensor().get()
            b, br = _nbytes(arr), _nbytes_replica(arr)
            full += b
            per_replica += br
            if br < b:
                sharded_slots += 1
                # the executor's NamedSharding put 1/nranks rows here
                assert br * NRANKS == b, (n, b, br)
    assert sharded_slots >= 6  # zw1/zb1/zw2 x two moments
    # acceptance: optimizer-state HBM per replica <= 1/4 of replicated
    assert per_replica <= full / 4, (per_replica, full)


def test_memory_audit_report_carries_per_replica_totals():
    from paddle_tpu.core import memory_audit

    report = {"arg_bytes_by_class": {"param_rw": 800},
              "arg_bytes_per_replica_by_class": {"param_rw": 100}}
    text = memory_audit.format_report(report)
    assert "per replica" in text, text


# --- (d) DL006 seeded-defect fixtures ---------------------------------------


def _verify(main, loss):
    from paddle_tpu.core import analysis

    return analysis.verify_program(main, feed_names=["x", "y"],
                                   fetch_names=[loss.name],
                                   expected_nranks=NRANKS)


def test_dl006_double_owned_shard_is_flagged():
    main, startup, loss = _build()
    _transpile(ShardedGradAllReduce, main, startup)
    blk = main.global_block()
    gather_idx = [i for i, op in enumerate(blk.ops)
                  if op.type == "c_allgather"
                  and op.output("Out") == ["zw1"]]
    assert len(gather_idx) == 1
    src = blk.ops[gather_idx[0]]
    # a second gather writing the same param: two owners race on its rows
    blk.append_op(type="c_allgather", inputs={"X": src.input("X")},
                  outputs={"Out": ["zw1"]},
                  attrs={"ring_id": src.attr("ring_id"), "nranks": NRANKS})
    dup_idx = len(blk.ops) - 1
    rep = _verify(main, loss)
    errs = [d for d in rep.errors if d.rule == "DL006"]
    assert errs, rep.format()
    assert any(d.op_idx == dup_idx for d in errs), \
        [(d.op_idx, d.message) for d in errs]


def test_dl006_drifted_dequant_scale_is_flagged():
    main, startup, loss = _build()
    _transpile(ShardedGradAllReduce, main, startup, dtype="int8")
    blk = main.global_block()
    dq_idx = [i for i, op in enumerate(blk.ops)
              if op.type in ("c_reducescatter_q", "c_allreduce_qsum")]
    assert dq_idx
    # drift the dequant geometry away from what its c_quant_pack produced
    bad = dq_idx[0]
    blk.ops[bad]._set_attr("bucket", int(blk.ops[bad].attr("bucket")) + 1)
    rep = _verify(main, loss)
    errs = [d for d in rep.errors if d.rule == "DL006"]
    assert errs, rep.format()
    assert any(d.op_idx == bad for d in errs), \
        [(d.op_idx, d.message) for d in errs]


def test_dl006_rewired_scale_input_is_flagged():
    main, startup, loss = _build()
    _transpile(ShardedGradAllReduce, main, startup, dtype="int8")
    blk = main.global_block()
    dq_idx = [i for i, op in enumerate(blk.ops)
              if op.type in ("c_reducescatter_q", "c_allreduce_qsum")]
    scales = sorted({op.input("Scale")[0]
                     for op in (blk.ops[i] for i in dq_idx)})
    if len(scales) < 2:
        pytest.skip("needs two quantized exchanges to cross-wire")
    bad = dq_idx[0]
    other = [s for s in scales if s != blk.ops[bad].input("Scale")[0]][0]
    blk.ops[bad].inputs["Scale"] = [other]  # dequant with a foreign scale
    rep = _verify(main, loss)
    errs = [d for d in rep.errors if d.rule == "DL006"]
    assert errs, rep.format()
    assert any(d.op_idx == bad for d in errs), \
        [(d.op_idx, d.message) for d in errs]


def test_dl006_clean_zero1_program_verifies_clean():
    main, startup, loss = _build()
    _transpile(ShardedGradAllReduce, main, startup)
    rep = _verify(main, loss)
    assert not rep.errors, rep.format()
