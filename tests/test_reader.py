"""Data pipeline tests: native blocking queue, DataLoader (iterable and
program-driven), reader decorators, Dataset/trainer path, corpora.

Mirrors the reference's reader tests (unittests/test_generator_dataloader.py,
test_py_reader_*, test_dataset.py, reader decorator tests)."""

import os
import threading

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.native.queue import NativeBlockingQueue, QueueClosed
from paddle_tpu import reader as decorators


# ---------------------------------------------------------------------------
# native queue
# ---------------------------------------------------------------------------


def test_native_queue_roundtrip():
    q = NativeBlockingQueue(4)
    a = np.arange(12, dtype="float32").reshape(3, 4)
    b = np.array([[1, 2]], dtype="int64")
    c = np.float32(3.5).reshape(())  # 0-d
    q.push([a, b, c])
    out = q.pop()
    np.testing.assert_array_equal(out[0], a)
    np.testing.assert_array_equal(out[1], b)
    assert out[2].shape == () and out[2] == np.float32(3.5)
    assert out[0].dtype == np.float32 and out[1].dtype == np.int64


def test_native_queue_close_drains_then_raises():
    q = NativeBlockingQueue(4)
    q.push([np.zeros(2)])
    q.close()
    assert q.pop() is not None
    with pytest.raises(QueueClosed):
        q.pop()
    with pytest.raises(QueueClosed):
        q.push([np.zeros(2)])


def test_native_queue_blocking_and_threads():
    q = NativeBlockingQueue(2)
    n = 200

    def producer():
        for i in range(n):
            q.push([np.full((4,), i, dtype="int32")])
        q.close()

    t = threading.Thread(target=producer)
    t.start()
    got = []
    while True:
        try:
            item = q.pop()
        except QueueClosed:
            break
        got.append(int(item[0][0]))
    t.join()
    assert got == list(range(n))


def test_native_queue_kill_unblocks():
    q = NativeBlockingQueue(1)
    errs = []

    def blocked_pop():
        try:
            q.pop()
        except QueueClosed:
            errs.append("closed")

    t = threading.Thread(target=blocked_pop)
    t.start()
    q.kill()
    t.join(timeout=5)
    assert errs == ["closed"]


# ---------------------------------------------------------------------------
# DataLoader
# ---------------------------------------------------------------------------


def _mlp_program(d=8, k=3):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[d])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        logits = fluid.layers.fc(x, k)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def _batches(n_batches=6, bs=16, d=8, k=3, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(n_batches):
        yield [rng.randn(bs, d).astype("float32"),
               rng.randint(0, k, (bs, 1)).astype("int64")]


def test_dataloader_iterable_trains():
    main, startup, loss = _mlp_program()
    x = main.global_block().var("x")
    y = main.global_block().var("y")
    loader = fluid.io.DataLoader.from_generator(
        feed_list=[x, y], capacity=8, use_double_buffer=False)
    loader.set_batch_generator(lambda: _batches(12))
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        seen = 0
        for feed in loader:
            out, = exe.run(main, feed=feed, fetch_list=[loss])
            seen += 1
        assert seen == 12


def test_dataloader_sample_generator_batching():
    def samples():
        for i in range(25):
            yield np.full((4,), i, "float32"), np.int64(i % 3)

    x = None
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
    loader = fluid.io.DataLoader.from_generator(
        feed_list=[x, y], capacity=4, use_double_buffer=False)
    loader.set_sample_generator(samples, batch_size=10, drop_last=True)
    batches = list(loader)
    assert len(batches) == 2
    assert batches[0]["x"].shape == (10, 4)
    assert batches[0]["y"].dtype == np.int64


def test_dataloader_non_iterable_eof():
    main, startup, loss = _mlp_program()
    x = main.global_block().var("x")
    y = main.global_block().var("y")
    with fluid.program_guard(main, startup):
        loader = fluid.io.DataLoader.from_generator(
            feed_list=[x, y], capacity=4, iterable=False)
    loader.set_batch_generator(lambda: _batches(5))
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for _epoch in range(2):
            loader.start()
            steps = 0
            try:
                while True:
                    exe.run(main, fetch_list=[loss])
                    steps += 1
            except fluid.core.EOFException:
                loader.reset()
            assert steps == 5


# ---------------------------------------------------------------------------
# decorators
# ---------------------------------------------------------------------------


def test_reader_decorators():
    r = lambda: iter(range(10))  # noqa: E731
    assert list(decorators.firstn(r, 3)()) == [0, 1, 2]
    assert sorted(decorators.shuffle(r, 5)()) == list(range(10))
    assert list(decorators.map_readers(lambda a, b: a + b, r, r)()) == \
        [2 * i for i in range(10)]
    assert list(decorators.chain(r, r)()) == list(range(10)) * 2
    assert list(decorators.cache(r)()) == list(range(10))
    assert list(decorators.buffered(r, 2)()) == list(range(10))
    got = list(decorators.compose(r, r)())
    assert got[0] == (0, 0) and len(got) == 10
    bs = list(decorators.batch(r, 4)())
    assert bs == [[0, 1, 2, 3], [4, 5, 6, 7], [8, 9]]
    bs = list(decorators.batch(r, 4, drop_last=True)())
    assert bs == [[0, 1, 2, 3], [4, 5, 6, 7]]
    out = list(decorators.xmap_readers(lambda x: x * 2, r, 3, 4, order=True)())
    assert out == [2 * i for i in range(10)]
    out = sorted(decorators.xmap_readers(lambda x: x * 2, r, 3, 4)())
    assert out == [2 * i for i in range(10)]


# ---------------------------------------------------------------------------
# Dataset (native MultiSlot store) + trainer path
# ---------------------------------------------------------------------------


def _write_multislot(tmp_path, n=64, seed=0):
    """Records: slot0 = 4 float features, slot1 = 1 int label."""
    rng = np.random.RandomState(seed)
    w = np.array([0.5, -1.0, 2.0, 0.25], "float32")
    path = os.path.join(tmp_path, "part-0.txt")
    with open(path, "w") as f:
        for _ in range(n):
            x = rng.randn(4).astype("float32")
            yv = int(x @ w > 0)
            f.write("4 %s 1 %d\n" % (" ".join("%.6f" % v for v in x), yv))
    return path


def test_inmemory_dataset_and_train_from_dataset(tmp_path):
    path = _write_multislot(str(tmp_path), n=64)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
        logits = fluid.layers.fc(x, 2)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(0.1).minimize(loss)

    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(16)
    ds.set_thread(2)
    ds.set_filelist([path])
    ds.set_use_var([x, y])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 64
    ds.local_shuffle()

    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        out = exe.train_from_dataset(
            main, ds, thread=2, fetch_list=[loss], fetch_info=["loss"],
            print_period=100)
        assert out and np.isfinite(float(out[0][0]))


def test_dataset_loader_batches(tmp_path):
    path = _write_multislot(str(tmp_path), n=32)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.data("y", shape=[1], dtype="int64")
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(8)
    ds.set_filelist([path])
    ds.set_use_var([x, y])
    ds.load_into_memory()
    batches = list(fluid.io.DataLoader.from_dataset(ds))
    assert len(batches) == 4
    assert batches[0]["x"].shape == (8, 4)
    assert batches[0]["x"].dtype == np.float32
    assert batches[0]["y"].dtype == np.int64


# ---------------------------------------------------------------------------
# corpora
# ---------------------------------------------------------------------------


def test_corpora_smoke():
    from paddle_tpu import datasets

    img, lbl = next(datasets.mnist.train()())
    assert img.shape == (784,) and 0 <= int(lbl) < 10
    x, y = next(datasets.uci_housing.train()())
    assert x.shape == (13,) and y.shape == (1,)
    ids, sent = next(datasets.imdb.train()())
    assert len(ids) >= 1 and int(sent) in (0, 1)
    s, t, tn = next(datasets.wmt16.train(100, 100)())
    assert len(t) == len(tn) and t[0] == 0 and tn[-1] == 1


def test_mnist_learnable_with_dataloader():
    """End-to-end: synthetic-MNIST via DataLoader trains to high accuracy."""
    from paddle_tpu import datasets

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape=[784])
        label = fluid.layers.data("label", shape=[1], dtype="int64")
        h = fluid.layers.fc(img, 64, act="relu")
        logits = fluid.layers.fc(h, 10)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
        fluid.optimizer.Adam(1e-3).minimize(loss)

    loader = fluid.io.DataLoader.from_generator(
        feed_list=[img, label], capacity=8, use_double_buffer=False)
    loader.set_sample_generator(
        decorators.firstn(datasets.mnist.train(), 2048), batch_size=128)

    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        last_acc = 0.0
        for _epoch in range(3):
            for feed in loader:
                _, a = exe.run(main, feed=feed, fetch_list=[loss, acc])
                last_acc = float(a[0])
        assert last_acc > 0.9, last_acc


def test_dataloader_generator_exception_propagates():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
    loader = fluid.io.DataLoader.from_generator(
        feed_list=[x], capacity=2, use_double_buffer=False)

    def bad_batches():
        yield [np.zeros((2, 4), "float32")]
        raise ValueError("corrupt shard")

    loader.set_batch_generator(bad_batches)
    it = iter(loader)
    next(it)
    with pytest.raises(RuntimeError, match="generator raised"):
        for _ in it:
            pass


def test_dataloader_next_advances():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[1])
    loader = fluid.io.DataLoader.from_generator(
        feed_list=[x], capacity=2, use_double_buffer=False)
    loader.set_batch_generator(
        lambda: iter([[np.full((1, 1), i, "float32")] for i in range(3)]))
    vals = [float(loader.next()["x"][0, 0]) for _ in range(3)]
    assert vals == [0.0, 1.0, 2.0]
    with pytest.raises(StopIteration):
        loader.next()


def test_data_feed_desc_prototxt(tmp_path):
    proto = tmp_path / "feed.prototxt"
    proto.write_text("""
batch_size: 64
multi_slot_desc {
  slots {
    name: "words"
    type: "uint64"
    is_dense: false
    is_used: true
  }
  slots {
    name: "label"
    type: "uint64"
    is_dense: false
    is_used: true
  }
}
""")
    d = fluid.DataFeedDesc(str(proto))
    assert d.batch_size == 64
    assert [s["name"] for s in d.slots] == ["words", "label"]
    d.set_batch_size(128)
    d.set_dense_slots(["label"])
    assert d.batch_size == 128
    assert d.slots[1]["is_dense"] and not d.slots[0]["is_dense"]
    assert 'name: "words"' in d.desc()
