"""Executor tests: feed/fetch, scope persistence, startup init, donation,
program cache (mirrors reference test_executor_* family)."""

import numpy as np

import paddle_tpu as fluid


def _new_progs():
    return fluid.Program(), fluid.Program()


def test_feed_fetch_roundtrip():
    main, startup = _new_progs()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3])
        y = fluid.layers.scale(x, scale=2.0, bias=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        xv = np.arange(6, dtype="float32").reshape(2, 3)
        out, = exe.run(main, feed={"x": xv}, fetch_list=[y])
        np.testing.assert_allclose(out, xv * 2 + 1, rtol=1e-6)


def test_startup_initializes_params():
    main, startup = _new_progs()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        out = fluid.layers.fc(x, 2, param_attr=fluid.ParamAttr(name="w_test"),
                              bias_attr=False)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        w = scope.find_var("w_test")
        assert w is not None
        assert np.asarray(w.get_tensor().numpy()).shape == (4, 2)


def test_persistable_updates_written_back():
    main, startup = _new_progs()
    with fluid.program_guard(main, startup):
        counter = fluid.layers.create_global_var(
            shape=[1], value=0.0, dtype="float32", persistable=True,
            name="step_counter")
        main.global_block().append_op(
            type="increment", inputs={"X": [counter]},
            outputs={"Out": [counter]}, attrs={"step": 1.0})
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            exe.run(main)
        val = np.asarray(scope.find_var("step_counter").get_tensor().numpy())
        assert val[0] == 3.0


def test_uninitialized_param_raises():
    main, startup = _new_progs()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        out = fluid.layers.fc(x, 2)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        # no startup run
        try:
            exe.run(main, feed={"x": np.zeros((1, 4), "float32")},
                    fetch_list=[out])
            assert False, "expected RuntimeError"
        except RuntimeError as e:
            assert "startup" in str(e)


def test_randomness_deterministic_per_seed():
    main, startup = _new_progs()
    main.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[100])
        y = fluid.layers.dropout(x, 0.5)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((2, 100), "float32")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        a, = exe.run(main, feed={"x": xv}, fetch_list=[y])
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        b, = exe.run(main, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_array_equal(a, b)


def test_varying_batch_size_recompiles():
    main, startup = _new_progs()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[3])
        y = fluid.layers.reduce_sum(x)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for bs in (2, 5, 8):
            xv = np.ones((bs, 3), "float32")
            out, = exe.run(main, feed={"x": xv}, fetch_list=[y])
            assert float(out[0]) == bs * 3


def test_program_uid_survives_gc_aliasing():
    """Cache keys must use Program._uid, not id(program): after a Program is
    GC'd, a new Program can land at the same id() with a colliding version
    (reference analog: ExecutorPrepareContext keyed by program address is
    rebuilt per Prepare call, executor.cc)."""
    import gc

    exe = fluid.Executor(fluid.CPUPlace())

    def build(scale):
        main, startup = _new_progs()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", shape=[3])
            y = fluid.layers.scale(x, scale=scale)
        return main, startup, y

    xv = np.ones((2, 3), "float32")
    seen_ids, uids = set(), set()
    for scale in (2.0, 3.0, 5.0):
        main, startup, y = build(scale)
        seen_ids.add(id(main))
        uids.update((main._uid, startup._uid))
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            out, = exe.run(main, feed={"x": xv}, fetch_list=[y])
            np.testing.assert_allclose(out, xv * scale, rtol=1e-6)
        del main, startup, y
        gc.collect()
    # UIDs never collide even if CPython reuses the address (id collision
    # is likely but not guaranteed; the UID guarantee is what we assert)
    assert len(uids) == 6
    # every cache key inserted used the uid namespace, not the address one
    assert {k[0] for k in exe._cache} <= uids


def test_program_clone_gets_fresh_uid():
    main, _ = _new_progs()
    assert main.clone()._uid != main._uid
    assert main.clone(for_test=True)._uid != main._uid
