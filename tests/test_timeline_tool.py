"""tools/timeline.py exporter tests: the from_profiler path round-trips a
real fluid.profiler capture (including the new ph:"M" metadata and ph:"i"
instant markers), and from_xplane decodes a hand-encoded synthetic
.xplane.pb through the in-repo proto reader."""

import json
import os
import sys

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import profiler

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import timeline  # noqa: E402


def _tiny_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[4])
        y = fluid.layers.fc(x, 3)
        loss = fluid.layers.reduce_mean(y)
    return main, startup, loss


def test_from_profiler_cli_round_trip(tmp_path):
    main, startup, loss = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    prof_path = str(tmp_path / "prof.json")
    out_path = str(tmp_path / "timeline.json")
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        profiler.reset_profiler()
        with profiler.profiler("All", "total", prof_path):
            for _ in range(2):
                exe.run(main, feed={"x": np.ones((2, 4), "f")},
                        fetch_list=[loss])
    rc = timeline.main(["--profile_path", prof_path,
                        "--timeline_path", out_path])
    assert rc == 0
    with open(out_path) as f:
        trace = json.load(f)
    evs = trace["traceEvents"]
    runs = [e for e in evs if e["name"] == "Executor::Run"]
    assert len(runs) == 2
    # the executor marks each step as a ph:"i" instant while profiling
    insts = [e for e in evs if e.get("ph") == "i"]
    assert [e["name"] for e in insts] == ["step", "step"]
    # the executor's step counter is cumulative, so only ordering is fixed
    s0, s1 = (e["args"]["step"] for e in insts)
    assert s1 == s0 + 1
    assert all(e["s"] == "g" for e in insts)
    # ph:"M" process/thread name metadata for chrome://tracing / Perfetto
    meta = {e["name"]: e for e in evs if e.get("ph") == "M"}
    assert meta["process_name"]["args"]["name"] == "paddle_tpu host"
    assert "thread_name" in meta


def test_from_profiler_accepts_bare_event_list(tmp_path):
    prof_path = str(tmp_path / "bare.json")
    out_path = str(tmp_path / "out.json")
    bare = [{"name": "op", "ph": "X", "pid": 0, "tid": 0,
             "ts": 1.0, "dur": 2.0}]
    with open(prof_path, "w") as f:
        json.dump(bare, f)
    assert timeline.main(["--profile_path", prof_path,
                          "--timeline_path", out_path]) == 0
    with open(out_path) as f:
        assert json.load(f)["traceEvents"] == bare


# --- synthetic XSpace proto (matches from_xplane's field numbers) -----------


def _varint(n):
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _msg(num, payload):
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def _num(num, value):
    return _varint(num << 3) + _varint(value)


def _make_xspace():
    # XSpace.planes[0]: name + one event_metadata + one line w/ two events
    emeta = _msg(4, _num(1, 7) + _msg(2, _msg(2, b"fusion.1")))
    ev1 = _msg(4, _num(1, 7) + _num(2, 2_000_000) + _num(3, 5_000_000))
    ev2 = _msg(4, _num(1, 7) + _num(2, 9_000_000) + _num(3, 1_000_000))
    line = _msg(3, _msg(2, b"XLA Ops") + _num(3, 1000) + ev1 + ev2)
    plane = _msg(2, b"/device:TPU:0") + emeta + line
    return _msg(1, plane)


def test_from_xplane_synthetic_proto(tmp_path):
    with open(str(tmp_path / "host.xplane.pb"), "wb") as f:
        f.write(_make_xspace())
    trace = timeline.from_xplane(str(tmp_path))
    evs = trace["traceEvents"]
    assert len(evs) == 2
    ev = evs[0]
    assert ev["name"] == "fusion.1"
    assert ev["pid"] == "/device:TPU:0" and ev["tid"] == "XLA Ops"
    # line ts0 is ns, event offset/duration are ps, chrome wants us:
    # 1000 ns + 2_000_000 ps = 3.0 us; dur 5_000_000 ps = 5.0 us
    assert ev["ts"] == 3.0 and ev["dur"] == 5.0
    assert evs[1]["ts"] == 10.0 and evs[1]["dur"] == 1.0


def test_from_xplane_cli_and_missing_dir(tmp_path):
    with open(str(tmp_path / "host.xplane.pb"), "wb") as f:
        f.write(_make_xspace())
    out_path = str(tmp_path / "device_timeline.json")
    assert timeline.main(["--xplane_dir", str(tmp_path),
                          "--timeline_path", out_path]) == 0
    with open(out_path) as f:
        assert len(json.load(f)["traceEvents"]) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    try:
        timeline.from_xplane(str(empty))
        raise AssertionError("expected FileNotFoundError")
    except FileNotFoundError:
        pass
