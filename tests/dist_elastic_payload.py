"""Runnable elastic-collective training payload (3 members, all-reduce
data parallelism under distributed/elastic.py's quorum layer).

Each process builds the same toy regression, wraps it in an
``ElasticMember`` (pristine programs; the member re-transpiles
GradAllReduce per quorum epoch and verifies the rewrite in error mode),
gates every step, shards the deterministic global batch by its CURRENT
dense pid/world, and checkpoints through a shared CheckpointManager.

Markers on stdout, one per line, for the test harness:

  start: rank=R epoch=E world=W restore=S     after the first adoption
  start_phases: compile=C source=SRC          cold compile ms of that adoption
                                              + where its state came from
                                              (peer|fs|none)
  mark:step=S world=W epoch=E                 before running step S
  loss:<float>                                after running a step
  requorum: epoch=E world=W restore=S         after adopting a new view
  requorum_phases: standby=B transpile=T verify=V compile=C restore=R source=SRC
                                              phase breakdown (ms) of the
                                              same adoption + restore source
  statehash:step=S hash=H                     sha256 (truncated) over the
                                              restored persistable state,
                                              after start and after every
                                              requorum — ranks that restored
                                              the same step must print the
                                              same hash, bitwise
  standby: {(ranks): compiled, ...}           after wait_standby (with
                                              --wait_standby)
  done: rank=R epoch=E world=W                clean completion

Flags:
  --ckpt_dir DIR     shared checkpoint directory (required)
  --pause_at S       print "pause:S" before gating step S, then sleep —
                     the test SIGKILLs this member there (outside any
                     collective, so gloo never wedges mid-all-reduce)
  --hold_at S N      at step S, spin on the gate until the world has
                     grown back to N members (deterministic rejoin rendezvous)
  --wait_standby     block until the background standby builder finishes
                     before entering the training loop (makes the
                     standby-hit path deterministic for the test)
"""

import argparse
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# exactly ONE local device per process (collectives span processes); the
# parent pytest env forces an 8-device CPU mesh via XLA_FLAGS
import re as _re

_xf = _re.sub(r"--xla_force_host_platform_device_count=\d+", "",
              os.environ.get("XLA_FLAGS", ""))
os.environ["XLA_FLAGS"] = (
    _xf + " --xla_force_host_platform_device_count=1").strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 1)
except AttributeError:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.distributed.elastic import ElasticMember
from paddle_tpu.io import CheckpointManager

STEPS = 12
ROWS = 12  # global batch rows per step; divisible by worlds 3 and 2


def build():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = 321
    startup.random_seed = 321
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", shape=[6])
        y = fluid.layers.data("y", shape=[1])
        h = fluid.layers.fc(x, 16, act="relu",
                            param_attr=fluid.ParamAttr(name="ew1"))
        pred = fluid.layers.fc(h, 1, param_attr=fluid.ParamAttr(name="ew2"))
        loss = fluid.layers.mean(fluid.layers.square(pred - y))
    return main, startup, loss


def make_data():
    rng = np.random.RandomState(23)
    w = rng.randn(6, 1).astype("f")
    xs, ys = [], []
    for _ in range(STEPS):
        x = rng.randn(ROWS, 6).astype("f")
        xs.append(x)
        ys.append((x @ w).astype("f"))
    return xs, ys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt_dir", required=True)
    ap.add_argument("--pause_at", type=int, default=None)
    ap.add_argument("--hold_at", type=int, nargs=2, default=None,
                    metavar=("STEP", "WORLD"))
    ap.add_argument("--wait_standby", action="store_true")
    args = ap.parse_args()

    main_p, startup_p, loss = build()
    with fluid.program_guard(main_p, startup_p):
        fluid.optimizer.SGD(0.05).minimize(loss)

    xs, ys = make_data()
    exe = fluid.Executor(fluid.CPUPlace())
    ckpt = CheckpointManager(args.ckpt_dir, save_interval=2, max_num=4)
    member = ElasticMember(
        main_p, startup_p, executor=exe, ckpt=ckpt,
        feed_names=["x", "y"], fetch_names=[loss.name],
        # per-world feed signature: lets the member pre-compile the step
        # for standby worlds and warm the adopted world eagerly
        feed_specs=lambda world: {"x": ((ROWS // world, 6), "float32"),
                                  "y": ((ROWS // world, 1), "float32")})
    def state_hash():
        import hashlib

        scope = fluid.global_scope()
        h = hashlib.sha256()
        for name in sorted(v.name for v in member.main_program.list_vars()
                           if v.persistable and not v.is_data):
            sv = scope.find_var(name)
            if sv is None or not sv.get_tensor()._is_initialized():
                continue
            arr = np.asarray(sv.get_tensor().numpy())
            h.update(name.encode())
            h.update(arr.tobytes())
        return h.hexdigest()[:16]

    member.start()
    print("start: rank=%d epoch=%d world=%d restore=%d"
          % (member.rank, member.epoch, member.world, member.restore_step),
          flush=True)
    print("start_phases: compile=%.3f source=%s"
          % (member.last_adopt_phases.get("compile", -1.0),
             member.last_restore_source or "none"), flush=True)
    print("statehash:step=%d hash=%s"
          % (member.restore_step, state_hash()), flush=True)
    if args.wait_standby:
        built = member.wait_standby(timeout=300.0)
        print("standby: %s" % sorted(built.items()), flush=True)

    def report_requorum():
        ph = member.last_adopt_phases
        print("requorum: epoch=%d world=%d restore=%d"
              % (member.epoch, member.world, member.restore_step), flush=True)
        print("requorum_phases: standby=%d transpile=%.3f verify=%.3f "
              "compile=%.3f restore=%.3f source=%s"
              % (1 if member.last_adopt_standby else 0,
                 ph.get("transpile", -1.0), ph.get("verify", -1.0),
                 ph.get("compile", -1.0), ph.get("restore", -1.0),
                 member.last_restore_source or "none"), flush=True)
        print("statehash:step=%d hash=%s"
              % (member.restore_step, state_hash()), flush=True)

    step = member.restore_step
    while step < STEPS:
        if args.pause_at is not None and step == args.pause_at:
            print("pause:%d" % step, flush=True)
            time.sleep(600)  # SIGKILLed here by the test
        if args.hold_at is not None and step == args.hold_at[0]:
            while member.world < args.hold_at[1]:
                if not member.gate(step):
                    step = member.restore_step
                    report_requorum()
                time.sleep(0.2)
        if not member.gate(step):
            step = member.restore_step
            report_requorum()
            continue
        shard = ROWS // member.world
        lo = shard * member.pid
        print("mark:step=%d world=%d epoch=%d"
              % (step, member.world, member.epoch), flush=True)
        out, = exe.run(member.main_program,
                       feed={"x": xs[step][lo:lo + shard],
                             "y": ys[step][lo:lo + shard]},
                       fetch_list=[loss.name])
        print("loss:%.8f" % float(np.asarray(out).reshape(-1)[0]),
              flush=True)
        if os.environ.get("ELASTIC_PAYLOAD_STEP_HASH"):
            print("shash:step=%d world=%d h=%s"
                  % (step, member.world, state_hash()), flush=True)
        step += 1
        member.maybe_save(step)
    print("done: rank=%d epoch=%d world=%d"
          % (member.rank, member.epoch, member.world), flush=True)
    member.finalize()


if __name__ == "__main__":
    main()
