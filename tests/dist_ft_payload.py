"""Fault-tolerance distributed payload (dist_fc_payload topology + the FT
runtime pieces): every trainer step is checkpointed through
io.CheckpointManager, and trainer 1 optionally SIGKILLs itself mid-round via
a fault-injection spec.  Run under distributed/launch.py --restart_failed
the killed trainer comes back, restores from its latest valid checkpoint,
rejoins the cluster at its CURRENT round, and finishes the job.

Env contract (on top of the PADDLE_* cluster vars):
- PADDLE_CKPT_DIR      — checkpoint root; each trainer uses a per-tid subdir
- PADDLE_FT_KILL=1     — arm ``rpc.send:kill`` on trainer 1's FIRST life
                         (dies during step 5's gradient sends: after the
                         heartbeat, before the round completes)
- PADDLE_RESTART_COUNT — set by the launcher; >0 means this is a relaunch,
                         so restore instead of arming the kill again
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.utils import fault_injection as fi

from dist_fc_payload import BS, STEPS, build, make_data, run_pserver


def run_trainer():
    eps = os.environ["PADDLE_PSERVER_ENDPOINTS"]
    tid = int(os.environ["PADDLE_TRAINER_ID"])
    n_trainers = int(os.environ["PADDLE_TRAINERS_NUM"])
    restart_count = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
    ckpt_dir = os.path.join(os.environ["PADDLE_CKPT_DIR"],
                            "trainer-%d" % tid)
    main, startup, loss = build()
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=tid, program=main, startup_program=startup,
                pservers=eps, trainers=n_trainers)
    tp = t.get_trainer_program()
    xs, ys = make_data(n_trainers)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    mgr = fluid.io.CheckpointManager(ckpt_dir, save_interval=1, max_num=2)
    with fluid.scope_guard(scope):
        exe.run(startup)
        start_step = 0
        if restart_count > 0:
            # relaunched life: resume the step counter from the newest
            # valid checkpoint (params themselves are re-pulled from the
            # pservers at the cluster's current round on the first run)
            start_step, _ = mgr.restore(exe, tp)
            print("resumed_from:%d" % start_step, flush=True)
        elif os.environ.get("PADDLE_FT_KILL") == "1" and tid == 1:
            # 5 rpc.send checks per step (1 hb + 4 grads: w1/w2 and the two
            # fc biases, single pserver); skip=21 → SIGKILL on check 22 =
            # step 5's second grad send — after the heartbeat and a partial
            # gradient set, squarely mid-round
            fi.arm("rpc.send:kill:1:1:21")
        half = slice(tid * BS, (tid + 1) * BS)
        final = None
        for i in range(start_step, STEPS):
            lo, = exe.run(tp, feed={"x": xs[i][half], "y": ys[i][half]},
                          fetch_list=[loss], scope=scope)
            final = float(np.asarray(lo).reshape(-1)[0])
            print("loss:%.8f" % final, flush=True)
            mgr.save(exe, tp, i + 1)
        print("final_loss:%.8f" % final, flush=True)
        scope._ps_comm.complete()


if __name__ == "__main__":
    role = os.environ.get("PADDLE_TRAINING_ROLE", "LOCAL")
    if role == "PSERVER":
        run_pserver()
    else:
        run_trainer()
