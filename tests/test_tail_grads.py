"""Numeric-vs-analytic gradient checks for the differentiable
coverage-tail ops (completing round-2 verdict item 1's "OpTest goldens +
grad checks": forward goldens live in test_op_tail_goldens.py; these
verify the auto-vjp grads against central finite differences)."""

import numpy as np

from op_test import OpTest


def _sigmoid(v):
    return 1.0 / (1.0 + np.exp(-v))


def _np_gru(proj, wh):
    B, T, D3 = proj.shape
    D = D3 // 3
    h = np.zeros((B, D), "f")
    hs = np.zeros((B, T, D), "f")
    for t in range(T):
        ur = proj[:, t, :2 * D] + h @ wh[:, :2 * D]
        u, r = _sigmoid(ur[:, :D]), _sigmoid(ur[:, D:])
        c = np.tanh(proj[:, t, 2 * D:] + (r * h) @ wh[:, 2 * D:])
        h = u * h + (1 - u) * c
        hs[:, t] = h
    return hs


def _np_lstm(proj, wh):
    B, T, D4 = proj.shape
    D = D4 // 4
    h = np.zeros((B, D), "f")
    c = np.zeros((B, D), "f")
    hs = np.zeros((B, T, D), "f")
    for t in range(T):
        g = proj[:, t] + h @ wh
        i, f = _sigmoid(g[:, :D]), _sigmoid(g[:, D:2 * D])
        cand = np.tanh(g[:, 2 * D:3 * D])
        o = _sigmoid(g[:, 3 * D:])
        c = f * c + i * cand
        h = o * np.tanh(c)
        hs[:, t] = h
    return hs


class TestFusionGruGrad(OpTest):
    op_type = "fusion_gru"

    def setup_method(self, m):
        rng = np.random.RandomState(0)
        B, T, F, D = 2, 4, 3, 2
        x = rng.uniform(-1, 1, (B, T, F)).astype("f")
        wx = rng.uniform(-0.5, 0.5, (F, 3 * D)).astype("f")
        wh = rng.uniform(-0.5, 0.5, (D, 3 * D)).astype("f")
        self.inputs = {"X": x, "WeightX": wx, "WeightH": wh}
        self.attrs = {}
        self.outputs = {"Hidden": _np_gru(x @ wx, wh)}

    def test_grad(self):
        self.check_grad(["X", "WeightX", "WeightH"],
                        output_names="Hidden", max_relative_error=0.02)


class TestFusionLstmGrad(OpTest):
    op_type = "fusion_lstm"

    def setup_method(self, m):
        rng = np.random.RandomState(1)
        B, T, F, D = 2, 3, 3, 2
        x = rng.uniform(-1, 1, (B, T, F)).astype("f")
        wx = rng.uniform(-0.5, 0.5, (F, 4 * D)).astype("f")
        wh = rng.uniform(-0.5, 0.5, (D, 4 * D)).astype("f")
        self.inputs = {"X": x, "WeightX": wx, "WeightH": wh}
        self.attrs = {}
        self.outputs = {"Hidden": _np_lstm(x @ wx, wh)}

    def test_grad(self):
        self.check_grad(["X", "WeightX", "WeightH"],
                        output_names="Hidden", max_relative_error=0.02)


class TestFusedElemwiseActivationGrad(OpTest):
    op_type = "fused_elemwise_activation"

    def setup_method(self, m):
        rng = np.random.RandomState(2)
        x = rng.uniform(0.2, 1.0, (3, 4)).astype("f")
        y = rng.uniform(0.2, 1.0, (3, 4)).astype("f")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"functor_list": ["tanh", "elementwise_add"]}
        self.outputs = {"Out": np.tanh(x + y),
                        "IntermediateOut": x + y}

    def test_grad(self):
        self.check_grad(["X", "Y"], output_names="Out")


class TestFusionRepeatedFcReluGrad(OpTest):
    op_type = "fusion_repeated_fc_relu"

    def setup_method(self, m):
        rng = np.random.RandomState(3)
        x = rng.uniform(0.1, 1, (3, 4)).astype("f")
        w1 = rng.uniform(0.1, 0.5, (4, 5)).astype("f")
        b1 = rng.uniform(0.1, 0.2, (5,)).astype("f")
        w2 = rng.uniform(0.1, 0.5, (5, 2)).astype("f")
        b2 = rng.uniform(0.1, 0.2, (2,)).astype("f")
        h1 = np.maximum(x @ w1 + b1, 0.0)
        out = np.maximum(h1 @ w2 + b2, 0.0)
        self.inputs = {"X": x, "W": [("gw1", w1), ("gw2", w2)],
                       "Bias": [("gb1", b1), ("gb2", b2)]}
        self.attrs = {}
        self.outputs = {"ReluOut": [("gr1", h1)], "Out": out}

    def test_grad(self):
        # positive-orthant inputs keep relu away from its kink (finite
        # differences are ill-defined there)
        self.check_grad(["X"], output_names="Out")


class TestSequenceScatterGrad(OpTest):
    op_type = "sequence_scatter"

    def setup_method(self, m):
        rng = np.random.RandomState(4)
        x = rng.uniform(-1, 1, (2, 6)).astype("f")
        ids = np.asarray([[1, 3, 5], [0, 2, 4]], np.int64)
        upd = rng.uniform(-1, 1, (2, 3)).astype("f")
        want = x.copy()
        for b in range(2):
            for t in range(3):
                want[b, ids[b, t]] += upd[b, t]
        self.inputs = {"X": x, "Ids": ids, "Updates": upd}
        self.attrs = {}
        self.outputs = {"Out": want}

    def test_output(self):
        self.check_output()

    def test_grad(self):
        self.check_grad(["X", "Updates"], output_names="Out")


class TestMatchMatrixTensorGrad(OpTest):
    op_type = "match_matrix_tensor"

    def setup_method(self, m):
        rng = np.random.RandomState(5)
        B, Tx, Ty, D1, D2, dim_t = 2, 3, 3, 2, 2, 2
        x = rng.uniform(-1, 1, (B, Tx, D1)).astype("f")
        y = rng.uniform(-1, 1, (B, Ty, D2)).astype("f")
        w = rng.uniform(-0.5, 0.5, (D1, dim_t, D2)).astype("f")
        out = np.einsum("bid,dte,bje->btij", x, w, y).reshape(B, -1)
        tmp = np.einsum("bid,dte->bite", x, w).reshape(B, -1)
        self.inputs = {"X": x, "Y": y, "W": w.reshape(D1, -1)}
        self.attrs = {"dim_t": dim_t}
        self.outputs = {"Out": out, "Tmp": tmp}

    def test_grad(self):
        self.check_grad(["X", "Y", "W"], output_names="Out")


class TestFusedFcElementwiseLayernormGrad(OpTest):
    op_type = "fused_fc_elementwise_layernorm"

    def setup_method(self, m):
        rng = np.random.RandomState(6)
        B, F, D = 3, 4, 5
        x = rng.uniform(-1, 1, (B, F)).astype("f")
        w = rng.uniform(-0.5, 0.5, (F, D)).astype("f")
        y = rng.uniform(-1, 1, (B, D)).astype("f")
        z = x @ w + y
        mu = z.mean(1, keepdims=True)
        var = z.var(1, keepdims=True)
        out = (z - mu) / np.sqrt(var + 1e-5)
        self.inputs = {"X": x, "W": w, "Y": y}
        self.attrs = {"epsilon": 1e-5}
        self.outputs = {"Out": out}

    def test_output(self):
        self.check_output(no_check_set=("Mean", "Variance"))

    def test_grad(self):
        self.check_grad(["X", "W", "Y"], output_names="Out",
                        max_relative_error=0.02)


class TestRowConvGrad(OpTest):
    op_type = "row_conv"

    def setup_method(self, m):
        rng = np.random.RandomState(7)
        B, T, D, Fut = 2, 5, 3, 2
        x = rng.uniform(-1, 1, (B, T, D)).astype("f")
        w = rng.uniform(-0.5, 0.5, (Fut + 1, D)).astype("f")
        pad = np.concatenate([x, np.zeros((B, Fut, D), "f")], 1)
        want = sum(pad[:, i:i + T] * w[i] for i in range(Fut + 1))
        self.inputs = {"X": x, "Filter": w}
        self.attrs = {}
        self.outputs = {"Out": want}

    def test_grad(self):
        self.check_grad(["X", "Filter"], output_names="Out")


class TestCudnnLstmGrad(OpTest):
    op_type = "cudnn_lstm"

    def setup_method(self, m):
        rng = np.random.RandomState(8)
        B, T, F, D = 2, 3, 3, 2
        x = rng.uniform(-1, 1, (B, T, F)).astype("f")
        wx = rng.uniform(-0.5, 0.5, (F, 4 * D)).astype("f")
        wh = rng.uniform(-0.5, 0.5, (D, 4 * D)).astype("f")
        bx = rng.uniform(-0.2, 0.2, (4 * D,)).astype("f")
        bh = rng.uniform(-0.2, 0.2, (4 * D,)).astype("f")
        blob = np.concatenate([wx.ravel(), wh.ravel(), bx, bh])
        proj = x @ wx + (bx + bh).reshape(1, 1, -1)
        self.inputs = {"Input": x, "W": blob}
        self.attrs = {"hidden_size": D, "num_layers": 1}
        self.outputs = {"Out": _np_lstm(proj.astype("f"), wh)}

    def test_grad(self):
        self.check_grad(["Input", "W"], output_names="Out",
                        max_relative_error=0.02)
