"""Small import-path compat shims for reference-internal modules user code
occasionally imports (python/paddle/fluid/{log_helper, wrapped_decorator,
annotations, default_scope_funcs, op, data_feed_desc, trainer_desc,
trainer_factory, device_worker, executor, parallel_executor,
communicator, dygraph_grad_clip}.py).  Each is registered in sys.modules
as paddle_tpu.<name> pointing at the live implementation or a faithful
mini-module."""

import contextlib
import functools
import logging
import sys
import types


def _module(name):
    m = types.ModuleType("paddle_tpu." + name)
    sys.modules["paddle_tpu." + name] = m
    return m


# -- log_helper --------------------------------------------------------------
_log = _module("log_helper")


def get_logger(name, level, fmt=None):
    logger = logging.getLogger(name)
    logger.setLevel(level)
    if not logger.handlers:
        h = logging.StreamHandler()
        if fmt:
            h.setFormatter(logging.Formatter(fmt))
        logger.addHandler(h)
    logger.propagate = False
    return logger


_log.get_logger = get_logger

# -- wrapped_decorator -------------------------------------------------------
_wd = _module("wrapped_decorator")


def wrap_decorator(decorator_func):
    @functools.wraps(decorator_func)
    def _decorate(func):
        return functools.wraps(func)(decorator_func(func))

    return _decorate


def signature_safe_contextmanager(func):
    return functools.wraps(func)(contextlib.contextmanager(func))


_wd.wrap_decorator = wrap_decorator
_wd.signature_safe_contextmanager = signature_safe_contextmanager

# -- annotations -------------------------------------------------------------
_ann = _module("annotations")


def deprecated(since, instead, extra_message=""):
    def decorator(func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            import warnings

            warnings.warn(
                "%s is deprecated since %s, use %s instead. %s"
                % (func.__name__, since, instead, extra_message),
                DeprecationWarning)
            return func(*args, **kwargs)

        return wrapper

    return decorator


_ann.deprecated = deprecated

# -- default_scope_funcs -----------------------------------------------------
_dsf = _module("default_scope_funcs")


def _wire_scope_funcs():
    from .core import executor as _exe

    _dsf.get_cur_scope = _exe.global_scope
    _dsf.scoped_function = lambda fn: fn()
    _dsf.find_var = lambda name: _exe.global_scope().find_var(name)
    _dsf.var = lambda name: _exe.global_scope().var(name)


_wire_scope_funcs()

# -- module aliases to live implementations ---------------------------------


def _alias(name, target_module):
    sys.modules["paddle_tpu." + name] = target_module


class Communicator:
    """Async-PS communicator facade (reference
    python/paddle/fluid/communicator.py): the actual send/merge threads
    live in the runtime PS communicator (distributed/ps.py TrainerPSComm,
    driven by the executor at step boundaries), so start/stop only track
    state for API parity."""

    def __init__(self, program=None, mode=None, **kwargs):
        self._program = program
        self._running = False

    def start(self):
        self._running = True

    def stop(self):
        self._running = False

    def is_running(self):
        return self._running


def wire_aliases():
    """Called at the end of paddle_tpu/__init__ once the real modules
    exist.  Each alias carries the canonical symbols the reference import
    path exports."""
    import paddle_tpu as _p

    from . import trainer as _trainer
    from .core import executor as _core_exe

    _alias("executor", _core_exe)
    _alias("trainer_factory", _trainer)
    _alias("trainer_desc", _trainer)
    _alias("device_worker", _trainer)

    # data_feed_desc.DataFeedDesc: the class lives on the package root
    # (defined after this call runs) — resolve lazily via PEP 562
    dfd = _module("data_feed_desc")
    dfd.__dict__["__getattr__"] = (
        lambda name: getattr(__import__("paddle_tpu"), name))

    comm = _module("communicator")
    comm.Communicator = Communicator

    from . import clip as _clip

    _alias("dygraph_grad_clip", _clip)
    from . import debugger as _dbg

    _alias("graphviz", _dbg)
    nd = _module("net_drawer")
    nd.draw_block_graphviz = _dbg.draw_block_graphviz

    def draw_graph(startup_program, main_program, **kwargs):
        """net_drawer.py:draw_graph: dot-file dump of the main block."""
        path = kwargs.get("graph_path", "./graph.dot")
        return _dbg.draw_block_graphviz(main_program.global_block(),
                                        path=path)

    nd.draw_graph = draw_graph
