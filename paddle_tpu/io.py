"""Checkpointing & inference-model save/load.

Parity: python/paddle/fluid/io.py (save_vars:149, save_persistables:523,
load_vars:588, load_persistables:801, save_inference_model:1011,
load_inference_model:1215) + the save/load ops (operators/save_op.h).
Format: one .npz per var-set + a JSON program desc (instead of the
reference's per-var binary streams + __model__ protobuf).

Crash safety: every writer here goes through write-temp-then-atomic-rename
(_atomic_write / LocalFS.atomic_write_dir) and checks the ``ckpt.write``
fault point between the temp write and the rename, so a process killed
mid-save can never leave a torn file under the final name — the previous
checkpoint survives intact.  CheckpointManager adds the rolling-directory
layer: save every N steps, keep the last K, and ``latest_valid()`` trusts
only directories whose ``_SUCCESS`` manifest exists and whose content
checksums match (parity target: the incubate fleet checkpoint utilities'
_SUCCESS convention, python/paddle/fluid/incubate/fleet/utils/fleet_util.py).
"""

import json
import logging
import os
import queue
import re
import threading
import time
import zlib

import numpy as np

from . import flags as _flags
from .core.executor import global_scope
from .framework import Parameter, Program, Variable
from .utils.fault_injection import maybe_fail
from .utils.fs import LocalFS

__all__ = [
    "CheckpointManager",
    "shard_read_plan",
    "DataLoader",
    "PyReader",
    "save_vars",
    "save_params",
    "save_persistables",
    "load_vars",
    "load_params",
    "load_persistables",
    "save_inference_model",
    "load_inference_model",
    "save_train_model",
    "load_train_model",
    "save",
    "load",
    "load_program_state",
    "set_program_state",
]


def __getattr__(name):  # lazy: io imports before reader in __init__
    if name in ("DataLoader", "PyReader"):
        from . import reader

        return getattr(reader, name)
    raise AttributeError(name)


def _is_persistable(var):
    return var.persistable and not var.is_data


def _is_parameter(var):
    return isinstance(var, Parameter)


def _atomic_write(path, write_fn, mode="wb"):
    """Write via temp file + os.replace so the final `path` is only ever
    complete or absent.  The ``ckpt.write`` fault point sits between the
    two: an injected kill tears only the temp file (crash-safety tests)."""
    tmp = "%s._tmp.%d" % (path, os.getpid())
    try:
        with open(tmp, mode) as f:
            write_fn(f)
        maybe_fail("ckpt.write")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def _gather(executor, dirname, program, predicate, filename):
    program = program or _default_main()
    scope = global_scope()
    out = {}
    for var in program.list_vars():
        if not predicate(var):
            continue
        sv = scope.find_var(var.name)
        if sv is None or not sv.get_tensor()._is_initialized():
            continue
        out[var.name] = np.asarray(sv.get_tensor().numpy())
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, filename or "__params__.npz")
    _atomic_write(path, lambda f: np.savez(f, **out))
    return path


def _default_main():
    from .framework import default_main_program

    return default_main_program()


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    if vars is not None:
        names = {v.name if isinstance(v, Variable) else v for v in vars}
        predicate = lambda v: v.name in names  # noqa: E731
    return _gather(executor, dirname, main_program, predicate, filename)


def save_params(executor, dirname, main_program=None, filename=None):
    return _gather(executor, dirname, main_program, _is_parameter, filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    return _gather(executor, dirname, main_program, _is_persistable, filename)


def _scatter(executor, dirname, program, predicate, filename):
    program = program or _default_main()
    scope = global_scope()
    path = os.path.join(dirname, filename or "__params__.npz")
    data = np.load(path, allow_pickle=False)
    loaded = 0
    for var in program.list_vars():
        if not predicate(var):
            continue
        if var.name in data.files:
            scope.var(var.name).set(data[var.name])
            loaded += 1
    return loaded


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    if vars is not None:
        names = {v.name if isinstance(v, Variable) else v for v in vars}
        predicate = lambda v: v.name in names  # noqa: E731
    return _scatter(executor, dirname, main_program, predicate, filename)


def load_params(executor, dirname, main_program=None, filename=None):
    return _scatter(executor, dirname, main_program, _is_parameter, filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    return _scatter(executor, dirname, main_program, _is_persistable, filename)


def _prune_for_inference(program, feed_names, target_names):
    """Keep only ops needed to compute targets from feeds (reference
    prune.cc): backward slice over the op list, dropping
    backward/optimize-role ops."""
    from .framework import OP_ROLE_KEY, OpRole

    block = program.global_block()
    needed = set(target_names)
    keep = []
    for op in reversed(block.ops):
        role = op.attr(OP_ROLE_KEY) or 0
        if int(role) & (OpRole.Backward | OpRole.Optimize):
            continue
        if int(role) == OpRole.LRSched:
            continue
        outs = [n for n in op.output_arg_names if n]
        if not any(n in needed for n in outs):
            continue
        keep.append(op)
        for n in op.input_arg_names:
            if n:
                needed.add(n)
    keep.reverse()
    pruned = program.clone(for_test=True)
    pb = pruned.global_block()
    kept_keys = {(op.type, json.dumps(op.inputs, sort_keys=True),
                  json.dumps(op.outputs, sort_keys=True)) for op in keep}
    pb.ops = [
        op for op in pb.ops
        if (op.type, json.dumps(op.inputs, sort_keys=True),
            json.dumps(op.outputs, sort_keys=True)) in kept_keys
    ]
    pruned._bump_version()
    return pruned


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False, legacy_format=False):
    """``legacy_format=True`` writes the reference's on-disk format
    (``__model__`` ProgramDesc protobuf + LoDTensor param streams,
    framework.proto:212 / lod_tensor.cc:219) so a reference install can load
    the directory; default is the JSON IR."""
    program = main_program or _default_main()
    target_names = [v.name if isinstance(v, Variable) else v for v in target_vars]
    pruned = _prune_for_inference(program, feeded_var_names, target_names)
    os.makedirs(dirname, exist_ok=True)
    if legacy_format:
        _save_legacy_model(dirname, feeded_var_names, target_names, pruned,
                           model_filename, params_filename,
                           program_only=program_only)
        return target_names
    model = {
        "program": pruned.to_dict(),
        "feed_names": list(feeded_var_names),
        "fetch_names": target_names,
    }
    with open(os.path.join(dirname, model_filename or "__model__.json"), "w") as f:
        json.dump(model, f)
    if not program_only:
        save_persistables(executor, dirname, pruned, params_filename)
    return target_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    """Loads either our JSON IR or a reference-saved directory (``__model__``
    ProgramDesc protobuf + per-var / combined LoDTensor streams).  The format
    is sniffed from the file content, so an explicit model_filename works for
    both."""
    if model_filename is not None:
        candidates = [os.path.join(dirname, model_filename)]
    else:
        candidates = [os.path.join(dirname, "__model__.json"),
                      os.path.join(dirname, "__model__")]
    path = next((p for p in candidates if os.path.exists(p)), candidates[0])
    from . import proto_compat

    with open(path, "rb") as f:
        head = f.read(1)
    if proto_compat.is_program_desc(head):
        return _load_legacy_model(dirname, path, params_filename)
    with open(path) as f:
        model = json.load(f)
    program = Program.from_dict(model["program"])
    try:
        load_persistables(executor, dirname, program, params_filename)
    except FileNotFoundError:
        pass
    fetch_vars = [program.global_block().var(n) for n in model["fetch_names"]]
    return program, model["feed_names"], fetch_vars


def _strip_feed_fetch(prog_dict):
    """Remove reference-style feed/fetch plumbing ops from a parsed program
    (reference load_inference_model keeps them and its executor consumes
    them; our executor feeds/fetches by var name).  Returns
    (feed_names by col, fetch_names by col)."""
    feeds, fetches = {}, {}
    for bd in prog_dict["blocks"]:
        kept = []
        for od in bd["ops"]:
            if od["type"] == "feed":
                col = od["attrs"].get("col", len(feeds))
                feeds[col] = od["outputs"]["Out"][0]
            elif od["type"] == "fetch":
                col = od["attrs"].get("col", len(fetches))
                fetches[col] = od["inputs"]["X"][0]
            else:
                kept.append(od)
        bd["ops"] = kept
        bd["vars"] = [v for v in bd["vars"]
                      if v["name"] not in ("feed", "fetch")]
    return ([feeds[k] for k in sorted(feeds)],
            [fetches[k] for k in sorted(fetches)])


def _load_legacy_model(dirname, model_path, params_filename):
    from . import proto_compat

    with open(model_path, "rb") as f:
        prog_dict = proto_compat.parse_program_desc(f.read())
    feed_names, fetch_names = _strip_feed_fetch(prog_dict)
    program = Program.from_dict(prog_dict)
    block = program.global_block()
    # mark data vars so executors treat feeds normally
    for n in feed_names:
        if block.has_var(n):
            block.var(n).is_data = True
    scope = global_scope()
    persistables = sorted(
        v.name for v in program.list_vars()
        if v.persistable and not v.is_data and v.type == "lod_tensor")
    if params_filename is not None:
        with open(os.path.join(dirname, params_filename), "rb") as f:
            # combine format: one stream per var, sorted by name
            # (reference io.py:718 loads sorted(load_var_map))
            for name in persistables:
                arr, _lod = proto_compat.read_lod_tensor(f)
                scope.var(name).set(arr)
    else:
        for name in persistables:
            path = os.path.join(dirname, name)
            if not os.path.exists(path):
                raise FileNotFoundError(
                    "parameter file %r missing from legacy model dir %s"
                    % (name, dirname))
            with open(path, "rb") as f:
                arr, _lod = proto_compat.read_lod_tensor(f)
            scope.var(name).set(arr)
    fetch_vars = [block.var(n) for n in fetch_names]
    return program, feed_names, fetch_vars


def _save_legacy_model(dirname, feed_names, fetch_names, pruned,
                       model_filename, params_filename, program_only=False):
    from . import proto_compat

    prog_dict = pruned.to_dict()
    b0 = prog_dict["blocks"][0]
    # reference-style plumbing: feed/fetch vars + ops with col attrs
    b0["vars"].append({"name": "feed", "shape": None, "dtype": None,
                       "lod_level": 0, "persistable": True,
                       "stop_gradient": True, "type": "feed_minibatch",
                       "is_data": False, "is_parameter": False})
    b0["vars"].append({"name": "fetch", "shape": None, "dtype": None,
                       "lod_level": 0, "persistable": True,
                       "stop_gradient": True, "type": "fetch_list",
                       "is_data": False, "is_parameter": False})
    feed_ops = [{"type": "feed", "inputs": {"X": ["feed"]},
                 "outputs": {"Out": [n]}, "attrs": {"col": i}}
                for i, n in enumerate(feed_names)]
    fetch_ops = [{"type": "fetch", "inputs": {"X": [n]},
                  "outputs": {"Out": ["fetch"]}, "attrs": {"col": i}}
                 for i, n in enumerate(fetch_names)]
    b0["ops"] = feed_ops + b0["ops"] + fetch_ops
    with open(os.path.join(dirname, model_filename or "__model__"),
              "wb") as f:
        f.write(proto_compat.serialize_program_desc(prog_dict))
    if program_only:
        return
    scope = global_scope()
    persistables = sorted(
        v.name for v in pruned.list_vars()
        if v.persistable and not v.is_data and v.type == "lod_tensor")
    arrays = {}
    for name in persistables:
        sv = scope.find_var(name)
        if sv is None or not sv.get_tensor()._is_initialized():
            # a silent skip would misalign the combined stream against the
            # loader's sorted(persistables) walk (reference save raises too)
            raise RuntimeError(
                "persistable variable %r is not initialized in scope; run "
                "the startup program before save_inference_model" % name)
        arrays[name] = np.asarray(sv.get_tensor().numpy())
    if params_filename is not None:
        with open(os.path.join(dirname, params_filename), "wb") as f:
            for name in sorted(arrays):
                proto_compat.write_lod_tensor(f, arrays[name])
    else:
        for name, arr in arrays.items():
            with open(os.path.join(dirname, name), "wb") as f:
                proto_compat.write_lod_tensor(f, arr)


def save_train_model(dirname, feed_names, fetch_vars, executor,
                     main_program=None, startup_program=None):
    """Save a full *training* bundle (main + startup programs + names) for
    the standalone C++ trainer (parity: the reference's
    train/demo workflow, which saves main/startup ProgramDescs via
    fluid.io and loads them from C++, train/demo/demo_trainer.cc:25-45)."""
    from .framework import default_startup_program

    main = main_program or _default_main()
    startup = startup_program or default_startup_program()
    fetch_names = [v.name if isinstance(v, Variable) else v
                   for v in fetch_vars]
    os.makedirs(dirname, exist_ok=True)
    bundle = {
        "main_program": main.to_dict(),
        "startup_program": startup.to_dict(),
        "feed_names": list(feed_names),
        "fetch_names": fetch_names,
    }
    with open(os.path.join(dirname, "__train_model__.json"), "w") as f:
        json.dump(bundle, f)
    # persist current params too so training can resume (optional at load)
    if executor is not None:
        save_persistables(executor, dirname, main)
    return fetch_names


def load_train_model(dirname, executor=None):
    """Load a bundle saved by save_train_model ->
    (main, startup, feed_names, fetch_names)."""
    with open(os.path.join(dirname, "__train_model__.json")) as f:
        bundle = json.load(f)
    main = Program.from_dict(bundle["main_program"])
    startup = Program.from_dict(bundle["startup_program"])
    return main, startup, bundle["feed_names"], bundle["fetch_names"]


# -- crash-safe rolling checkpoints ------------------------------------------

_SUCCESS_NAME = "_SUCCESS"


def _file_crc32(path, chunk=1 << 20):
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return crc
            crc = zlib.crc32(b, crc)


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:  # EPERM etc: exists but not ours
        return True
    return True


# how long rank 0 waits for peer shard parts before failing the save
_SHARD_WAIT_S = 60.0

_TMP_RE = re.compile(r"\._tmp\.(\d+)$")
_SHARD_FILE = "__shard_%dof%d__.npz"


def shard_read_plan(manifest, new_world):
    """Partition a sharded manifest's per-rank shard files across a new
    world so each file is read by EXACTLY ONE new rank (the world-4 -> 2
    restore reads each tensor once across ranks, not N full copies).
    Contiguous blocks: new rank r gets the old shards covering its row
    range.  -> {new_rank: [old_rank, ...]}"""
    old_world = int((manifest.get("shards") or {}).get("world", 1))
    new_world = int(new_world)
    plan = {r: [] for r in range(new_world)}
    for old in range(old_world):
        plan[min((old * new_world) // old_world, new_world - 1)].append(old)
    return plan


class CheckpointManager:
    """Rolling crash-safe checkpoints under ``ckpt_dir/ckpt-<step>``.

    Each checkpoint directory is materialized through
    LocalFS.atomic_write_dir (temp dir -> atomic rename) and carries a
    ``_SUCCESS`` manifest — written LAST — recording the step, optional
    user extra state, and a crc32 per file.  ``latest_valid()`` walks steps
    descending and returns the newest directory whose manifest exists and
    verifies, silently skipping torn/partial saves (a SIGKILL mid-save, a
    crashed rename window, a truncated npz).  Retention keeps the newest
    ``max_num`` checkpoints.

    Typical supervised-relaunch flow (distributed/launch.py
    --restart_failed): the trainer calls ``maybe_save`` every step; after
    a crash the relaunched process calls ``restore`` and resumes from the
    returned step instead of 0.

    Async save (``FLAGS_checkpoint_async`` / ``async_save=True``): the
    step-path cost of ``save`` collapses to one D2H snapshot
    (Executor.snapshot_state); serialization, crc32, and the sealed
    directory write run on a background writer thread.  At most one write
    is in flight — a save landing while one is still writing is DROPPED
    loudly (warning + ``checkpoint_save_overlap_total``) rather than
    queued, so a slow disk can never stack snapshots in host RAM.
    ``checkpoint_save_stall_ms`` records the foreground stall,
    ``checkpoint_write_ms`` the background write.

    Sharded save (``FLAGS_checkpoint_sharded``, on by default): when the
    program carries zero1 collective meta with an exported
    ``ckpt_shard_layout``, each rank writes only its own dim-0 rows of the
    layout vars (``__shard_<r>of<w>__.npz`` staged under
    ``ckpt-<step>.parts/``); rank 0 writes the replicated vars, adopts the
    peer parts, and seals the manifest (which records the shard layout).
    ``restore`` reassembles — or, with ``shard_scope="local"``, re-shards —
    across world changes; :func:`shard_read_plan` partitions the shard
    files so a world change reads each file exactly once across ranks.
    """

    _PREFIX = "ckpt-"

    def __init__(self, ckpt_dir, save_interval=10, max_num=3, fs=None,
                 async_save=None, sharded=None):
        if int(save_interval) < 1:
            raise ValueError("save_interval must be >= 1")
        if int(max_num) < 1:
            raise ValueError("max_num must be >= 1")
        self.ckpt_dir = ckpt_dir
        self.save_interval = int(save_interval)
        self.max_num = int(max_num)
        self._fs = fs or LocalFS()
        if async_save is None:
            async_save = bool(_flags.flag("checkpoint_async"))
        if sharded is None:
            sharded = bool(_flags.flag("checkpoint_sharded"))
        self.async_save = bool(async_save)
        self.sharded = bool(sharded)
        # latest_valid() used to re-crc every candidate file on every
        # call — cache the verdict per directory stat signature instead
        self._valid_cache = {}
        # async writer: single-slot queue, one daemon thread, one in-flight
        self._idle = threading.Event()
        self._idle.set()
        self._queue = None
        self._writer = None
        self._write_err = None
        # spans for cross-tree links (elastic requorum restore phase)
        self.last_save_span = None
        self.last_restore_span = None

    # -- enumeration --------------------------------------------------------

    def _step_dirs(self):
        """Sorted [(step, path)] of plausible checkpoint dirs (validity is
        latest_valid's job)."""
        out = []
        for name in self._fs.ls_dir(self.ckpt_dir):
            if not name.startswith(self._PREFIX):
                continue
            try:
                step = int(name[len(self._PREFIX):])
            except ValueError:
                continue
            out.append((step, os.path.join(self.ckpt_dir, name)))
        return sorted(out)

    def _manifest(self, path):
        try:
            with open(os.path.join(path, _SUCCESS_NAME)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _verify(self, path):
        man = self._manifest(path)
        if man is None:
            return False
        for fname, crc in man.get("files", {}).items():
            fpath = os.path.join(path, fname)
            try:
                if _file_crc32(fpath) != crc:
                    return False
            except OSError:
                return False
        return True

    def _dir_sig(self, path):
        """Stat signature of every file in the checkpoint dir (name, mtime,
        size) — None when the dir or its _SUCCESS is unreadable."""
        if not os.path.exists(os.path.join(path, _SUCCESS_NAME)):
            return None
        try:
            sig = []
            for name in sorted(os.listdir(path)):
                st = os.stat(os.path.join(path, name))
                sig.append((name, st.st_mtime_ns, st.st_size))
            return tuple(sig)
        except OSError:
            return None

    def _is_valid(self, path):
        """_verify with a per-(path, dir stat signature) cache so elastic
        re-quorum doesn't pay a full-directory hash walk per adoption.  A
        sealed directory is immutable, so the stat walk (mtime+size of every
        file) is a sound cache key — any rewrite, replace, or in-place
        tamper changes it; the crc walk runs only on a signature miss."""
        sig = self._dir_sig(path)
        if sig is None:
            self._valid_cache.pop(path, None)
            return False
        hit = self._valid_cache.get(path)
        if hit is not None and hit[0] == sig:
            return hit[1]
        ok = self._verify(path)
        self._valid_cache[path] = (sig, ok)
        return ok

    def latest_valid(self):
        """-> (step, path) of the newest checkpoint whose _SUCCESS manifest
        verifies, or None when no usable checkpoint exists.  Waits out any
        in-flight background write first so an async save just submitted is
        visible to the caller."""
        self._idle.wait()
        for step, path in reversed(self._step_dirs()):
            if self._is_valid(path):
                return step, path
        return None

    # -- write side ---------------------------------------------------------

    def _snapshot(self, executor, program):
        """D2H host-copy of the persistable state — the only step-path cost
        of an async save.  Prefers Executor.snapshot_state (one device_get
        per tensor, traced); degrades to a direct scope walk for bare
        executors (tests, legacy callers)."""
        if hasattr(executor, "snapshot_state"):
            return executor.snapshot_state(program or _default_main())
        scope = global_scope()
        out = {}
        for var in (program or _default_main()).list_vars():
            if not _is_persistable(var):
                continue
            sv = scope.find_var(var.name)
            if sv is None or not sv.get_tensor()._is_initialized():
                continue
            out[var.name] = np.array(sv.get_tensor().numpy(), copy=True)
        return out

    def _shard_plan(self, program):
        """-> {"rank","world","layout"} when this program runs zero1 with an
        exported checkpoint shard layout and sharded save is on, else None
        (plain full-state save)."""
        if not self.sharded or program is None:
            return None
        meta = getattr(program, "_collective_meta", None)
        if not meta or meta.get("mode") != "zero1":
            return None
        world = int(meta.get("nranks") or 1)
        layout = meta.get("ckpt_shard_layout") or {}
        if world <= 1 or not layout:
            return None
        return {"rank": int(meta.get("rank") or 0), "world": world,
                "layout": layout}

    def save(self, executor, program, step, extra=None):
        """Write checkpoint ``ckpt-<step>`` (persistables + manifest) and
        prune beyond max_num.  Returns the checkpoint path — which, under
        async save, the background writer may still be sealing (call
        ``wait()`` to block on it); returns None when the save was dropped
        because a previous write is still in flight."""
        from .core import telemetry as _tm
        from .core import tracing as _tr

        t0 = time.perf_counter()
        mode = "async" if self.async_save else "sync"
        root = _tr.start_span("checkpoint.save", step=int(step), mode=mode)
        plan = self._shard_plan(program)
        target = os.path.join(self.ckpt_dir, "%s%d" % (self._PREFIX, step))
        if self.async_save and not self._idle.is_set():
            logging.warning(
                "checkpoint save at step %d dropped: previous background "
                "write still in flight (disk slower than save_interval?)",
                step)
            if _tm.enabled():
                _tm.inc("checkpoint_save_overlap_total")
            root.annotate(dropped=True).end()
            return None
        with _tr.activate(root):
            state = self._snapshot(executor, program)
        if self.async_save:
            self._submit(state, int(step), extra, plan, root)
        else:
            self._write_checkpoint(state, int(step), extra, plan,
                                   parent=root)
        root.end()
        self.last_save_span = root
        if _tm.enabled():
            stall = (time.perf_counter() - t0) * 1e3
            # checkpoint_save_ms keeps its historical meaning (foreground
            # cost of save()); the stall/write split is the async story
            _tm.observe("checkpoint_save_ms", stall)
            _tm.observe("checkpoint_save_stall_ms", stall)
            _tm.event("checkpoint_save", step=int(step),
                      ms=round(stall, 3), mode=mode)
        return target

    def maybe_save(self, executor, program, step, extra=None):
        """save() every save_interval steps (step counts from 1)."""
        if step and step % self.save_interval == 0:
            return self.save(executor, program, step, extra=extra)
        return None

    # -- background writer ---------------------------------------------------

    def _submit(self, state, step, extra, plan, root):
        if self._writer is None or not self._writer.is_alive():
            self._queue = queue.Queue(maxsize=1)
            self._writer = threading.Thread(target=self._writer_loop,
                                            name="ckpt-writer", daemon=True)
            self._writer.start()
        self._idle.clear()
        self._queue.put((state, step, extra, plan, root))

    def _writer_loop(self):
        while True:
            item = self._queue.get()
            if item is None:
                return
            state, step, extra, plan, root = item
            try:
                self._write_checkpoint(state, step, extra, plan, parent=root)
            except BaseException as e:  # surfaced by the next wait()
                logging.error("checkpoint background write for step %d "
                              "failed: %s", step, e)
                self._write_err = e
            finally:
                self._idle.set()

    def wait(self, timeout=None):
        """Block until no background write is in flight; re-raises a stashed
        writer failure.  -> True when idle (False on timeout)."""
        ok = self._idle.wait(timeout)
        err, self._write_err = self._write_err, None
        if err is not None:
            raise err
        return ok

    # -- serialization (runs on the writer thread under async save) ---------

    def _write_checkpoint(self, state, step, extra, plan, parent=None):
        from .core import telemetry as _tm
        from .core import tracing as _tr

        t0 = time.perf_counter()
        self._fs.mkdirs(self.ckpt_dir)
        target = os.path.join(self.ckpt_dir, "%s%d" % (self._PREFIX, step))
        with _tr.span("checkpoint.write", parent=parent, step=int(step)):
            if plan is None:
                with self._fs.atomic_write_dir(target) as tmp:
                    _atomic_write(os.path.join(tmp, "__params__.npz"),
                                  lambda f: np.savez(f, **state))
                    self._seal(tmp, step, extra, None)
            else:
                self._write_sharded(target, state, step, extra, plan)
            self._prune()
        if _tm.enabled():
            ms = (time.perf_counter() - t0) * 1e3
            _tm.observe("checkpoint_write_ms", ms)
            _tm.event("checkpoint_write", step=int(step), ms=round(ms, 3),
                      files=len(state))
        return target

    def _seal(self, tmp, step, extra, shards):
        """crc every file then write the _SUCCESS manifest LAST: its
        presence asserts every file above is complete."""
        files = {
            name: _file_crc32(os.path.join(tmp, name))
            for name in sorted(os.listdir(tmp))
            if name != _SUCCESS_NAME
        }
        manifest = {"step": int(step), "files": files}
        if shards is not None:
            manifest["shards"] = shards
        if extra is not None:
            manifest["extra"] = extra
        with open(os.path.join(tmp, _SUCCESS_NAME), "w") as f:
            json.dump(manifest, f)

    def _write_sharded(self, target, state, step, extra, plan):
        """zero1 multi-writer: rank r stages only its own dim-0 rows of the
        layout vars under ``<target>.parts/``; rank 0 writes the replicated
        vars + its shard, adopts peer parts, and seals.  A rank killed
        mid-part leaves only temp files / an unsealed parts dir — the
        previous checkpoint stays the latest valid one."""
        rank, world, layout = plan["rank"], plan["world"], plan["layout"]
        parts = target + ".parts"
        self._fs.mkdirs(parts)
        mine = {}
        for name, lay in layout.items():
            if name not in state:
                continue
            rpr = int(lay["rows_per_rank"])
            mine[name] = state[name][rank * rpr:(rank + 1) * rpr]
        fname = _SHARD_FILE % (rank, world)
        if rank != 0:
            path = os.path.join(parts, fname)
            _atomic_write(path, lambda f: np.savez(f, **mine))
            # .ok marker last: tells rank 0 the part is complete
            _atomic_write(path + ".ok", lambda f: json.dump(
                {"crc": _file_crc32(path)}, f), mode="w")
            return
        repl = {n: a for n, a in state.items() if n not in layout}
        with self._fs.atomic_write_dir(target) as tmp:
            _atomic_write(os.path.join(tmp, "__params__.npz"),
                          lambda f: np.savez(f, **repl))
            _atomic_write(os.path.join(tmp, fname),
                          lambda f: np.savez(f, **mine))
            deadline = time.monotonic() + _SHARD_WAIT_S
            for r in range(1, world):
                pf = os.path.join(parts, _SHARD_FILE % (r, world))
                while not os.path.exists(pf + ".ok"):
                    if time.monotonic() > deadline:
                        raise RuntimeError(
                            "sharded checkpoint step %d: rank %d part not "
                            "staged within %.0fs (%s)"
                            % (step, r, _SHARD_WAIT_S, pf))
                    time.sleep(0.02)
                os.replace(pf, os.path.join(tmp, _SHARD_FILE % (r, world)))
                os.remove(pf + ".ok")
            self._seal(tmp, step, extra, {
                "world": int(world),
                "layout": {n: {"dim0": int(lay["dim0"]),
                               "rows_per_rank": int(lay["rows_per_rank"])}
                           for n, lay in layout.items()}})
        self._fs.delete(parts)

    def _prune(self):
        dirs = self._step_dirs()
        for _, path in dirs[:-self.max_num]:
            self._fs.delete(path)
            self._valid_cache.pop(path, None)
        self._gc_stale_tmps()

    def _gc_stale_tmps(self):
        """Satellite GC: a SIGKILL mid-atomic_write_dir leaves
        ``<dir>._tmp.<pid>`` orphans (and a sharded save can leave a
        ``.parts`` staging dir) that keep-last-K pruning never touched.
        Temps owned by a live pid are spared — that's a concurrent writer."""
        from .core import telemetry as _tm

        removed = 0
        newest = max((s for s, _ in self._step_dirs()), default=None)
        for name in self._fs.ls_dir(self.ckpt_dir):
            full = os.path.join(self.ckpt_dir, name)
            m = _TMP_RE.search(name)
            if m:
                pid = int(m.group(1))
                if pid != os.getpid() and not _pid_alive(pid):
                    self._fs.delete(full)
                    removed += 1
                continue
            if name.startswith(self._PREFIX) and name.endswith(".parts"):
                base = name[:-len(".parts")]
                try:
                    step = int(base[len(self._PREFIX):])
                except ValueError:
                    continue
                sealed = os.path.join(self.ckpt_dir, base, _SUCCESS_NAME)
                if os.path.exists(sealed) or (newest is not None
                                              and step < newest):
                    self._fs.delete(full)
                    removed += 1
        if removed and _tm.enabled():
            _tm.inc("checkpoint_tmp_gc_total", removed)
        return removed

    # -- read side ----------------------------------------------------------

    def restore(self, executor, program, shard_scope="full", world=None,
                rank=None):
        """Load the newest valid checkpoint into the global scope.
        Returns (step, extra) — or (0, None) when nothing valid exists, so
        callers can resume their loop unconditionally from the result.

        Sharded checkpoints reassemble the full arrays by default (each
        shard file opened exactly once per process, any world).  With
        ``shard_scope="local"`` (+ ``world``/``rank`` overriding the
        program's collective meta) only the shard files overlapping this
        rank's dim-0 rows are read — the multi-process path where a world
        change reads each tensor once ACROSS ranks, per shard_read_plan."""
        from .core import telemetry as _tm
        from .core import tracing as _tr

        t0 = time.perf_counter()
        found = self.latest_valid()
        if found is None:
            return 0, None
        step, path = found
        man = self._manifest(path)
        with _tr.span("checkpoint.restore", step=int(step)) as root:
            if (man or {}).get("shards"):
                self._load_sharded(path, man, program, shard_scope,
                                   world, rank)
            else:
                load_persistables(executor, path, program)
        self.last_restore_span = root
        if _tm.enabled():
            ms = (time.perf_counter() - t0) * 1e3
            _tm.observe("checkpoint_restore_ms", ms)
            _tm.event("checkpoint_restore", step=int(step),
                      ms=round(ms, 3))
        return step, (man or {}).get("extra")

    def _load_sharded(self, path, man, program, shard_scope, world, rank):
        """Reassemble (or locally re-shard) a sharded checkpoint.  The scope
        holds FULL arrays for zero1 layout vars (the executor's sharding
        annotation re-slices them onto whatever mesh compiles), so "full"
        concatenates every shard; "local" fills only this rank's rows into
        the existing scope array and leaves the rest untouched."""
        program = program or _default_main()
        scope = global_scope()
        shards = man["shards"]
        old_world = int(shards["world"])
        layout = shards.get("layout") or {}
        names = {v.name for v in program.list_vars() if _is_persistable(v)}
        with np.load(os.path.join(path, "__params__.npz"),
                     allow_pickle=False) as data:
            for name in data.files:
                if name in names:
                    scope.var(name).set(data[name])
        wanted = [n for n in layout if n in names]
        if not wanted:
            return
        if shard_scope == "local":
            if world is None or rank is None:
                meta = getattr(program, "_collective_meta", None) or {}
                world = int(meta.get("nranks") or 1)
                rank = int(meta.get("rank") or 0)
            reads = shard_read_plan(man, world).get(int(rank), [])
        else:
            reads = list(range(old_world))
        pieces = {n: {} for n in wanted}
        for old in reads:  # each shard file opened exactly once
            sf = os.path.join(path, _SHARD_FILE % (old, old_world))
            with np.load(sf, allow_pickle=False) as sd:
                for n in wanted:
                    if n in sd.files:
                        pieces[n][old] = sd[n]
        for n in wanted:
            got = pieces[n]
            if not got:
                continue
            if shard_scope != "local":
                full = np.concatenate([got[o] for o in sorted(got)], axis=0)
                scope.var(n).set(full)
                continue
            rpr = int(layout[n]["rows_per_rank"])
            dim0 = int(layout[n]["dim0"])
            sample = next(iter(got.values()))
            sv = scope.find_var(n)
            if sv is not None and sv.get_tensor()._is_initialized():
                cur = np.array(sv.get_tensor().numpy(), copy=True)
            else:
                cur = np.zeros((dim0,) + sample.shape[1:], sample.dtype)
            for o, arr in got.items():
                cur[o * rpr:o * rpr + arr.shape[0]] = arr
            scope.var(n).set(cur)


# -- fluid.save / fluid.load (v1.6 single-call training state) ---------------

def _is_belong_to_optimizer(var):
    """Persistable non-Parameter state: optimizer accumulators, LR counters
    (reference io.py:109 is_belong_to_optimizer)."""
    return _is_persistable(var) and not isinstance(var, Parameter)


def save(program, model_path):
    """Save parameters (``.pdparams``), optimizer state (``.pdopt``, only
    written when non-empty) and the network description (``.pdmodel``) under
    a ``dirname/file_prefix`` path (reference io.py:1493 ``save``).

    The reference pickles name->ndarray dicts and serializes the ProgramDesc
    protobuf; we pickle the same dicts and store the JSON program IR."""
    import pickle

    base_name = os.path.basename(model_path)
    assert base_name != "", (
        "model_path MUST be format of dirname/filename, Now filename is "
        "empty str")
    dirname = os.path.dirname(model_path)
    if dirname:
        os.makedirs(dirname, exist_ok=True)
    scope = global_scope()

    def get_tensor(var):
        sv = scope.find_var(var.name)
        assert sv is not None and sv.get_tensor()._is_initialized(), (
            "variable %r is not initialized; run the startup program before "
            "fluid.save" % var.name)
        return np.asarray(sv.get_tensor().numpy())

    param_dict = {v.name: get_tensor(v)
                  for v in program.list_vars() if _is_parameter(v)}
    _atomic_write(model_path + ".pdparams",
                  lambda f: pickle.dump(param_dict, f))

    opt_dict = {v.name: get_tensor(v)
                for v in program.list_vars() if _is_belong_to_optimizer(v)}
    if opt_dict:  # reference: "If the optimizer have no variable ... the
        # file will not generated" (SGD has no accumulators)
        _atomic_write(model_path + ".pdopt",
                      lambda f: pickle.dump(opt_dict, f))

    _atomic_write(model_path + ".pdmodel",
                  lambda f: json.dump(program.to_dict(), f), mode="w")


def _check_var_match(var_name, old_np, new_np):
    """Shape/dtype guard shared by load() and set_program_state()
    (reference io.py set_var / set_program_state asserts)."""
    assert tuple(old_np.shape) == tuple(new_np.shape), (
        "Shape not matching: the Program requires a parameter with a shape "
        "of ({}), while the loaded parameter (namely [ {} ]) has a shape of "
        "({}).".format(tuple(old_np.shape), var_name, tuple(new_np.shape)))
    assert old_np.dtype == new_np.dtype, (
        "Dtype not matching: the Program requires a parameter with a dtype "
        "of ({}), while the loaded parameter (namely [ {} ]) has a dtype of "
        "({}).".format(old_np.dtype, var_name, new_np.dtype))


def load(program, model_path, executor=None):
    """Restore parameters + optimizer state saved by :func:`save` into the
    global scope, checking shape/dtype (reference io.py:1547 ``load``).

    Without ``executor`` the startup program must have run (the reference
    dereferences the scope tensor and errors on a missing var); passing an
    executor allows loading into a fresh scope (the reference pre-creates
    the tensors via _create_loaded_parameter)."""
    import pickle

    parameter_file_name = model_path + ".pdparams"
    assert os.path.exists(parameter_file_name), (
        "Parameter file [{}] not exits".format(parameter_file_name))
    scope = global_scope()

    def set_var(var, nd):
        sv = scope.find_var(var.name)
        if sv is None or not sv.get_tensor()._is_initialized():
            if executor is None:
                raise RuntimeError(
                    "Variable [ %s ] is not initialized in the scope; run "
                    "the startup program before fluid.load, or pass "
                    "executor= to create it" % var.name)
        else:
            _check_var_match(var.name, np.asarray(sv.get_tensor().numpy()),
                             nd)
        scope.var(var.name).set(nd)

    with open(parameter_file_name, "rb") as f:
        load_dict = pickle.load(f)
    for v in program.list_vars():
        if not _is_parameter(v):
            continue
        assert v.name in load_dict, (
            "Can not find [{}] in model file [{}]".format(
                v.name, parameter_file_name))
        set_var(v, load_dict[v.name])

    opt_vars = [v for v in program.list_vars() if _is_belong_to_optimizer(v)]
    if opt_vars:
        opt_file_name = model_path + ".pdopt"
        assert os.path.exists(opt_file_name), (
            "Optimizer file [{}] not exits".format(opt_file_name))
        with open(opt_file_name, "rb") as f:
            load_dict = pickle.load(f)
        for v in opt_vars:
            assert v.name in load_dict, (
                "Can not find [{}] in model file [{}]".format(
                    v.name, opt_file_name))
            set_var(v, load_dict[v.name])


def load_program_state(model_path):
    """-> merged name->ndarray dict of params + optimizer state
    (reference io.py:1630)."""
    import pickle

    parameter_file_name = model_path + ".pdparams"
    assert os.path.exists(parameter_file_name), (
        "Parameter file [{}] not exits".format(parameter_file_name))
    with open(parameter_file_name, "rb") as f:
        para_dict = pickle.load(f)
    opt_file_name = model_path + ".pdopt"
    if os.path.exists(opt_file_name):
        with open(opt_file_name, "rb") as f:
            para_dict.update(pickle.load(f))
    return para_dict


def set_program_state(program, state_dict):
    """Set persistable vars from a state dict, warning about unused keys
    (reference io.py:1672).  MUST be called after the startup program ran."""
    import warnings

    scope = global_scope()
    used = set()
    for var in program.list_vars():
        if not _is_persistable(var):
            continue
        sv = scope.find_var(var.name)
        assert sv is not None, (
            "Variable [ {} ] Not found, Please make sure run startup "
            "program".format(var.name))
        if var.name not in state_dict:
            continue
        new_np = np.asarray(state_dict[var.name])
        old_np = np.asarray(sv.get_tensor().numpy())
        _check_var_match(var.name, old_np, new_np)
        scope.var(var.name).set(new_np)
        used.add(var.name)
    unused = [k for k in state_dict if k not in used]
    if unused:
        warnings.warn(
            "This list is not set, Because of Paramerter not found in "
            "program. There are: {}".format(" ".join(unused)))
