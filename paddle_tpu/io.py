"""Checkpointing & inference-model save/load.

Parity: python/paddle/fluid/io.py (save_vars:149, save_persistables:523,
load_vars:588, load_persistables:801, save_inference_model:1011,
load_inference_model:1215) + the save/load ops (operators/save_op.h).
Format: one .npz per var-set + a JSON program desc (instead of the
reference's per-var binary streams + __model__ protobuf).
"""

import json
import os

import numpy as np

from .core.executor import global_scope
from .framework import Parameter, Program, Variable

__all__ = [
    "DataLoader",
    "PyReader",
    "save_vars",
    "save_params",
    "save_persistables",
    "load_vars",
    "load_params",
    "load_persistables",
    "save_inference_model",
    "load_inference_model",
    "save_train_model",
    "load_train_model",
]


def __getattr__(name):  # lazy: io imports before reader in __init__
    if name in ("DataLoader", "PyReader"):
        from . import reader

        return getattr(reader, name)
    raise AttributeError(name)


def _is_persistable(var):
    return var.persistable and not var.is_data


def _is_parameter(var):
    return isinstance(var, Parameter)


def _gather(executor, dirname, program, predicate, filename):
    program = program or _default_main()
    scope = global_scope()
    out = {}
    for var in program.list_vars():
        if not predicate(var):
            continue
        sv = scope.find_var(var.name)
        if sv is None or not sv.get_tensor()._is_initialized():
            continue
        out[var.name] = np.asarray(sv.get_tensor().numpy())
    os.makedirs(dirname, exist_ok=True)
    path = os.path.join(dirname, filename or "__params__.npz")
    np.savez(path, **out)
    return path


def _default_main():
    from .framework import default_main_program

    return default_main_program()


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    if vars is not None:
        names = {v.name if isinstance(v, Variable) else v for v in vars}
        predicate = lambda v: v.name in names  # noqa: E731
    return _gather(executor, dirname, main_program, predicate, filename)


def save_params(executor, dirname, main_program=None, filename=None):
    return _gather(executor, dirname, main_program, _is_parameter, filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    return _gather(executor, dirname, main_program, _is_persistable, filename)


def _scatter(executor, dirname, program, predicate, filename):
    program = program or _default_main()
    scope = global_scope()
    path = os.path.join(dirname, filename or "__params__.npz")
    data = np.load(path, allow_pickle=False)
    loaded = 0
    for var in program.list_vars():
        if not predicate(var):
            continue
        if var.name in data.files:
            scope.var(var.name).set(data[var.name])
            loaded += 1
    return loaded


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    if vars is not None:
        names = {v.name if isinstance(v, Variable) else v for v in vars}
        predicate = lambda v: v.name in names  # noqa: E731
    return _scatter(executor, dirname, main_program, predicate, filename)


def load_params(executor, dirname, main_program=None, filename=None):
    return _scatter(executor, dirname, main_program, _is_parameter, filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    return _scatter(executor, dirname, main_program, _is_persistable, filename)


def _prune_for_inference(program, feed_names, target_names):
    """Keep only ops needed to compute targets from feeds (reference
    prune.cc): backward slice over the op list, dropping
    backward/optimize-role ops."""
    from .framework import OP_ROLE_KEY, OpRole

    block = program.global_block()
    needed = set(target_names)
    keep = []
    for op in reversed(block.ops):
        role = op.attr(OP_ROLE_KEY) or 0
        if int(role) & (OpRole.Backward | OpRole.Optimize):
            continue
        if int(role) == OpRole.LRSched:
            continue
        outs = [n for n in op.output_arg_names if n]
        if not any(n in needed for n in outs):
            continue
        keep.append(op)
        for n in op.input_arg_names:
            if n:
                needed.add(n)
    keep.reverse()
    pruned = program.clone(for_test=True)
    pb = pruned.global_block()
    kept_keys = {(op.type, json.dumps(op.inputs, sort_keys=True),
                  json.dumps(op.outputs, sort_keys=True)) for op in keep}
    pb.ops = [
        op for op in pb.ops
        if (op.type, json.dumps(op.inputs, sort_keys=True),
            json.dumps(op.outputs, sort_keys=True)) in kept_keys
    ]
    pruned._bump_version()
    return pruned


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    program = main_program or _default_main()
    target_names = [v.name if isinstance(v, Variable) else v for v in target_vars]
    pruned = _prune_for_inference(program, feeded_var_names, target_names)
    os.makedirs(dirname, exist_ok=True)
    model = {
        "program": pruned.to_dict(),
        "feed_names": list(feeded_var_names),
        "fetch_names": target_names,
    }
    with open(os.path.join(dirname, model_filename or "__model__.json"), "w") as f:
        json.dump(model, f)
    if not program_only:
        save_persistables(executor, dirname, pruned, params_filename)
    return target_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    with open(os.path.join(dirname, model_filename or "__model__.json")) as f:
        model = json.load(f)
    program = Program.from_dict(model["program"])
    try:
        load_persistables(executor, dirname, program, params_filename)
    except FileNotFoundError:
        pass
    fetch_vars = [program.global_block().var(n) for n in model["fetch_names"]]
    return program, model["feed_names"], fetch_vars


def save_train_model(dirname, feed_names, fetch_vars, executor,
                     main_program=None, startup_program=None):
    """Save a full *training* bundle (main + startup programs + names) for
    the standalone C++ trainer (parity: the reference's
    train/demo workflow, which saves main/startup ProgramDescs via
    fluid.io and loads them from C++, train/demo/demo_trainer.cc:25-45)."""
    from .framework import default_startup_program

    main = main_program or _default_main()
    startup = startup_program or default_startup_program()
    fetch_names = [v.name if isinstance(v, Variable) else v
                   for v in fetch_vars]
    os.makedirs(dirname, exist_ok=True)
    bundle = {
        "main_program": main.to_dict(),
        "startup_program": startup.to_dict(),
        "feed_names": list(feed_names),
        "fetch_names": fetch_names,
    }
    with open(os.path.join(dirname, "__train_model__.json"), "w") as f:
        json.dump(bundle, f)
    # persist current params too so training can resume (optional at load)
    if executor is not None:
        save_persistables(executor, dirname, main)
    return fetch_names


def load_train_model(dirname, executor=None):
    """Load a bundle saved by save_train_model ->
    (main, startup, feed_names, fetch_names)."""
    with open(os.path.join(dirname, "__train_model__.json")) as f:
        bundle = json.load(f)
    main = Program.from_dict(bundle["main_program"])
    startup = Program.from_dict(bundle["startup_program"])
    return main, startup, bundle["feed_names"], bundle["fetch_names"]
