"""Named fault points for fault-injection testing.

The reference PS stack is exercised in CI by killing workers and dropping
RPCs at the process level (test_dist_base.py); here the runtime itself
exposes *named fault points* so a single in-process spec can deterministically
tear any layer: the RPC transport (``rpc.send``, ``rpc.get``), the pserver
round loop (``ps.round``), and the checkpoint writer (``ckpt.write``).
Call sites are free to define additional points (tests use e.g.
``trainer.step``) — a point is just a name checked against the armed spec.

Arming: set ``FLAGS_fault_spec`` (flag or env var) to a ``;``-separated list
of ``point:kind:prob[:count[:skip]]`` entries:

- ``point`` — fault-point name matched exactly against ``maybe_fail(point)``.
- ``kind``  — one of ``drop | delay | error | kill``.
- ``prob``  — firing probability per armed check (0..1].
- ``count`` — max number of firings (default: unlimited).
- ``skip``  — number of armed checks to let pass before the point may fire
  (default 0; makes ``kill`` deterministic mid-job instead of at step 0).

What a firing does is split between this module and the call site:

- ``delay`` — sleeps ~100 ms here, then the operation proceeds (slow link /
  slow server; exercises deadlines).
- ``kill``  — SIGKILLs the current process here (torn state on disk/in
  flight; exercises crash-safety + supervised relaunch).
- ``drop`` / ``error`` — returned to the caller as the fired kind; the call
  site maps them onto its own failure modes (rpc.py: ``drop`` = frame lost
  before transmission, ``error`` = transport failure after delivery — the
  ACK-lost case that forces dedupe-by-sequence).

``maybe_fail`` costs one dict lookup when the spec is empty — fault points
are free in production.
"""

import os
import random

__all__ = ["maybe_fail", "FaultInjected", "arm", "disarm", "fault_stats"]

KINDS = ("drop", "delay", "error", "kill")

DELAY_SECONDS = 0.1


class FaultInjected(ConnectionError):
    """Raised by call sites for injected transport errors.  Subclasses
    ConnectionError so retry paths treat injected and real transport
    failures identically."""


class _Point:
    __slots__ = ("name", "kind", "prob", "count", "skip", "fired", "checked")

    def __init__(self, name, kind, prob, count, skip):
        self.name = name
        self.kind = kind
        self.prob = prob
        self.count = count      # None = unlimited firings
        self.skip = skip        # armed checks to let pass first
        self.fired = 0
        self.checked = 0


# armed points by name; _spec_src caches the parsed spec string so a flag
# change re-arms lazily without a hook into flags.set_flags
_points = {}
_spec_src = None
_rng = random.Random()


def _parse_spec(spec):
    points = {}
    for entry in (spec or "").replace(",", ";").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 3:
            raise ValueError(
                "bad FLAGS_fault_spec entry %r (want point:kind:prob"
                "[:count[:skip]])" % entry)
        name, kind, prob = parts[0], parts[1], float(parts[2])
        if kind not in KINDS:
            raise ValueError("bad fault kind %r in %r (known: %s)"
                             % (kind, entry, "|".join(KINDS)))
        count = int(parts[3]) if len(parts) > 3 and parts[3] != "" else None
        skip = int(parts[4]) if len(parts) > 4 else 0
        points[name] = _Point(name, kind, prob, count, skip)
    return points


def _refresh():
    """Re-parse when the flag/env spec string changed."""
    global _points, _spec_src
    from .. import flags

    spec = flags.flag("fault_spec") or ""
    if spec != _spec_src:
        _spec_src = spec
        _points = _parse_spec(spec)


def arm(spec, seed=None):
    """Programmatically arm a spec string (in addition to, and overriding,
    FLAGS_fault_spec — same syntax).  seed makes prob<1 draws reproducible."""
    global _points, _spec_src
    _spec_src = None  # force re-read of the flag on next maybe_fail
    _points = _parse_spec(spec)
    if seed is not None:
        _rng.seed(seed)


def disarm():
    global _points, _spec_src
    _points = {}
    _spec_src = ""


def fault_stats():
    """point name -> (checked, fired) counters for armed points."""
    return {p.name: (p.checked, p.fired) for p in _points.values()}


def maybe_fail(point):
    """Check the named fault point.  Returns None (no fault), or the fired
    kind ``"drop"``/``"error"`` for the call site to act on.  ``delay``
    sleeps here and returns None; ``kill`` does not return."""
    if not _points:
        if _spec_src is None or _spec_src == "":
            # unarmed fast path — but a spec may have been set via flags
            # since the last check
            _refresh()
            if not _points:
                return None
        else:
            return None
    p = _points.get(point)
    if p is None:
        return None
    p.checked += 1
    if p.checked <= p.skip:
        return None
    if p.count is not None and p.fired >= p.count:
        return None
    if p.prob < 1.0 and _rng.random() >= p.prob:
        return None
    p.fired += 1
    # telemetry BEFORE the fault acts: a "kill" never returns, and the
    # post-mortem registry (pserver __metrics__ scrape / relaunch logs)
    # should still attribute the crash to the injected point
    from ..core import telemetry as _tm

    _tm.inc("fault_injected_total", point=p.name, kind=p.kind)
    # flight-recorder dump BEFORE the fault acts, same reasoning: the
    # note() write-through puts the postmortem on disk even for "kill"
    try:
        from ..core import tracing as _tracing

        _tracing.note("fault", point=p.name, fault_kind=p.kind)
    except Exception:
        pass
    if p.kind == "delay":
        import time

        time.sleep(DELAY_SECONDS * (0.5 + _rng.random()))
        return None
    if p.kind == "kill":
        import signal

        os.kill(os.getpid(), signal.SIGKILL)
    return p.kind  # "drop" | "error"
