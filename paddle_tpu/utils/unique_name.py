"""Unique name generation for variables/ops.

Mirrors the capability of ``python/paddle/fluid/unique_name.py`` in the
reference (generator with prefix counters, guard for scoped renaming).
"""

import contextlib
import threading

__all__ = ["generate", "guard", "switch"]


class _NameGenerator:
    def __init__(self, prefix=""):
        self._prefix = prefix
        self._counters = {}
        self._lock = threading.Lock()

    def generate(self, key):
        with self._lock:
            idx = self._counters.get(key, 0)
            self._counters[key] = idx + 1
        return "%s%s_%d" % (self._prefix, key, idx)


# One shared default generator (uniqueness across ALL threads appending to
# the same program), with per-thread overrides: a thread that wants an
# isolated, reproducible name sequence (pserver/worker role threads standing
# in for the reference's separate processes) opts in via guard()/switch().
_default_generator = _NameGenerator()
_tls = threading.local()


def _gen():
    return getattr(_tls, "generator", None) or _default_generator


def generate(key):
    """Generate a unique name like ``fc_0.w_0`` for the given key."""
    return _gen().generate(key)


def switch(new_generator=None):
    old = getattr(_tls, "generator", None)
    _tls.generator = (new_generator if new_generator is not None
                      else _NameGenerator())
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    if isinstance(new_generator, str):
        new_generator = _NameGenerator(new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        # restore exactly: None means "no thread-local override" (shared
        # default generator), not a fresh generator
        _tls.generator = old
