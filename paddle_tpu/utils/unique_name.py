"""Unique name generation for variables/ops.

Mirrors the capability of ``python/paddle/fluid/unique_name.py`` in the
reference (generator with prefix counters, guard for scoped renaming).
"""

import contextlib
import threading

__all__ = ["generate", "guard", "switch"]


class _NameGenerator:
    def __init__(self, prefix=""):
        self._prefix = prefix
        self._counters = {}
        self._lock = threading.Lock()

    def generate(self, key):
        with self._lock:
            idx = self._counters.get(key, 0)
            self._counters[key] = idx + 1
        return "%s%s_%d" % (self._prefix, key, idx)


_generator = _NameGenerator()


def generate(key):
    """Generate a unique name like ``fc_0.w_0`` for the given key."""
    return _generator.generate(key)


def switch(new_generator=None):
    global _generator
    old = _generator
    _generator = new_generator if new_generator is not None else _NameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    if isinstance(new_generator, str):
        new_generator = _NameGenerator(new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
