"""Filesystem shims: local FS + HDFS via shell (parity:
paddle/fluid/framework/io/fs.cc + shell.cc — the reference shells out to
`hadoop fs` through popen; so do we — and
python/paddle/fluid/incubate/fleet/utils/hdfs.py HDFSClient)."""

import contextlib
import os
import shutil
import subprocess

__all__ = ["LocalFS", "HDFSClient"]


class LocalFS:
    """Local filesystem with the fs.cc surface (localfs_* functions)."""

    @contextlib.contextmanager
    def atomic_write_dir(self, path):
        """Context manager yielding a temp directory that becomes `path`
        on clean exit (write-temp-then-rename, the crash-safe checkpoint
        idiom: a SIGKILL mid-write leaves only an invisible temp dir, never
        a torn `path`).  The rename is atomic when `path` does not already
        exist; a pre-existing `path` is deleted first — that narrow window
        is why checkpoint readers must also gate on the _SUCCESS manifest
        (io.CheckpointManager.latest_valid)."""
        tmp = "%s._tmp.%d" % (path, os.getpid())
        self.delete(tmp)
        os.makedirs(tmp)
        try:
            yield tmp
        except BaseException:
            self.delete(tmp)
            raise
        self.delete(path)
        os.replace(tmp, path)

    def ls_dir(self, path):
        if not os.path.exists(path):
            return []
        return sorted(os.listdir(path))

    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def touch(self, path):
        open(path, "a").close()

    def mv(self, src, dst):
        shutil.move(src, dst)

    def upload(self, local, remote):
        shutil.copy(local, remote)

    def download(self, remote, local):
        shutil.copy(remote, local)


class HDFSClient:
    """HDFS client shelling out to `hadoop fs` (hdfs.py:HDFSClient;
    fs.cc hdfs_* commands run the same shell pipeline).

    hadoop_home: directory containing bin/hadoop.  configs: dict of
    hadoop config key->value passed as -D options (e.g.
    fs.default.name, hadoop.job.ugi)."""

    def __init__(self, hadoop_home=None, configs=None, retry_times=3):
        self.hadoop_home = hadoop_home or os.environ.get("HADOOP_HOME", "")
        self.configs = dict(configs or {})
        self.retry_times = retry_times
        self._bin = (os.path.join(self.hadoop_home, "bin", "hadoop")
                     if self.hadoop_home else "hadoop")

    def _base_cmd(self):
        cmd = [self._bin, "fs"]
        for k, v in self.configs.items():
            cmd += ["-D%s=%s" % (k, v)]
        return cmd

    def _run(self, args, check=True, retry=True):
        if shutil.which(self._bin) is None and not os.path.exists(self._bin):
            raise RuntimeError(
                "hadoop binary not found (%r); set hadoop_home or "
                "HADOOP_HOME" % self._bin)
        last = None
        for _ in range(max(self.retry_times, 1) if retry else 1):
            p = subprocess.run(self._base_cmd() + args, capture_output=True,
                               text=True)
            last = p
            if p.returncode == 0:
                return p
        if check:
            raise RuntimeError("hadoop fs %s failed: %s"
                               % (" ".join(args), last.stderr))
        return last

    # -- HDFSClient surface (hdfs.py) -----------------------------------------

    def ls(self, path):
        p = self._run(["-ls", path])
        out = []
        for line in p.stdout.splitlines():
            parts = line.split()
            if len(parts) >= 8:
                out.append(parts[-1])
        return out

    def is_exist(self, path):
        p = self._run(["-test", "-e", path], check=False, retry=False)
        return p is not None and p.returncode == 0

    def is_dir(self, path):
        p = self._run(["-test", "-d", path], check=False, retry=False)
        return p is not None and p.returncode == 0

    def is_file(self, path):
        return self.is_exist(path) and not self.is_dir(path)

    def delete(self, path):
        self._run(["-rmr", path], check=False)

    def makedirs(self, path):
        self._run(["-mkdir", "-p", path])

    def rename(self, src, dst):
        self._run(["-mv", src, dst])

    def upload(self, hdfs_path, local_path, overwrite=False):
        args = ["-put"]
        if overwrite:
            args.append("-f")
        self._run(args + [local_path, hdfs_path])

    def download(self, hdfs_path, local_path, overwrite=False):
        if overwrite and os.path.exists(local_path):
            LocalFS().delete(local_path)
        self._run(["-get", hdfs_path, local_path])

    def touch(self, path):
        self._run(["-touchz", path])
