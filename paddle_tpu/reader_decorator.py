"""Reader decorators: composable sample-stream transforms.

Parity: python/paddle/reader/decorator.py (map_readers, shuffle, buffered,
compose, chain, firstn, xmap_readers) and paddle.batch
(python/paddle/batch.py).  A "reader" is a nullary callable returning an
iterator of samples.
"""

import itertools
import queue as _queue
import random
import threading

__all__ = [
    "map_readers", "buffered", "compose", "chain", "shuffle", "firstn",
    "cache", "batch", "xmap_readers", "multiprocess_reader",
]


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)

    return reader


def shuffle(reader, buf_size):
    def shuffled():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return shuffled


def chain(*readers):
    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(map(make_tuple, outputs), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum(map(make_tuple, outputs), ())

    return reader


def buffered(reader, size):
    """Prefetch up to `size` samples on a background thread."""

    class _End:
        pass

    def data_reader():
        r = reader()
        q = _queue.Queue(maxsize=size)

        def feed():
            try:
                for d in r:
                    q.put(d)
            finally:
                q.put(_End)

        t = threading.Thread(target=feed, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e

    return data_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def cache(reader):
    all_data = tuple(reader())

    def cached():
        yield from all_data

    return cached


def batch(reader, batch_size, drop_last=False):
    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a sample stream with worker threads (the reference
    uses threads too — xmap_readers in python/paddle/reader/decorator.py)."""

    class _End:
        pass

    def xreader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)

        def read_worker():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample))
            for _ in range(process_num):
                in_q.put(_End)

        def map_worker():
            while True:
                item = in_q.get()
                if item is _End:
                    out_q.put(_End)
                    return
                i, sample = item
                out_q.put((i, mapper(sample)))

        threading.Thread(target=read_worker, daemon=True).start()
        workers = [threading.Thread(target=map_worker, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()

        finished = 0
        if order:
            import heapq

            heap, want = [], 0
            while finished < process_num:
                item = out_q.get()
                if item is _End:
                    finished += 1
                    continue
                heapq.heappush(heap, item)
                while heap and heap[0][0] == want:
                    yield heapq.heappop(heap)[1]
                    want += 1
            while heap:
                yield heapq.heappop(heap)[1]
        else:
            while finished < process_num:
                item = out_q.get()
                if item is _End:
                    finished += 1
                    continue
                yield item[1]

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """API-parity shim: runs the readers on threads (functionally equivalent
    stream; the native queue already decouples producers from the device)."""
    return buffered(chain(*readers), queue_size)
