"""Gradient clipping (parity: python/paddle/fluid/clip.py)."""

from .framework import default_main_program

__all__ = [
    "set_gradient_clip",
    "ErrorClipByValue",
    "GradientClipByValue",
    "GradientClipByNorm",
    "GradientClipByGlobalNorm",
    "append_gradient_clip_ops",
]

_clip_attr = {"global": None}


class BaseGradientClipAttr:
    def _process(self, params_grads):
        raise NotImplementedError


class ErrorClipByValue:
    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def _process(self, params_grads):
        from . import layers

        out = []
        program = default_main_program()
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            with program._optimized_guard([p, g]):
                ng = layers.clip(g, self.min, self.max)
            out.append((p, ng))
        return out


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _process(self, params_grads):
        from . import layers

        out = []
        program = default_main_program()
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            with program._optimized_guard([p, g]):
                ng = layers.clip_by_norm(g, self.clip_norm)
            out.append((p, ng))
        return out


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _process(self, params_grads):
        from . import layers

        program = default_main_program()
        # current_block: see regularizer.append_regularization_ops
        block = program.current_block()
        norms = []
        with program._backward_role_guard():
            for p, g in params_grads:
                if g is None:
                    continue
                helper_out = block.create_var(
                    name=g.name + "@sq_l2", shape=(1,), dtype=g.dtype
                )
                block.append_op(
                    type="squared_l2_norm",
                    inputs={"X": [g]},
                    outputs={"Out": [helper_out]},
                )
                norms.append(helper_out)
            if not norms:
                return params_grads
            total = block.create_var(
                name="global_norm@" + self.group_name + "@var",
                shape=(1,), dtype=norms[0].dtype
            )
            block.append_op(
                type="sum", inputs={"X": norms}, outputs={"Out": [total]}
            )
            gnorm = layers.sqrt(total)
            clip_var = layers.fill_constant((1,), gnorm.dtype, self.clip_norm)
            scale = layers.elementwise_div(
                clip_var,
                layers.elementwise_max(clip_var, gnorm),
            )
            out = []
            for p, g in params_grads:
                if g is None:
                    out.append((p, g))
                    continue
                ng = layers.elementwise_mul(g, scale)
                out.append((p, ng))
        return out


def set_gradient_clip(clip, param_list=None, program=None):
    _clip_attr["global"] = clip
    if param_list is not None:
        for p in param_list:
            if hasattr(p, "gradient_clip_attr"):
                p.gradient_clip_attr = clip


def append_gradient_clip_ops(params_grads):
    clip = _clip_attr.get("global")
    # per-param attr wins
    per_param = [getattr(p, "gradient_clip_attr", None) for p, _ in params_grads]
    if clip is None and not any(per_param):
        return params_grads
    if clip is not None:
        return clip._process(params_grads)
    out = []
    for (p, g), attr in zip(params_grads, per_param):
        if attr is None or g is None:
            out.append((p, g))
        else:
            out.extend(attr._process([(p, g)]))
    return out
