"""Program visualization (reference python/paddle/fluid/debugger.py
draw_block_graphviz + graphviz.py/net_drawer.py): emit a Graphviz dot of a
block's op/var graph."""

__all__ = ["draw_block_graphviz", "pprint_program_codes"]


def _dot_escape(s):
    return str(s).replace('"', '\\"')


def draw_block_graphviz(block, highlights=None, path="./temp.dot"):
    """Write the block's dataflow as a .dot file; vars are ellipses, ops
    are boxes (debugger.py draw_block_graphviz)."""
    highlights = set(highlights or [])
    lines = ["digraph G {", "  rankdir=TB;"]
    var_ids = {}
    for i, (name, var) in enumerate(block.vars.items()):
        var_ids[name] = "var_%d" % i
        color = ', style=filled, fillcolor="yellow"' \
            if name in highlights else ""
        label = "%s\\n%s %s" % (_dot_escape(name), var.dtype,
                                list(var.shape) if var.shape else "?")
        lines.append('  var_%d [shape=ellipse, label="%s"%s];'
                     % (i, label, color))
    for j, op in enumerate(block.ops):
        lines.append('  op_%d [shape=box, style=rounded, label="%s"];'
                     % (j, _dot_escape(op.type)))
        for n in op.input_arg_names:
            if n in var_ids:
                lines.append("  %s -> op_%d;" % (var_ids[n], j))
        for n in op.output_arg_names:
            if n in var_ids:
                lines.append("  op_%d -> %s;" % (j, var_ids[n]))
    lines.append("}")
    with open(path, "w") as f:
        f.write("\n".join(lines))
    return path


def pprint_program_codes(program):
    print(program.to_string(throw_on_error=False))
