"""Program visualization (reference python/paddle/fluid/debugger.py
draw_block_graphviz + graphviz.py/net_drawer.py): emit a Graphviz dot of a
block's op/var graph, or a plain-text op graph with verifier diagnostics
annotated onto the offending ops (``tools/proglint.py --dump``)."""

__all__ = ["draw_block_graphviz", "draw_program", "pprint_program_codes"]


def _dot_escape(s):
    return str(s).replace('"', '\\"')


def draw_block_graphviz(block, highlights=None, path="./temp.dot"):
    """Write the block's dataflow as a .dot file; vars are ellipses, ops
    are boxes (debugger.py draw_block_graphviz)."""
    highlights = set(highlights or [])
    lines = ["digraph G {", "  rankdir=TB;"]
    var_ids = {}
    for i, (name, var) in enumerate(block.vars.items()):
        var_ids[name] = "var_%d" % i
        color = ', style=filled, fillcolor="yellow"' \
            if name in highlights else ""
        label = "%s\\n%s %s" % (_dot_escape(name), var.dtype,
                                list(var.shape) if var.shape else "?")
        lines.append('  var_%d [shape=ellipse, label="%s"%s];'
                     % (i, label, color))
    for j, op in enumerate(block.ops):
        lines.append('  op_%d [shape=box, style=rounded, label="%s"];'
                     % (j, _dot_escape(op.type)))
        for n in op.input_arg_names:
            if n in var_ids:
                lines.append("  %s -> op_%d;" % (var_ids[n], j))
        for n in op.output_arg_names:
            if n in var_ids:
                lines.append("  op_%d -> %s;" % (j, var_ids[n]))
    lines.append("}")
    with open(path, "w") as f:
        f.write("\n".join(lines))
    return path


def _diags_by_op(diagnostics, block_idx):
    by_op = {}
    for d in diagnostics or ():
        if d.op_idx is not None and (d.block_idx or 0) == block_idx:
            by_op.setdefault(d.op_idx, []).append(d)
    return by_op


_SEV_MARK = {"error": "!!", "warning": " !", "info": " ."}


def draw_program(program, diagnostics=None, max_var_width=40):
    """Render a program as a plain-text op graph, one line per op
    (``idx: type(inputs) -> outputs``), with any verifier diagnostics
    attached under the op they point at.  Program-level diagnostics (no op
    index) are listed in a trailing section.  Returns the string."""
    lines = []
    diagnostics = list(diagnostics or ())
    for blk in program.blocks:
        lines.append("block %d (%d ops, %d vars):"
                     % (blk.idx, len(blk.ops), len(blk.vars)))
        by_op = _diags_by_op(diagnostics, blk.idx)
        for i, op in enumerate(blk.ops):
            ins = ", ".join(n for n in op.input_arg_names if n)
            outs = ", ".join(n for n in op.output_arg_names if n)
            if len(ins) > max_var_width:
                ins = ins[: max_var_width - 3] + "..."
            if len(outs) > max_var_width:
                outs = outs[: max_var_width - 3] + "..."
            lines.append("  %4d: %s(%s) -> %s" % (i, op.type, ins, outs))
            for d in by_op.get(i, ()):
                lines.append("        %s %s %s: %s"
                             % (_SEV_MARK.get(d.severity, "??"), d.rule,
                                d.severity.upper(), d.message))
                if d.suggestion:
                    lines.append("           fix: %s" % d.suggestion)
    prog_level = [d for d in diagnostics if d.op_idx is None]
    if prog_level:
        lines.append("program-level:")
        for d in prog_level:
            lines.append("  %s %s %s: %s"
                         % (_SEV_MARK.get(d.severity, "??"), d.rule,
                            d.severity.upper(), d.message))
    return "\n".join(lines)


def pprint_program_codes(program, diagnostics=None):
    """Print the program repr; with verifier diagnostics, print the
    annotated text graph instead of the bare dump."""
    if diagnostics:
        print(draw_program(program, diagnostics))
    else:
        print(program.to_string(throw_on_error=False))
