"""CompiledProgram: multi-device execution via jax.sharding.Mesh.

TPU-native analog of ``python/paddle/fluid/compiler.py:65`` +
``paddle/fluid/framework/parallel_executor.cc``.  Instead of replicating the
graph into per-device SSA op handles with NCCL all-reduce handles, data
parallelism is expressed as SPMD sharding: the feed batch is sharded over the
mesh 'data' axis, parameters are replicated (or sharded per their annotation
for tensor parallelism), and XLA's SPMD partitioner inserts the ICI
collectives (the all-reduce the reference builds by hand in
details/all_reduce_op_handle.cc falls out of the partitioner).
"""

import jax

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy"]


class BuildStrategy:
    """Knobs kept for API parity (details/build_strategy.h:58-139).  Most are
    no-ops under XLA (fusion/memory-reuse are the compiler's job); the ones
    that matter map to sharding/compile choices."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = False
        self.fuse_all_optimizer_ops = False
        self.enable_inplace = True
        self.memory_optimize = True
        self.nccl_comm_num = 1
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 0
        self.enable_sequential_execution = False
        self.remove_unnecessary_lock = True
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 1
        self.use_experimental_executor = False


class CompiledProgram:
    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._is_data_parallel = False
        self._data_axis = None
        self._places = None
        self._mesh_cached = None
        self._loss_name = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._is_data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._places = places
        self._data_axis = "data"
        return self

    def with_inference_optimize(self, config):
        return self

    def _with_mesh(self, mesh, data_axis="data"):
        """TPU extension: run over an explicit jax.sharding.Mesh (e.g. a
        ('data','model') mesh for DP x TP).  Parameters annotated with
        Variable.sharding get the corresponding PartitionSpec."""
        if data_axis not in mesh.axis_names:
            raise ValueError(
                "data_axis %r is not an axis of the mesh (axes: %s)"
                % (data_axis, mesh.axis_names)
            )
        self._is_data_parallel = True
        self._mesh_cached = mesh
        self._data_axis = data_axis
        return self

    def _mesh(self):
        if not self._is_data_parallel:
            return None
        if self._mesh_cached is None:
            devices = jax.devices()
            if self._places is not None:
                devices = devices[: len(self._places)] or devices
            from jax.sharding import Mesh
            import numpy as np

            self._mesh_cached = Mesh(np.array(devices), ("data",))
        return self._mesh_cached
