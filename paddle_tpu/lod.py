"""Multi-level LoD <-> padded-dense conversion.

The reference represents nested variable-length structure as LoD offsets
(framework/lod_tensor.h:52 `LoD = vector<Vector<size_t>>`, e.g. paragraphs
-> sentences -> words on one flat buffer).  The TPU-native layout replaces
ragged buffers with padded dense tensors + per-level length arrays
(ops/sequence.py design note); this module is the bridge for lod_level >= 2:

  level 1: list[seq]                 -> [B, T, ...]        + len [B]
  level 2: list[list[seq]]           -> [B, S, T, ...]     + (nseq [B],
                                                             len [B, S])

`lengths_to_lod` / `lod_to_lengths` convert between the reference's offset
form and per-level length lists, so TpuTensor.set_lod round-trips.
"""

import numpy as np

__all__ = [
    "pad_sequences", "pad_nested_sequences", "unpad_nested_sequences",
    "lengths_to_lod", "lod_to_lengths",
]


def lengths_to_lod(lengths_per_level):
    """[[2,1],[3,2,4]] -> [[0,2,3],[0,3,5,9]] (offset form, lod_tensor.h)."""
    lod = []
    for lens in lengths_per_level:
        offs = [0]
        for l in lens:
            offs.append(offs[-1] + int(l))
        lod.append(offs)
    return lod


def lod_to_lengths(lod):
    return [[b - a for a, b in zip(l, l[1:])] for l in lod]


def pad_sequences(seqs, dtype=None):
    """level-1: list of [Ti, ...] -> ([B, Tmax, ...], lengths [B])."""
    seqs = [np.asarray(s) for s in seqs]
    dtype = dtype or seqs[0].dtype
    tmax = max((s.shape[0] for s in seqs), default=0)
    tail = seqs[0].shape[1:] if seqs else ()
    out = np.zeros((len(seqs), tmax) + tail, dtype)
    lens = np.zeros((len(seqs),), "int64")
    for i, s in enumerate(seqs):
        out[i, : s.shape[0]] = s
        lens[i] = s.shape[0]
    return out, lens


def pad_nested_sequences(nested, dtype=None):
    """level-2: list (batch) of lists (seqs) of [Ti, ...] arrays ->
    ([B, Smax, Tmax, ...], nseq [B], lens [B, Smax])."""
    B = len(nested)
    flat0 = next((np.asarray(s) for row in nested for s in row), None)
    if flat0 is None:
        raise ValueError("empty nested batch")
    dtype = dtype or flat0.dtype
    smax = max(len(row) for row in nested)
    tmax = max((np.asarray(s).shape[0] for row in nested for s in row),
               default=0)
    tail = flat0.shape[1:]
    out = np.zeros((B, smax, tmax) + tail, dtype)
    nseq = np.zeros((B,), "int64")
    lens = np.zeros((B, smax), "int64")
    for i, row in enumerate(nested):
        nseq[i] = len(row)
        for j, s in enumerate(row):
            s = np.asarray(s)
            out[i, j, : s.shape[0]] = s
            lens[i, j] = s.shape[0]
    return out, nseq, lens


def unpad_nested_sequences(arr, nseq, lens):
    """Inverse of pad_nested_sequences."""
    out = []
    for i in range(arr.shape[0]):
        row = []
        for j in range(int(nseq[i])):
            row.append(np.asarray(arr[i, j, : int(lens[i, j])]))
        out.append(row)
    return out
