"""Program-level IR passes (parity: paddle/fluid/framework/ir/ —
ir::Pass + PassRegistry, ir/pass.h:38).

Most of the reference's ~75 passes dissolve into XLA (fusion, memory reuse,
placement).  What remains meaningful at the program level are
*graph-rewriting* optimizations whose benefit XLA cannot recover because
they change the parameter values themselves or delete stateful ops:

- conv_bn_fuse_pass (ir/conv_bn_fuse_pass.cc): fold an inference-mode
  batch_norm into the preceding conv2d's weights/bias.  Removes the BN op
  and its four parameter reads entirely.
- delete_dropout_pass (delete_dropout_op_pass): drop is_test dropout ops
  (identity at inference).

Passes run on (Program, Scope) pairs — the scope carries the parameter
values a folding pass rewrites, mirroring how the reference's passes read
the global scope for persistables."""

import numpy as np

__all__ = ["Pass", "register_pass", "get_pass", "apply_pass", "all_passes"]

_PASS_REGISTRY = {}


class Pass:
    """Base class (ir/pass.h:38 analog): override apply(program, scope).

    `protected` holds variable names a pass must keep PRODUCED (feed/fetch
    targets of a loaded inference model — fetch ops are stripped at load,
    io.py _strip_feed_fetch, so fetched vars have no op consumers and
    would otherwise look swallowable)."""

    name = None
    protected = frozenset()

    def apply(self, program, scope):
        raise NotImplementedError


def register_pass(name):
    def deco(cls):
        cls.name = name
        _PASS_REGISTRY[name] = cls
        return cls

    return deco


def get_pass(name):
    return _PASS_REGISTRY[name]()


def all_passes():
    return sorted(_PASS_REGISTRY)


def apply_pass(name, program, scope, protected=()):
    """Apply one registered pass in place; returns the program.
    `protected`: var names that must stay produced (fetch targets)."""
    p = get_pass(name)
    p.protected = frozenset(protected)
    p.apply(program, scope)
    return program


def _build_consumers(block):
    """name -> [ops reading it] (shared by the fusion passes)."""
    consumers = {}
    for op in block.ops:
        for n in op.input_arg_names:
            consumers.setdefault(n, []).append(op)
    return consumers


@register_pass("delete_dropout_pass")
class DeleteDropoutPass(Pass):
    """Replace is_test dropout ops with `assign` (identity).  Using assign
    instead of deleting + rewiring keeps every output var produced — fetch
    targets and chained dropouts stay valid — and XLA folds the copy."""

    def apply(self, program, scope):
        from .framework import Operator

        block = program.global_block()
        new_ops = []
        for op in block.ops:
            if (op.type == "dropout" and op.attrs.get("is_test")
                    and op.attrs.get("dropout_implementation")
                    == "upscale_in_train"):
                # upscale_in_train is identity at test time; downgrade
                # mode rescales, so only upscale is replaceable
                new_ops.append(Operator(
                    block, type="assign",
                    inputs={"X": [op.input("X")[0]]},
                    outputs={"Out": [op.output("Out")[0]]}, attrs={}))
            else:
                new_ops.append(op)
        block.ops = new_ops
        program._bump_version()


@register_pass("conv_bn_fuse_pass")
class ConvBNFusePass(Pass):
    """Fold inference batch_norm into the preceding conv2d
    (ir/conv_bn_fuse_pass.cc): W' = W * gamma/std (per out-channel),
    b' = beta - mean * gamma/std; the BN op is replaced by one
    elementwise_add of b'."""

    def apply(self, program, scope):
        block = program.global_block()
        # conv output name -> conv op, only when that output feeds exactly
        # one consumer (the BN)
        consumers = _build_consumers(block)
        filter_uses = {}
        for op in block.ops:
            if op.type == "conv2d":
                f = op.input("Filter")[0]
                filter_uses[f] = filter_uses.get(f, 0) + 1

        new_ops = []
        i = 0
        ops = block.ops
        while i < len(ops):
            op = ops[i]
            fused = False
            if op.type == "conv2d":
                out = op.output("Output")[0]
                cons = consumers.get(out, [])
                w_name = op.input("Filter")[0]
                # a filter shared by several convs (siamese nets) can't be
                # folded — scaling it would corrupt the other conv
                if (len(cons) == 1 and cons[0].type == "batch_norm"
                        and cons[0].attrs.get("is_test")
                        and filter_uses.get(w_name, 0) == 1):
                    bn = cons[0]
                    names = {s: bn.input(s)[0] for s in
                             ("Scale", "Bias", "Mean", "Variance")}
                    vals = {}
                    ok = True
                    for s, n in names.items():
                        v = scope.find_var(n)
                        if v is None or not v.get_tensor()._is_initialized():
                            ok = False
                            break
                        vals[s] = np.asarray(v.get_tensor().numpy())
                    wvar = scope.find_var(w_name)
                    if ok and wvar is not None and \
                            wvar.get_tensor()._is_initialized():
                        eps = float(bn.attrs.get("epsilon", 1e-5))
                        std = np.sqrt(vals["Variance"] + eps)
                        factor = vals["Scale"] / std          # [O]
                        W = np.asarray(wvar.get_tensor().numpy())
                        wvar.get_tensor().set(
                            (W * factor.reshape(-1, 1, 1, 1)).astype(W.dtype))
                        bias = vals["Bias"] - vals["Mean"] * factor
                        # keyed by the BN output: unique per fused pair
                        bias_name = bn.output("Y")[0] + "@bn_fused_bias"
                        bvar = block.create_var(
                            name=bias_name, shape=[len(bias)],
                            dtype="float32", persistable=True)
                        scope.var(bias_name).set(bias.astype("float32"))
                        bn_out = bn.output("Y")[0]
                        from .framework import Operator

                        add = Operator(
                            block, type="elementwise_add",
                            inputs={"X": [out], "Y": [bias_name]},
                            outputs={"Out": [bn_out]},
                            attrs={"axis": 1})
                        new_ops.append(op)
                        new_ops.append(add)
                        i += 1
                        # skip every op up to and including the BN (they
                        # are contiguous in topological emit order)
                        while ops[i] is not bn:
                            new_ops.append(ops[i])
                            i += 1
                        i += 1  # past the bn
                        fused = True
            if not fused:
                new_ops.append(op)
                i += 1
        block.ops = new_ops
        program._bump_version()


@register_pass("fc_fuse_pass")
class FCFusePass(Pass):
    """Fuse mul(X, W) + elementwise_add(., b) [+ relu] into one `fc` op
    (ir/fc_fuse_pass.cc).  Conditions mirror the reference pattern: the mul
    output feeds exactly the add, the bias is a 1-D persistable, and (for
    the act variant) the add output feeds exactly the relu."""

    def apply(self, program, scope):
        from .framework import Operator

        block = program.global_block()
        consumers = _build_consumers(block)

        def only_consumer(name, want_type):
            cons = consumers.get(name, [])
            if (len(cons) == 1 and cons[0].type == want_type
                    and name not in self.protected):
                return cons[0]
            return None

        skip = set()
        new_ops = []
        for op in block.ops:
            if id(op) in skip:
                continue
            if (op.type == "mul"
                    and int(op.attrs.get("y_num_col_dims", 1)) == 1):
                mul_out = op.output("Out")[0]
                add = only_consumer(mul_out, "elementwise_add")
                if add is not None:
                    b_name = add.input("Y")[0]
                    bvar = block._find_var_recursive(b_name)
                    # bias must broadcast along the LAST dim (fc semantics):
                    # for a 2-D mul output that is axis -1 or 1
                    axis_ok = int(add.attrs.get("axis", -1)) in (-1, 1)
                    if (bvar is not None and bvar.persistable
                            and bvar.shape is not None
                            and len(bvar.shape) == 1 and axis_ok
                            and add.input("X")[0] == mul_out):
                        act = ""
                        out_name = add.output("Out")[0]
                        relu = only_consumer(out_name, "relu")
                        tail_ops = [add]
                        if relu is not None:
                            act = "relu"
                            out_name = relu.output("Out")[0]
                            tail_ops.append(relu)
                        new_ops.append(Operator(
                            block, type="fc",
                            inputs={"Input": [op.input("X")[0]],
                                    "W": [op.input("Y")[0]],
                                    "Bias": [b_name]},
                            outputs={"Out": [out_name]},
                            attrs={"in_num_col_dims": int(op.attrs.get(
                                "x_num_col_dims", 1)),
                                "activation_type": act}))
                        skip.update(id(t) for t in tail_ops)
                        continue
            new_ops.append(op)
        block.ops = new_ops
        program._bump_version()


@register_pass("repeated_fc_relu_fuse_pass")
class RepeatedFCReluFusePass(Pass):
    """Fuse chains of relu-activated `fc` ops into one
    fusion_repeated_fc_relu op (ir/repeated_fc_relu_fuse_pass.cc).  The
    fused kernel applies fc+bias+relu to EVERY layer
    (fusion_repeated_fc_relu_op.cc:118-139), so only all-relu chains are
    eligible; a terminal plain fc stays unfused.  Run after fc_fuse_pass,
    which creates the fc ops this pass stitches."""

    MIN_CHAIN = 2

    def apply(self, program, scope):
        from .framework import Operator

        block = program.global_block()
        consumers = _build_consumers(block)
        producers = {}
        for op in block.ops:
            for n in op.output_arg_names:
                producers[n] = op

        def _eligible(o):
            # fusion_repeated_fc_relu does raw x @ w (no flattening) and
            # requires a Bias per fc: only fuse plain 2-D fcs with bias
            if int(o.attrs.get("in_num_col_dims", 1)) != 1:
                return False
            if not o.input("Bias"):
                return False
            v = block._find_var_recursive(o.input("Input")[0])
            return (v is not None and v.shape is not None
                    and len(v.shape) == 2)

        chains = []  # list of op lists
        used = set()
        for op in block.ops:
            if op.type != "fc" or id(op) in used:
                continue
            # only start a chain at a relu-activated fc whose producer
            # could NOT itself chain into it (true chain head): the skip
            # must mirror the extension conditions below, else a producer
            # with a multi-consumer/protected output blocks its consumer
            # from heading a valid chain
            if op.attrs.get("activation_type") != "relu":
                continue
            if not _eligible(op):
                continue
            in_name = op.input("Input")[0]
            prev = producers.get(in_name)
            if (prev is not None and prev.type == "fc"
                    and prev.attrs.get("activation_type") == "relu"
                    and _eligible(prev)
                    and len(consumers.get(in_name, [])) == 1
                    and in_name not in self.protected):
                continue
            chain = [op]
            cur = op
            while True:
                out_n = cur.output("Out")[0]
                nxt_cons = consumers.get(out_n, [])
                if (len(nxt_cons) != 1 or nxt_cons[0].type != "fc"
                        or out_n in self.protected
                        or not _eligible(nxt_cons[0])
                        or nxt_cons[0].attrs.get(
                            "activation_type") != "relu"):
                    break
                cur = nxt_cons[0]
                chain.append(cur)
            if len(chain) >= self.MIN_CHAIN:
                chains.append(chain)
                used.update(id(o) for o in chain)

        if not chains:
            return
        replaced = {}
        for chain in chains:
            head, tail = chain[0], chain[-1]
            relu_outs = [o.output("Out")[0] + "@fused_relu"
                         for o in chain[:-1]]
            for n in relu_outs:
                block.create_var(name=n)
            fused = Operator(
                block, type="fusion_repeated_fc_relu",
                inputs={"X": [head.input("Input")[0]],
                        "W": [o.input("W")[0] for o in chain],
                        "Bias": [o.input("Bias")[0] for o in chain]},
                outputs={"ReluOut": relu_outs,
                         "Out": [tail.output("Out")[0]]})
            replaced[id(head)] = fused
            for o in chain[1:]:
                replaced[id(o)] = None
        _commit_replacements(program, block, replaced)


def _sole_consumer(consumers, name, protected):
    """The single op reading `name`, or None if 0/many or protected."""
    cons = consumers.get(name, [])
    if len(cons) != 1 or name in protected:
        return None
    return cons[0]


def _commit_replacements(program, block, replaced):
    """Rewrite block.ops from a {id(op): new_op|None} map (None deletes;
    missing keeps) and bump the program version.  Shared epilogue of the
    fusion passes."""
    if not replaced:
        return
    block.ops = [replaced.get(id(op), op) for op in block.ops
                 if replaced.get(id(op), op) is not None]
    program._bump_version()


@register_pass("multihead_matmul_fuse_pass")
class MultiheadMatmulFusePass(Pass):
    """Rewrite composed scaled-dot-product attention into the fused
    `flash_attention` op (the TPU-native analog of
    ir/multihead_matmul_fuse_pass.cc constructing multihead_matmul_op.cu).

    Pattern (the repo's own layer emission, models/bert.py and
    nets.scaled_dot_product_attention):

        matmul(Q, K, transpose_Y=True[, alpha])
          -> [elementwise_add(scores, mask)]
          -> softmax
          -> [assign        # residue of delete_dropout_pass]
          -> matmul(probs, V)

    with Q/K/V rank-4 [B, H, S, D].  Replaced by one flash_attention op
    (Pallas blockwise kernel above the measured seq cutoff, XLA-fused jnp
    composition below it — either way >= the op-at-a-time composition).
    alpha becomes the kernel scale; alpha == 1.0 passes scale=1.0 ("already
    scaled", e.g. a separate upstream scale op) rather than the 1/sqrt(d)
    default that scale=0.0 selects."""

    def apply(self, program, scope):
        from .framework import Operator

        block = program.global_block()
        consumers = _build_consumers(block)

        def rank(name):
            v = block._find_var_recursive(name)
            return None if v is None or v.shape is None else len(v.shape)

        matches = []
        for op in block.ops:
            if op.type != "matmul":
                continue
            if not op.attrs.get("transpose_Y") or op.attrs.get(
                    "transpose_X"):
                continue
            q_name, k_name = op.input("X")[0], op.input("Y")[0]
            if rank(q_name) != 4 or rank(k_name) != 4:
                continue
            chain = [op]
            mask_name = None
            cur = _sole_consumer(consumers, op.output("Out")[0],
                                 self.protected)
            if cur is not None and cur.type == "elementwise_add":
                if cur.input("X")[0] != op.output("Out")[0]:
                    continue  # scores must be the X side
                if rank(cur.input("Y")[0]) != 4:
                    continue  # kernel bias contract: [B, 1|H, Sq, Sk]
                mask_name = cur.input("Y")[0]
                chain.append(cur)
                cur = _sole_consumer(consumers, cur.output("Out")[0],
                                     self.protected)
            if cur is None or cur.type != "softmax":
                continue
            ax = cur.attrs.get("axis", -1)
            if ax not in (-1, 3):
                continue
            chain.append(cur)
            cur = _sole_consumer(consumers, cur.output("Out")[0],
                                 self.protected)
            while cur is not None and cur.type == "assign":
                chain.append(cur)
                cur = _sole_consumer(consumers, cur.output("Out")[0],
                                     self.protected)
            if (cur is None or cur.type != "matmul"
                    or cur.attrs.get("transpose_X")
                    or cur.attrs.get("transpose_Y")
                    or float(cur.attrs.get("alpha", 1.0)) != 1.0
                    or cur.input("X")[0] != chain[-1].output("Out")[0]):
                continue
            v_name = cur.input("Y")[0]
            if rank(v_name) != 4:
                continue
            chain.append(cur)
            matches.append((chain, q_name, k_name, v_name, mask_name))

        if not matches:
            return
        replaced = {}
        for chain, q_name, k_name, v_name, mask_name in matches:
            alpha = float(chain[0].attrs.get("alpha", 1.0))
            inputs = {"Q": [q_name], "K": [k_name], "V": [v_name]}
            if mask_name is not None:
                inputs["BiasQK"] = [mask_name]
            fused = Operator(
                block, type="flash_attention", inputs=inputs,
                outputs={"Out": [chain[-1].output("Out")[0]]},
                attrs={"causal": False, "scale": alpha})
            replaced[id(chain[0])] = fused
            for o in chain[1:]:
                replaced[id(o)] = None
        _commit_replacements(program, block, replaced)


@register_pass("fuse_elewise_add_act_pass")
class FuseElewiseAddActPass(Pass):
    """elementwise_add -> {relu,tanh,sigmoid} becomes one
    fused_elemwise_activation op (ir/fuse_elewise_add_act_pass.cc)."""

    ACTS = ("relu", "tanh", "sigmoid")

    def apply(self, program, scope):
        from .framework import Operator

        block = program.global_block()
        consumers = _build_consumers(block)
        replaced = {}
        for op in block.ops:
            if op.type != "elementwise_add" or id(op) in replaced:
                continue
            nxt = _sole_consumer(consumers, op.output("Out")[0],
                                 self.protected)
            if nxt is None or nxt.type not in self.ACTS:
                continue
            if id(nxt) in replaced:
                continue
            fused = Operator(
                block, type="fused_elemwise_activation",
                inputs={"X": [op.input("X")[0]],
                        "Y": [op.input("Y")[0]]},
                outputs={"Out": [nxt.output("Out")[0]],
                         "IntermediateOut": [op.output("Out")[0]]},
                attrs={"functor_list": [nxt.type, "elementwise_add"],
                       "axis": int(op.attrs.get("axis", -1)),
                       "save_intermediate_out": True})
            replaced[id(op)] = fused
            replaced[id(nxt)] = None
        _commit_replacements(program, block, replaced)


@register_pass("seqpool_concat_fuse_pass")
class SeqPoolConcatFusePass(Pass):
    """N sequence_pool(pooltype) branches feeding one concat fuse into
    fusion_seqpool_concat (ir/seqpool_concat_fuse_pass.cc)."""

    POOLTYPES = ("SUM", "AVERAGE", "SQRT")

    def apply(self, program, scope):
        from .framework import Operator

        block = program.global_block()
        consumers = _build_consumers(block)
        producers = {}
        for op in block.ops:
            for n in op.output_arg_names:
                producers[n] = op
        replaced = {}
        for op in block.ops:
            if op.type != "concat" or id(op) in replaced:
                continue
            if int(op.attrs.get("axis", 0)) not in (1, -1):
                continue
            branches = []
            pooltype = None
            ok = True
            for n in op.input("X"):
                prod = producers.get(n)
                if (prod is None or prod.type != "sequence_pool"
                        or id(prod) in replaced
                        or prod.input("Length")
                        or _sole_consumer(consumers, n,
                                          self.protected) is not op):
                    ok = False
                    break
                # pooled output must be rank-2 (input [B, T, D]) so the
                # fused op's axis=-1 concat equals this concat's axis=1
                xv = block._find_var_recursive(prod.input("X")[0])
                if xv is None or xv.shape is None or len(xv.shape) != 3:
                    ok = False
                    break
                pt = prod.attrs.get("pooltype", "AVERAGE").upper()
                if pt not in self.POOLTYPES or (pooltype is not None
                                                and pt != pooltype):
                    ok = False
                    break
                pooltype = pt
                branches.append(prod)
            if not ok or len(branches) < 2:
                continue
            fused = Operator(
                block, type="fusion_seqpool_concat",
                inputs={"X": [b.input("X")[0] for b in branches]},
                outputs={"Out": [op.output("Out")[0]]},
                attrs={"pooltype": pooltype, "axis": 1})
            replaced[id(op)] = fused
            for b in branches:
                replaced[id(b)] = None
        _commit_replacements(program, block, replaced)


@register_pass("fuse_optimizer_ops_pass")
class FuseOptimizerOpsPass(Pass):
    """Coalesce per-parameter optimizer ops into one fused update
    (ir/fuse_optimizer_ops_pass.cc + coalesce_tensor: fuse_adam /
    fuse_sgd / fuse_momentum).  Groups ops of one type sharing the same
    hyperparameter attrs + LearningRate var + param dtype; each group
    becomes one fused_<type> op over duplicable input/output lists, placed
    at the LAST member's position.  A group is skipped when a non-member
    op between the first and last member reads or writes any of the
    group's state vars, or WRITES the shared LearningRate var (ordering
    hazards), or when adam uses per-op beta tensors.  Divergent adam
    beta-pow accumulators are safe: fused_adam applies each member's own
    bias correction."""

    MIN_GROUP = 4
    # fuse only params of rank <= this (0 = no restriction).  None reads
    # FLAGS_fuse_optimizer_max_rank at apply time (default 2: BERT's 2-D
    # encoder weights + embeddings fuse into one adam group; 4-D conv
    # kernels stay unfused — flattening tiled TPU layouts costs relayout
    # copies that exceed the launch savings).  1-D params (BN gamma/beta,
    # biases) are linear-layout so concat is copy-free at any setting.
    max_param_rank = None
    _STATE_SLOTS = {
        "sgd": ("Param", "Grad"),
        "momentum": ("Param", "Grad", "Velocity"),
        "adam": ("Param", "Grad", "Moment1", "Moment2", "Beta1Pow",
                 "Beta2Pow"),
    }
    _FUSED_ATTRS = {
        "sgd": (),
        "momentum": ("mu", "use_nesterov", "regularization_method",
                     "regularization_coeff"),
        "adam": ("beta1", "beta2", "epsilon"),
    }
    _META_ATTRS = frozenset({"op_role", "op_role_var", "op_namescope",
                             "op_callstack", "op_device"})

    def apply(self, program, scope):
        from .framework import Operator

        block = program.global_block()
        pos = {id(op): i for i, op in enumerate(block.ops)}
        groups = {}
        for op in block.ops:
            if op.type not in self._STATE_SLOTS:
                continue
            if op.type == "adam" and (op.input("Beta1Tensor")
                                      or op.input("Beta2Tensor")):
                continue
            pv = block._find_var_recursive(op.input("Param")[0])
            attrs_key = tuple(
                (k, tuple(v) if isinstance(v, list) else v)
                for k, v in sorted(op.attrs.items())
                if k not in self._META_ATTRS)
            key = (op.type, op.input("LearningRate")[0],
                   None if pv is None else pv.dtype, attrs_key)
            groups.setdefault(key, []).append(op)

        if self.max_param_rank is None:
            from .flags import flag as _flag
            max_rank = int(_flag("fuse_optimizer_max_rank") or 0)
        else:
            max_rank = int(self.max_param_rank)
        replaced = {}
        for (op_type, lr_name, _dt, _ak), ops in groups.items():
            if max_rank:
                # restrict fusion to low-rank params: flattening tiled
                # TPU layouts (4-D conv kernels) costs relayout copies
                # that exceed the launch savings (round-3 measurement:
                # fuse-everything = 1786 img/s vs 2200 unfused)
                ops = [o for o in ops
                       if (lambda v: v is not None and v.shape is not None
                           and len(v.shape) <= max_rank)(
                               block._find_var_recursive(
                                   o.input("Param")[0]))]
            if len(ops) < self.MIN_GROUP:
                continue
            slots = self._STATE_SLOTS[op_type]
            state = set()
            for o in ops:
                for s in slots:
                    state.update(o.input(s))
                state.update(o.output_arg_names)
            if state & self.protected:
                continue
            member = set(id(o) for o in ops)
            lo = min(pos[id(o)] for o in ops)
            hi = max(pos[id(o)] for o in ops)
            hazard = False
            for other in block.ops[lo:hi + 1]:
                if id(other) in member:
                    continue
                touched = set(other.input_arg_names) | set(
                    other.output_arg_names)
                # a write to the shared LR between members would make the
                # single fused read diverge from the unfused sequence
                if (touched & state
                        or lr_name in other.output_arg_names):
                    hazard = True
                    break
            if hazard:
                continue
            inputs = {s: [o.input(s)[0] for o in ops] for s in slots}
            inputs["LearningRate"] = [lr_name]
            out_slot_map = {"sgd": ("ParamOut",),
                            "momentum": ("ParamOut", "VelocityOut"),
                            "adam": ("ParamOut", "Moment1Out",
                                     "Moment2Out", "Beta1PowOut",
                                     "Beta2PowOut")}[op_type]
            outputs = {s: [o.output(s)[0] for o in ops]
                       for s in out_slot_map}
            attrs = {k: ops[0].attrs.get(k)
                     for k in self._FUSED_ATTRS[op_type]
                     if k in ops[0].attrs}
            fused = Operator(block, type="fused_" + op_type,
                             inputs=inputs, outputs=outputs, attrs=attrs)
            last = max(ops, key=lambda o: pos[id(o)])
            for o in ops:
                replaced[id(o)] = fused if o is last else None
        _commit_replacements(program, block, replaced)
