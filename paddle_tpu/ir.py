"""Program-level IR passes (parity: paddle/fluid/framework/ir/ —
ir::Pass + PassRegistry, ir/pass.h:38).

Most of the reference's ~75 passes dissolve into XLA (fusion, memory reuse,
placement).  What remains meaningful at the program level are
*graph-rewriting* optimizations whose benefit XLA cannot recover because
they change the parameter values themselves or delete stateful ops:

- conv_bn_fuse_pass (ir/conv_bn_fuse_pass.cc): fold an inference-mode
  batch_norm into the preceding conv2d's weights/bias.  Removes the BN op
  and its four parameter reads entirely.
- delete_dropout_pass (delete_dropout_op_pass): drop is_test dropout ops
  (identity at inference).

Passes run on (Program, Scope) pairs — the scope carries the parameter
values a folding pass rewrites, mirroring how the reference's passes read
the global scope for persistables."""

import numpy as np

__all__ = ["Pass", "register_pass", "get_pass", "apply_pass", "all_passes"]

_PASS_REGISTRY = {}


class Pass:
    """Base class (ir/pass.h:38 analog): override apply(program, scope)."""

    name = None

    def apply(self, program, scope):
        raise NotImplementedError


def register_pass(name):
    def deco(cls):
        cls.name = name
        _PASS_REGISTRY[name] = cls
        return cls

    return deco


def get_pass(name):
    return _PASS_REGISTRY[name]()


def all_passes():
    return sorted(_PASS_REGISTRY)


def apply_pass(name, program, scope):
    """Apply one registered pass in place; returns the program."""
    get_pass(name).apply(program, scope)
    return program


@register_pass("delete_dropout_pass")
class DeleteDropoutPass(Pass):
    """Replace is_test dropout ops with `assign` (identity).  Using assign
    instead of deleting + rewiring keeps every output var produced — fetch
    targets and chained dropouts stay valid — and XLA folds the copy."""

    def apply(self, program, scope):
        from .framework import Operator

        block = program.global_block()
        new_ops = []
        for op in block.ops:
            if (op.type == "dropout" and op.attrs.get("is_test")
                    and op.attrs.get("dropout_implementation")
                    == "upscale_in_train"):
                # upscale_in_train is identity at test time; downgrade
                # mode rescales, so only upscale is replaceable
                new_ops.append(Operator(
                    block, type="assign",
                    inputs={"X": [op.input("X")[0]]},
                    outputs={"Out": [op.output("Out")[0]]}, attrs={}))
            else:
                new_ops.append(op)
        block.ops = new_ops
        program._bump_version()


@register_pass("conv_bn_fuse_pass")
class ConvBNFusePass(Pass):
    """Fold inference batch_norm into the preceding conv2d
    (ir/conv_bn_fuse_pass.cc): W' = W * gamma/std (per out-channel),
    b' = beta - mean * gamma/std; the BN op is replaced by one
    elementwise_add of b'."""

    def apply(self, program, scope):
        block = program.global_block()
        # conv output name -> conv op, only when that output feeds exactly
        # one consumer (the BN)
        consumers = {}
        filter_uses = {}
        for op in block.ops:
            for n in op.input_arg_names:
                consumers.setdefault(n, []).append(op)
            if op.type == "conv2d":
                f = op.input("Filter")[0]
                filter_uses[f] = filter_uses.get(f, 0) + 1

        new_ops = []
        i = 0
        ops = block.ops
        while i < len(ops):
            op = ops[i]
            fused = False
            if op.type == "conv2d":
                out = op.output("Output")[0]
                cons = consumers.get(out, [])
                w_name = op.input("Filter")[0]
                # a filter shared by several convs (siamese nets) can't be
                # folded — scaling it would corrupt the other conv
                if (len(cons) == 1 and cons[0].type == "batch_norm"
                        and cons[0].attrs.get("is_test")
                        and filter_uses.get(w_name, 0) == 1):
                    bn = cons[0]
                    names = {s: bn.input(s)[0] for s in
                             ("Scale", "Bias", "Mean", "Variance")}
                    vals = {}
                    ok = True
                    for s, n in names.items():
                        v = scope.find_var(n)
                        if v is None or not v.get_tensor()._is_initialized():
                            ok = False
                            break
                        vals[s] = np.asarray(v.get_tensor().numpy())
                    wvar = scope.find_var(w_name)
                    if ok and wvar is not None and \
                            wvar.get_tensor()._is_initialized():
                        eps = float(bn.attrs.get("epsilon", 1e-5))
                        std = np.sqrt(vals["Variance"] + eps)
                        factor = vals["Scale"] / std          # [O]
                        W = np.asarray(wvar.get_tensor().numpy())
                        wvar.get_tensor().set(
                            (W * factor.reshape(-1, 1, 1, 1)).astype(W.dtype))
                        bias = vals["Bias"] - vals["Mean"] * factor
                        # keyed by the BN output: unique per fused pair
                        bias_name = bn.output("Y")[0] + "@bn_fused_bias"
                        bvar = block.create_var(
                            name=bias_name, shape=[len(bias)],
                            dtype="float32", persistable=True)
                        scope.var(bias_name).set(bias.astype("float32"))
                        bn_out = bn.output("Y")[0]
                        from .framework import Operator

                        add = Operator(
                            block, type="elementwise_add",
                            inputs={"X": [out], "Y": [bias_name]},
                            outputs={"Out": [bn_out]},
                            attrs={"axis": 1})
                        new_ops.append(op)
                        new_ops.append(add)
                        i += 1
                        # skip every op up to and including the BN (they
                        # are contiguous in topological emit order)
                        while ops[i] is not bn:
                            new_ops.append(ops[i])
                            i += 1
                        i += 1  # past the bn
                        fused = True
            if not fused:
                new_ops.append(op)
                i += 1
        block.ops = new_ops
        program._bump_version()
