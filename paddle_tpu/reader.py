"""DataLoader / PyReader: the host-side input pipeline.

Parity: python/paddle/fluid/reader.py (DataLoader.from_generator:75,
PyReader, GeneratorLoader) + operators/reader/buffered_reader.cc (async
double buffering).  TPU-native shape: a producer thread feeds batches into
the native C++ blocking queue (paddle_tpu/native/csrc/dataqueue.cc); the
consumer side optionally stages the *next* batch onto the device with
``jax.device_put`` while the current one is being consumed, so host→HBM
copies overlap compute (the buffered_reader double-buffer analog).

Non-iterable mode keeps the reference's program-driven contract: after
``loader.start()``, ``exe.run(program)`` with no feed pulls the next batch
from the queue and raises ``fluid.core.EOFException`` when the epoch ends.
"""

import threading

import numpy as np

from .framework import Variable, core, dtype_to_np
from .reader_decorator import (  # noqa: F401  (paddle.reader.* decorators)
    batch, buffered, cache, chain, compose, firstn, map_readers,
    multiprocess_reader, shuffle, xmap_readers,
)

__all__ = ["DataLoader", "PyReader", "GeneratorLoader"]


class EOFException(Exception):
    """Raised by exe.run when a started (non-iterable) DataLoader drains."""


core.EOFException = EOFException  # framework._CoreShim
from . import core as _core_pkg  # noqa: E402  (fluid.core resolves here)

_core_pkg.EOFException = EOFException


def _to_numpy_batch(items, feed_vars):
    """Coerce one batch (tuple/list of arrays) to the feed vars' dtypes."""
    out = []
    for i, x in enumerate(items):
        arr = np.asarray(x)
        if feed_vars and i < len(feed_vars):
            v = feed_vars[i]
            if v.dtype is not None:
                want = dtype_to_np(v.dtype)
                if arr.dtype != want:
                    arr = arr.astype(want)
        out.append(arr)
    return out


class GeneratorLoader:
    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False, drop_last=True):
        if feed_list is None:
            feed_list = []
        self._feed_vars = [v for v in feed_list]
        for v in self._feed_vars:
            if not isinstance(v, Variable):
                raise TypeError("feed_list must contain Variables")
        self._names = [v.name for v in self._feed_vars]
        self._capacity = capacity
        self._use_double_buffer = use_double_buffer
        self._iterable = iterable
        self._return_list = return_list
        self._drop_last = drop_last
        self._batch_reader = None
        self._places = None
        self._queue = None
        self._thread = None
        self._started = False
        self._producer_exc = None
        self._iter = None  # persistent iterator for next()
        if not iterable:
            # program-driven mode: attach to the program that owns the feed
            # vars so Executor.run(program, feed=None) can find us; a new
            # loader over the same feed names replaces the old one
            if not self._feed_vars:
                raise ValueError("non-iterable DataLoader needs a feed_list")
            program = self._feed_vars[0].block.program
            program._attached_loaders = [
                l for l in program._attached_loaders
                if set(l._names) != set(self._names)
            ] + [self]

    # -- wiring --------------------------------------------------------------
    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        def batch_reader():
            batch = []
            for sample in reader():
                if not isinstance(sample, (list, tuple)):
                    sample = (sample,)
                batch.append(sample)
                if len(batch) == batch_size:
                    yield [np.stack([np.asarray(s[i]) for s in batch])
                           for i in range(len(batch[0]))]
                    batch = []
            if batch and not drop_last:
                yield [np.stack([np.asarray(s[i]) for s in batch])
                       for i in range(len(batch[0]))]

        return self.set_batch_generator(batch_reader, places)

    def set_sample_list_generator(self, reader, places=None):
        def batch_reader():
            for batch in reader():
                yield [np.stack([np.asarray(s[i]) for s in batch])
                       for i in range(len(batch[0]))]

        return self.set_batch_generator(batch_reader, places)

    def set_batch_generator(self, reader, places=None):
        self._batch_reader = reader
        self._places = places
        return self

    # -- producer ------------------------------------------------------------
    def _producer(self, queue):
        from .native.queue import QueueClosed

        try:
            for batch in self._batch_reader():
                if not isinstance(batch, (list, tuple)):
                    batch = (batch,)
                if isinstance(batch, (list, tuple)) and len(batch) == 1 and \
                        isinstance(batch[0], dict):
                    batch = [batch[0][n] for n in self._names]
                if isinstance(batch, dict):
                    batch = [batch[n] for n in self._names]
                try:
                    queue.push(_to_numpy_batch(batch, self._feed_vars))
                except QueueClosed:
                    return
        except BaseException as e:  # surface in the consumer, not stderr
            self._producer_exc = e
        finally:
            queue.close()

    def _start_thread(self):
        from .native.queue import NativeBlockingQueue

        if self._batch_reader is None:
            raise RuntimeError(
                "DataLoader has no data source — call set_sample_generator/"
                "set_sample_list_generator/set_batch_generator first")
        self._producer_exc = None
        self._queue = NativeBlockingQueue(self._capacity)
        self._thread = threading.Thread(
            target=self._producer, args=(self._queue,), daemon=True)
        self._thread.start()
        self._started = True

    def _stop(self, queue=None):
        if queue is not None and queue is not self._queue:
            # stale generator cleanup: kill only its own (abandoned) queue,
            # never the currently active pipeline
            queue.kill()
            return
        if self._queue is not None:
            self._queue.kill()
        if self._thread is not None:
            try:
                self._thread.join(timeout=5)
            except TypeError:
                pass  # interpreter teardown: threading internals cleared
        self._queue = None
        self._thread = None
        self._started = False

    def _check_producer(self):
        if self._producer_exc is not None:
            exc, self._producer_exc = self._producer_exc, None
            raise RuntimeError("DataLoader generator raised") from exc

    # -- iterable mode -------------------------------------------------------
    def __iter__(self):
        from .native.queue import QueueClosed

        if self._iterable is False:
            raise RuntimeError("this DataLoader is non-iterable; use "
                               "start()/reset() with exe.run()")
        self._stop()
        self._start_thread()
        dev = self._device()
        queue = self._queue

        def gen():
            pending = None  # device-staged batch (double buffer)
            try:
                while True:
                    try:
                        batch = queue.pop()
                    except QueueClosed:
                        batch = None
                    if batch is None:
                        self._check_producer()
                    if self._use_double_buffer and dev is not None:
                        staged = pending
                        if batch is not None:
                            import jax

                            pending = [jax.device_put(a, dev) for a in batch]
                        else:
                            pending = None
                        if staged is None:
                            if pending is None:
                                return
                            continue  # prime the buffer
                        yield self._emit(staged)
                    else:
                        if batch is None:
                            return
                        yield self._emit(batch)
            finally:
                self._stop(queue)

        return gen()

    def _device(self):
        if not self._use_double_buffer:
            return None
        places = self._places
        if places:
            p = places[0] if isinstance(places, (list, tuple)) else places
            try:
                return p.jax_device()
            except Exception:
                return None
        return None

    def _emit(self, batch):
        if self._return_list:
            return list(batch)
        return dict(zip(self._names, batch))

    # -- program-driven (non-iterable) mode ----------------------------------
    def start(self):
        if self._iterable:
            raise RuntimeError("start() is only for non-iterable loaders")
        self._stop()
        self._start_thread()

    def reset(self):
        self._stop()

    def _next_feed(self):
        """Called by Executor.run(feed=None). Raises EOFException at end."""
        from .native.queue import QueueClosed

        if not self._started:
            raise RuntimeError("DataLoader.start() was not called")
        try:
            batch = self._queue.pop()
        except QueueClosed:
            batch = None
        if batch is None:
            self._check_producer()
            raise EOFException("data loader drained")
        return dict(zip(self._names, batch))

    # reference-API convenience: successive batches from one live epoch
    def next(self):
        if self._iter is None:
            self._iter = iter(self)
        try:
            return next(self._iter)
        except StopIteration:
            self._iter = None
            raise


class DataLoader:
    @staticmethod
    def from_generator(feed_list=None, capacity=64, use_double_buffer=True,
                       iterable=True, return_list=False, use_multiprocess=False,
                       drop_last=True):
        # use_multiprocess accepted for API parity; the native queue +
        # thread producer already overlaps host work with device steps
        return GeneratorLoader(feed_list, capacity, use_double_buffer,
                               iterable, return_list, drop_last)

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        from .dataset import DatasetLoader

        return DatasetLoader(dataset, places, drop_last)


class PyReader:
    """Legacy fluid.io.PyReader facade over GeneratorLoader
    (python/paddle/fluid/reader.py PyReader)."""

    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False):
        self._loader = GeneratorLoader(feed_list, capacity, use_double_buffer,
                                       iterable, return_list)

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        self._loader.set_sample_generator(sample_generator, batch_size,
                                          drop_last, places)

    def decorate_sample_list_generator(self, reader, places=None):
        self._loader.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        self._loader.set_batch_generator(reader, places)

    def start(self):
        self._loader.start()

    def reset(self):
        self._loader.reset()

    def __iter__(self):
        return iter(self._loader)
