"""Deprecated high-level Inferencer API.

Parity: python/paddle/fluid/contrib/inferencer.py:31 (deprecated
upstream; kept for user-code compatibility).
"""

import contextlib

from .. import io
from ..core.executor import Executor, scope_guard
from ..core.scope import Scope
from ..framework import Program, program_guard
from .trainer import check_and_get_place

__all__ = ["Inferencer"]


class Inferencer(object):
    """infer_func() rebuilds the prediction network; parameters load from
    param_path; infer(inputs) runs a feed-dict through it."""

    def __init__(self, infer_func, param_path, place=None, parallel=False):
        self.param_path = param_path
        self.scope = Scope()
        self.parallel = parallel
        self.place = check_and_get_place(place)
        from ..utils import unique_name

        self.inference_program = Program()
        with program_guard(self.inference_program):
            # fresh name scope so infer_func recreates the SAME parameter
            # names train_func did (the reference wraps infer_func in
            # unique_name.guard())
            with unique_name.guard():
                self.predict_var = infer_func()
        self.exe = Executor(self.place)
        with self._prog_and_scope_guard():
            io.load_persistables(self.exe, param_path,
                                 main_program=self.inference_program)
        self.inference_program = self.inference_program.clone(for_test=True)

    def infer(self, inputs, return_numpy=True):
        if not isinstance(inputs, dict):
            raise ValueError(
                "inputs should be a map of {'input_name': input_var}")
        with self._prog_and_scope_guard():
            return self.exe.run(self.inference_program, feed=inputs,
                                fetch_list=[self.predict_var.name],
                                return_numpy=return_numpy)

    @contextlib.contextmanager
    def _prog_and_scope_guard(self):
        with program_guard(main_program=self.inference_program):
            with scope_guard(self.scope):
                yield
