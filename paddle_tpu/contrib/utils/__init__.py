"""contrib.utils — HDFS transfer helpers + distributed-lookup-table
checkpoint utilities (parity:
python/paddle/fluid/contrib/utils/__init__.py:15)."""

from . import hdfs_utils
from .hdfs_utils import *  # noqa: F401,F403
from . import lookup_table_utils
from .lookup_table_utils import *  # noqa: F401,F403

__all__ = hdfs_utils.__all__ + lookup_table_utils.__all__
