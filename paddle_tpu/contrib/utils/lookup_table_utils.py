"""Distributed-lookup-table checkpoint/conversion helpers.

Parity: python/paddle/fluid/contrib/utils/lookup_table_utils.py:28 —
convert_dist_to_sparse_program, load_persistables_for_increment,
load_persistables_for_inference, get_inference_model.

The reference operates on transpiled trainer/pserver programs whose
distributed lookups are prefetch-op triples; in this framework the
transpiler emits `distributed_lookup_table` ops (layers/nn.py embedding
with is_distributed=True; distributed/sparse_table.py holds the sharded
table).  The conversions therefore rewrite between that op and the plain
local `lookup_table`, and the loaders combine the repo's persistable
loader with the sharded-table piece files the PS path saves.
"""

import logging
import os

import numpy as np

from ... import io as _io
from ...distribute_lookup_table import find_distributed_lookup_table
from ...framework import Program

__all__ = [
    "load_persistables_for_increment", "load_persistables_for_inference",
    "convert_dist_to_sparse_program",
]

_logger = logging.getLogger(__name__)

model_filename = "__model__"
lookup_table_dir = "__lookup_table__"


def convert_dist_to_sparse_program(program):
    """Rewrite `distributed_lookup_table` ops to LOCAL lookups so a
    program trained against remote sharded tables can run local
    inference over the merged table (reference
    lookup_table_utils.py:85).  Returns the same program, modified."""
    table_name = find_distributed_lookup_table(program)
    if not table_name:
        _logger.warning(
            "There are no distributed lookup tables need to be converted")
        return program
    block = program.global_block()
    for op in block.ops:
        if (op.type == "distributed_lookup_table"
                and table_name in op.input("W")):
            op.type = "lookup_table"
            op.attrs.setdefault("is_sparse", True)
            op.attrs["is_distributed"] = False
            op.attrs.pop("endpoints", None)
            op.attrs.pop("table_names", None)
        elif (op.type == "lookup_table" and table_name in op.input("W")
              and op.attrs.get("is_distributed")):
            op.attrs["is_distributed"] = False
    program._bump_version()
    return program


def _load_table_pieces(dirname_or_path):
    """Merge sharded lookup-table piece files (id -> row) saved by the
    pserver path: each piece is an .npz with `ids` and `rows`."""
    paths = []
    if os.path.isdir(dirname_or_path):
        for name in sorted(os.listdir(dirname_or_path)):
            paths.append(os.path.join(dirname_or_path, name))
    elif os.path.exists(dirname_or_path):
        paths = [dirname_or_path]
    merged = {}
    for path in paths:
        try:
            with np.load(path) as z:
                for gid, row in zip(z["ids"], z["rows"]):
                    merged[int(gid)] = row
        except Exception:
            continue
    return merged


def load_persistables_for_increment(dirname, executor, program,
                                    lookup_table_var,
                                    lookup_table_var_path):
    """Load dense persistables AND the correctly-sliced lookup-table var
    for resuming distributed training (reference :136).  The sliced
    rows in `lookup_table_var_path` overwrite their ids' rows in the
    in-scope table."""
    _io.load_persistables(executor, dirname, main_program=program)
    from ...core.executor import global_scope

    scope = global_scope()
    var = scope.find_var(lookup_table_var)
    if var is None:
        _logger.warning("lookup table var %r not found in scope",
                        lookup_table_var)
        return
    table = np.array(np.asarray(var.get_tensor()))
    for gid, row in _load_table_pieces(lookup_table_var_path).items():
        if 0 <= gid < table.shape[0]:
            table[gid] = row
    var.get_tensor().set(table, executor.place)


def load_persistables_for_inference(dirname, executor, program,
                                    lookup_table_var_name):
    """Load every persistable (excluding the usual fluid framework vars)
    plus the FULL merged lookup table for local inference
    (reference :260)."""
    _io.load_persistables(executor, dirname, main_program=program)
    table_dir = os.path.join(dirname, lookup_table_dir)
    pieces = _load_table_pieces(table_dir)
    if not pieces:
        return
    from ...core.executor import global_scope

    scope = global_scope()
    var = scope.find_var(lookup_table_var_name)
    if var is None:
        return
    table = np.array(np.asarray(var.get_tensor()))
    for gid, row in pieces.items():
        if 0 <= gid < table.shape[0]:
            table[gid] = row
    var.get_tensor().set(table, executor.place)


def get_inference_model(main_program, feeded_var_names, target_vars):
    """Prune `main_program` to an inference program over the given
    feeds/fetches, converting distributed lookups to local ones
    (reference :413)."""
    from ...framework import Variable, default_main_program

    if main_program is None:
        main_program = default_main_program()
    if not isinstance(feeded_var_names, list) or not all(
            isinstance(n, str) for n in feeded_var_names):
        raise ValueError("feeded_var_names should be a list of str.")
    if not isinstance(target_vars, list) or not all(
            isinstance(v, Variable) for v in target_vars):
        raise ValueError("target_vars should be a list of Variable.")
    pruned = main_program.clone(for_test=True)
    convert_dist_to_sparse_program(pruned)
    # prune to the fetch targets (the repo's inference-save pipeline)
    names = [v.name for v in target_vars]
    pruned = pruned._prune_with_input(feeded_var_names, names) \
        if hasattr(pruned, "_prune_with_input") else pruned
    return pruned
