"""HDFS utilities with sharded multi-process transfer.

Parity: python/paddle/fluid/contrib/utils/hdfs_utils.py:29 — HDFSClient
(recursive lsr / make_local_dirs on top of the core client in
utils/fs.py, which shells out to `hadoop fs` exactly like the
reference's __run_hdfs_cmd) plus multi_download / multi_upload: each
trainer takes its `trainer_id::trainers` shard of the file list and
moves it with a pool of workers.
"""

import logging
import multiprocessing.pool
import os

from ...utils import fs as _fs

__all__ = ["HDFSClient", "multi_download", "multi_upload"]

_logger = logging.getLogger(__name__)


class HDFSClient(_fs.HDFSClient):
    """contrib-surface HDFS client (reference hdfs_utils.HDFSClient).

    Extends the core client with the recursive listing and local-dir
    helpers the sharded transfer functions need."""

    @staticmethod
    def make_local_dirs(local_path):
        os.makedirs(local_path, exist_ok=True)

    def lsr(self, hdfs_path, only_file=True, sort=True):
        """Recursive listing of `hdfs_path` (file paths only by
        default), sorted by modification time like the reference."""
        p = self._run(["-lsr", hdfs_path], check=False)
        if p is None or p.returncode != 0:
            p = self._run(["-ls", "-R", hdfs_path])
        lines = []
        for line in p.stdout.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            perms, path = parts[0], parts[-1]
            if only_file and perms.startswith("d"):
                continue
            # [date, time] fields sort lexicographically == chronologically
            lines.append((parts[-3] + " " + parts[-2], path))
        if sort:
            lines.sort(key=lambda kv: kv[0])
        return [path for _, path in lines]


def _pool_run(fn, shards, multi_processes):
    # worker threads, not processes: each job shells out to `hadoop fs`,
    # so the parallelism lives in the subprocesses and threads sidestep
    # pickling the client
    with multiprocessing.pool.ThreadPool(max(multi_processes, 1)) as pool:
        pool.map(fn, shards)


def multi_download(client, hdfs_path, local_path, trainer_id, trainers,
                   multi_processes=5):
    """Download this trainer's shard (`trainer_id::trainers`) of the
    recursive file list under hdfs_path with a worker pool; returns the
    local paths downloaded (reference hdfs_utils.py:437)."""
    assert isinstance(client, _fs.HDFSClient)
    HDFSClient.make_local_dirs(local_path)
    all_files = client.lsr(hdfs_path, sort=True)
    need = all_files[trainer_id::trainers]
    _logger.info("multi_download: %d of %d files from %s", len(need),
                 len(all_files), hdfs_path)

    def _dest_dir(data):
        re_path = os.path.relpath(os.path.dirname(data), hdfs_path)
        return (local_path if re_path == os.curdir
                else os.path.join(local_path, re_path))

    def download_one(data):
        sub = _dest_dir(data)
        os.makedirs(sub, exist_ok=True)
        client.download(data, sub)

    _pool_run(download_one, need, multi_processes)
    # single source of truth for destinations: the same helper the
    # workers used
    return [os.path.join(_dest_dir(d), os.path.basename(d)) for d in need]


def getfilelist(path):
    rlist = []
    for d, _folders, files in os.walk(path):
        for f in files:
            rlist.append(os.path.join(d, f))
    return rlist


def multi_upload(client, hdfs_path, local_path, multi_processes=5,
                 overwrite=False, sync=True):
    """Upload everything under local_path with a worker pool
    (reference hdfs_utils.py:518)."""
    assert isinstance(client, _fs.HDFSClient)
    files = getfilelist(local_path)

    def upload_one(data):
        re_path = os.path.relpath(os.path.dirname(data), local_path)
        target = (hdfs_path if re_path == os.curdir
                  else "%s/%s" % (hdfs_path.rstrip("/"), re_path))
        client.makedirs(target)
        client.upload(target, data, overwrite=overwrite)

    _pool_run(upload_one, files, multi_processes)
    _logger.info("multi_upload: %d files to %s", len(files), hdfs_path)
    return files
