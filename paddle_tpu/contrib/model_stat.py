"""Model PARAMs/FLOPs summary table (parity: contrib/model_stat.py:36-194
`summary`).  Counts conv2d/depthwise_conv2d, pool2d, mul/fc-style matmul,
relu/sigmoid family, batch_norm — same per-op formulas as the reference
(NVIDIA convention, mul+add = 2 FLOPs).  Renders an ASCII table without
the prettytable dependency (zero-egress environment)."""

from collections import OrderedDict

__all__ = ["summary"]

_ACTS = ("relu", "sigmoid", "tanh", "relu6", "leaky_relu")


def _summary_model(block, op):
    def shape(name):
        v = block._find_var_recursive(name)
        return tuple(v.shape) if v is not None and v.shape else None

    if op.type in ("conv2d", "depthwise_conv2d"):
        k = shape(op.input("Filter")[0])
        inp = shape(op.input("Input")[0])
        out = shape(op.output("Output")[0])
        if None in (k, inp, out):
            return None
        c_out, c_in_per_group, k_h, k_w = k
        _, c_out_, h_out, w_out = out
        # Filter shape is [O, C/groups, kh, kw] (layers/nn.py conv2d):
        # the channel dim is ALREADY per-group — dividing by groups again
        # would zero out depthwise convs
        kernel_ops = k_h * k_w * c_in_per_group
        bias_ops = 0 if not op.input("Bias") else 1
        params = c_out * (kernel_ops + bias_ops)
        flops = 2 * h_out * w_out * c_out * (kernel_ops + bias_ops)
        return inp, out, params, flops
    if op.type == "pool2d":
        inp = shape(op.input("X")[0])
        out = shape(op.output("Out")[0])
        if None in (inp, out):
            return None
        _, c_out, h_out, w_out = out
        ksize = op.attrs.get("ksize", [1, 1])
        params = 0
        flops = h_out * w_out * c_out * ksize[0] * ksize[1]
        return inp, out, params, flops
    if op.type in ("mul", "matmul"):
        x = shape(op.input("X")[0])
        y = shape(op.input("Y")[0])
        out = shape(op.output("Out")[0])
        if None in (x, y, out):
            return None
        params = 1
        for d in y:
            params *= d
        flops = 2 * params * (x[0] if x[0] and x[0] > 0 else 1)
        return x, out, params, flops
    if op.type in _ACTS:
        inp = shape(op.input("X")[0])
        out = shape(op.output("Out")[0])
        if None in (inp, out):
            return None
        n = 1
        for d in out[1:]:
            n *= d
        return inp, out, 0, n
    if op.type == "batch_norm":
        inp = shape(op.input("X")[0])
        out = shape(op.output("Y")[0])
        if None in (inp, out):
            return None
        c = out[1]
        n = 1
        for d in out[1:]:
            n *= d
        # gamma, beta, mean, var
        return inp, out, 4 * c, n
    return None


def _render(rows, total_params, total_flops):
    headers = ["No.", "TYPE", "INPUT", "OUTPUT", "PARAMs", "FLOPs"]
    table = [[str(i), r["type"], str(r["input_shape"]),
              str(r["out_shape"]), str(r["PARAMs"]), str(r["FLOPs"])]
             for i, r in enumerate(rows)]
    widths = [max(len(h), *(len(t[c]) for t in table)) if table else len(h)
              for c, h in enumerate(headers)]
    sep = "+-" + "-+-".join("-" * w for w in widths) + "-+"
    lines = [sep,
             "| " + " | ".join(h.rjust(w)
                               for h, w in zip(headers, widths)) + " |",
             sep]
    for t in table:
        lines.append("| " + " | ".join(v.rjust(w)
                                       for v, w in zip(t, widths)) + " |")
    lines.append(sep)
    lines.append("Total PARAMs: %d(%.4fG)"
                 % (total_params, total_params / 1e9))
    lines.append("Total FLOPs: %d(%.2fG)" % (total_flops, total_flops / 1e9))
    return "\n".join(lines)


def summary(main_prog):
    """Print (and return) the per-op PARAMs/FLOPs table for a program."""
    rows = []
    for block in main_prog.blocks:
        for op in block.ops:
            res = _summary_model(block, op)
            if res is None:
                continue
            rows.append(OrderedDict(
                type=op.type,
                input_shape=res[0][1:], out_shape=res[1][1:],
                PARAMs=res[2], FLOPs=res[3]))
    total_params = sum(r["PARAMs"] for r in rows)
    total_flops = sum(r["FLOPs"] for r in rows)
    text = _render(rows, total_params, total_flops)
    print(text)
    return total_params, total_flops
