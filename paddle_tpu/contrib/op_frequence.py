"""Op-frequency statistics over a program (parity:
contrib/op_frequence.py:23-104 `op_freq_statistic`): single-op counts and
adjacent-pair counts (producer->consumer through non-parameter vars),
both sorted descending."""

from collections import OrderedDict

from ..framework import Program

__all__ = ["op_freq_statistic"]


def op_freq_statistic(program):
    """Returns (uni_op_freq, adj_2_op_freq): OrderedDicts of
    "type" -> count and "producer,consumer" -> count, sorted by count
    descending."""
    if not isinstance(program, Program):
        raise TypeError("The input type should be Porgram."
                        "But you passed in %s" % type(program))

    uni_op_freq = OrderedDict()
    adj_2_op_freq = OrderedDict()
    block = program.global_block()
    parameters = {p.name for p in block.all_parameters()}

    for op in block.ops:
        recorded = False
        for name in op.output_arg_names:
            if name in parameters:
                continue
            if not recorded:
                uni_op_freq[op.type] = uni_op_freq.get(op.type, 0) + 1
                recorded = True

    var_gen_op = {}
    op_in_ops = OrderedDict()
    for op in block.ops:
        for name in op.input_arg_names:
            if name in parameters:
                continue
            gens = var_gen_op.get(name)
            if gens:
                op_in_ops.setdefault(op.type, []).append(gens[-1])
        for name in op.output_arg_names:
            if name in parameters:
                continue
            var_gen_op.setdefault(name, []).append(op.type)

    for op_type, in_ops in op_in_ops.items():
        for in_op in in_ops:
            key = in_op + "," + op_type
            adj_2_op_freq[key] = adj_2_op_freq.get(key, 0) + 1

    uni = OrderedDict(sorted(uni_op_freq.items(), key=lambda kv: -kv[1]))
    adj = OrderedDict(sorted(adj_2_op_freq.items(), key=lambda kv: -kv[1]))
    return uni, adj
