"""contrib.layers — experimental composite layers.

Parity with the reference package
(python/paddle/fluid/contrib/layers/__init__.py:15-27): the 8 fused /
variable-length layer wrappers (nn.py), the composite basic_gru /
basic_lstm RNN API (rnn_impl.py), and ctr_metric_bundle (metric_op.py).
"""

from . import nn
from .nn import *  # noqa: F401,F403
from . import rnn_impl
from .rnn_impl import *  # noqa: F401,F403
from . import metric_op
from .metric_op import *  # noqa: F401,F403

__all__ = []
__all__ += nn.__all__
__all__ += rnn_impl.__all__
__all__ += metric_op.__all__
