"""Composite basic GRU / LSTM API built from basic operators.

Parity: python/paddle/fluid/contrib/layers/rnn_impl.py:19
(BasicGRUUnit :22, basic_gru :139, basic_lstm :358, BasicLSTMUnit :632).
The reference composes these with StaticRNN (a per-step unrolled
sub-graph); here the whole single-direction multi-layer recurrence is ONE
op lowering to `lax.scan` (ops/contrib_rnn.py) — static shapes and
compiler-friendly control flow, the idiomatic XLA emission for an RNN —
while the unit classes remain eager dygraph Layers with exactly the
reference's equations and parameter shapes.
"""

from ... import layers
from ...dygraph import Layer
from ...layer_helper import LayerHelper

__all__ = ["BasicGRUUnit", "basic_gru", "BasicLSTMUnit", "basic_lstm"]

_ACT_NAMES = {None: None, "sigmoid": "sigmoid", "tanh": "tanh",
              "relu": "relu", "identity": "identity"}


def _act_name(fn, default):
    """Map a layers.* activation callable (or string) to the op attr."""
    if fn is None:
        return default
    if isinstance(fn, str):
        if fn not in _ACT_NAMES:
            raise NotImplementedError("activation %r" % fn)
        return fn
    name = getattr(fn, "__name__", None)
    if name in ("sigmoid", "tanh", "relu"):
        return name
    raise NotImplementedError(
        "basic_gru/basic_lstm support sigmoid/tanh/relu activations; got %r"
        % (fn,))


class BasicGRUUnit(Layer):
    """Single GRU step from basic operators (reference rnn_impl.py:22):

        r, u = sigmoid(W_g [x, h] + b_g).split(2)
        m = tanh(W_c [x, r*h] + b_c)
        h' = u * h + (1 - u) * m
    """

    def __init__(self, name_scope, hidden_size, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._hidden_size = hidden_size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._gate_activation = gate_activation or layers.sigmoid
        self._activation = activation or layers.tanh
        self._dtype = dtype
        self._built = False

    def _build_once(self, input):
        input_size = input.shape[-1]
        H = self._hidden_size
        self._gate_weight = self.create_parameter(
            attr=self._param_attr, shape=[input_size + H, 2 * H],
            dtype=self._dtype)
        self._candidate_weight = self.create_parameter(
            attr=self._param_attr, shape=[input_size + H, H],
            dtype=self._dtype)
        self._gate_bias = self.create_parameter(
            self._bias_attr, shape=[2 * H], dtype=self._dtype, is_bias=True)
        self._candidate_bias = self.create_parameter(
            self._bias_attr, shape=[H], dtype=self._dtype, is_bias=True)
        self._built = True

    def forward(self, input, pre_hidden):
        if not self._built:
            self._build_once(input)
        cat = layers.concat([input, pre_hidden], 1)
        gate = self._gate_activation(
            layers.elementwise_add(
                layers.matmul(cat, self._gate_weight), self._gate_bias))
        r, u = layers.split(gate, num_or_sections=2, dim=1)
        cand_in = layers.concat(
            [input, layers.elementwise_mul(r, pre_hidden)], 1)
        c = self._activation(
            layers.elementwise_add(
                layers.matmul(cand_in, self._candidate_weight),
                self._candidate_bias))
        one_minus_u = layers.scale(u, scale=-1.0, bias=1.0)
        return layers.elementwise_add(
            layers.elementwise_mul(u, pre_hidden),
            layers.elementwise_mul(one_minus_u, c))


class BasicLSTMUnit(Layer):
    """Single LSTM step from basic operators (reference rnn_impl.py:632):

        i, j, f, o = (W [x, h] + b).split(4)
        c' = c * sigmoid(f + forget_bias) + sigmoid(i) * tanh(j)
        h' = tanh(c') * sigmoid(o)
    """

    def __init__(self, name_scope, hidden_size, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 forget_bias=1.0, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._hidden_size = hidden_size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._gate_activation = gate_activation or layers.sigmoid
        self._activation = activation or layers.tanh
        self._forget_bias = float(forget_bias)
        self._dtype = dtype
        self._built = False

    def _build_once(self, input):
        input_size = input.shape[-1]
        H = self._hidden_size
        self._weight = self.create_parameter(
            attr=self._param_attr, shape=[input_size + H, 4 * H],
            dtype=self._dtype)
        self._bias = self.create_parameter(
            attr=self._bias_attr, shape=[4 * H], dtype=self._dtype,
            is_bias=True)
        self._built = True

    def forward(self, input, pre_hidden, pre_cell):
        if not self._built:
            self._build_once(input)
        cat = layers.concat([input, pre_hidden], 1)
        gate = layers.elementwise_add(
            layers.matmul(cat, self._weight), self._bias)
        i, j, f, o = layers.split(gate, num_or_sections=4, dim=-1)
        new_cell = layers.elementwise_add(
            layers.elementwise_mul(
                pre_cell,
                self._gate_activation(
                    layers.scale(f, bias=self._forget_bias))),
            layers.elementwise_mul(self._gate_activation(i),
                                   self._activation(j)))
        new_hidden = layers.elementwise_mul(
            self._activation(new_cell), self._gate_activation(o))
        return new_hidden, new_cell



def _per_param_attr(attr, pname, suffix):
    """Uniquify a (possibly named) ParamAttr per layer/direction/slot: a
    user-supplied name like 'gru_w' must become gru_w_<dir>_layers_<i>_<slot>
    or every weight matrix would silently alias ONE parameter (the
    reference renames through the per-layer BasicGRUUnit name scopes)."""
    from ...param_attr import ParamAttr

    if attr is None or attr is False:
        return attr
    attr = ParamAttr._to_attr(attr)
    if not attr.name:
        return attr
    import copy

    new = copy.copy(attr)
    new.name = "%s_%s_%s" % (attr.name, pname, suffix)
    return new


def _rnn_prologue(input, batch_first, sequence_length):
    """Shared input normalization: time-major input + optional [T, B]
    mask from per-batch lengths (reference basic_gru body)."""
    if batch_first:
        input = layers.transpose(input, [1, 0, 2])
    mask = None
    if sequence_length is not None:
        max_seq_len = input.shape[0]
        mask = layers.sequence_mask(sequence_length, maxlen=max_seq_len,
                                    dtype="float32")
        mask = layers.transpose(mask, [1, 0])
    return input, mask


def basic_gru(input, init_hidden, hidden_size, num_layers=1,
              sequence_length=None, dropout_prob=0.0, bidirectional=False,
              batch_first=True, param_attr=None, bias_attr=None,
              gate_activation=None, activation=None, dtype="float32",
              name="basic_gru"):
    """Multi-layer (optionally bidirectional) GRU
    (reference contrib/layers/rnn_impl.py:139; one lax.scan op per
    direction, ops/contrib_rnn.py basic_gru_rnn).

    Returns (rnn_out, last_hidden): rnn_out [T,B,H*dirs] (or batch-first),
    last_hidden [num_layers*dirs, B, H]."""
    g_act = _act_name(gate_activation, "sigmoid")
    c_act = _act_name(activation, "tanh")
    helper = LayerHelper(name)
    input, mask = _rnn_prologue(input, batch_first, sequence_length)
    input_size = input.shape[2]
    direc_num = 2 if bidirectional else 1
    if init_hidden is not None:
        init_hidden = layers.reshape(
            init_hidden, shape=[num_layers, direc_num, -1, hidden_size])

    def one_direction(rnn_input, rnn_mask, direc_index, dname):
        gw, cw, gb, cb = [], [], [], []
        for i in range(num_layers):
            layer_in = input_size if i == 0 else hidden_size
            pname = "%s_layers_%d" % (dname, i)
            gw.append(helper.create_parameter(
                attr=_per_param_attr(param_attr, pname, "gate_w"),
                shape=[layer_in + hidden_size, 2 * hidden_size],
                dtype=dtype))
            cw.append(helper.create_parameter(
                attr=_per_param_attr(param_attr, pname, "cand_w"),
                shape=[layer_in + hidden_size, hidden_size], dtype=dtype))
            gb.append(helper.create_parameter(
                attr=_per_param_attr(bias_attr, pname, "gate_b"),
                shape=[2 * hidden_size], dtype=dtype, is_bias=True))
            cb.append(helper.create_parameter(
                attr=_per_param_attr(bias_attr, pname, "cand_b"),
                shape=[hidden_size], dtype=dtype, is_bias=True))
        h0 = None
        if init_hidden is not None:
            h0 = layers.reshape(
                layers.slice(init_hidden, axes=[1], starts=[direc_index],
                             ends=[direc_index + 1]),
                shape=[num_layers, -1, hidden_size])
        out = helper.create_variable_for_type_inference(dtype)
        last_h = helper.create_variable_for_type_inference(dtype)
        inputs = {"Input": [rnn_input], "GateWeight": gw, "CandWeight": cw,
                  "GateBias": gb, "CandBias": cb}
        if h0 is not None:
            inputs["InitHidden"] = [h0]
        if rnn_mask is not None:
            inputs["Mask"] = [rnn_mask]
        helper.append_op(
            type="basic_gru_rnn",
            inputs=inputs,
            outputs={"Out": [out], "LastHidden": [last_h]},
            attrs={"hidden_size": hidden_size, "num_layers": num_layers,
                   "dropout_prob": float(dropout_prob or 0.0),
                   "is_test": False, "gate_activation": g_act,
                   "activation": c_act},
        )
        return out, last_h

    fw_out, fw_last = one_direction(input, mask, 0, "fw")
    if bidirectional:
        bw_in = layers.reverse(input, axis=[0])
        bw_mask = layers.reverse(mask, axis=[0]) if mask is not None else None
        bw_out, bw_last = one_direction(bw_in, bw_mask, 1, "bw")
        bw_out = layers.reverse(bw_out, axis=[0])
        rnn_out = layers.concat([fw_out, bw_out], axis=2)
        last_hidden = layers.concat([fw_last, bw_last], axis=1)
        last_hidden = layers.reshape(
            last_hidden, shape=[num_layers * direc_num, -1, hidden_size])
    else:
        rnn_out, last_hidden = fw_out, fw_last
    if batch_first:
        rnn_out = layers.transpose(rnn_out, [1, 0, 2])
    return rnn_out, last_hidden


def basic_lstm(input, init_hidden, init_cell, hidden_size, num_layers=1,
               sequence_length=None, dropout_prob=0.0, bidirectional=False,
               batch_first=True, param_attr=None, bias_attr=None,
               gate_activation=None, activation=None, forget_bias=1.0,
               dtype="float32", name="basic_lstm"):
    """Multi-layer (optionally bidirectional) LSTM
    (reference contrib/layers/rnn_impl.py:358; one lax.scan op per
    direction, ops/contrib_rnn.py basic_lstm_rnn).

    Returns (rnn_out, last_hidden, last_cell)."""
    g_act = _act_name(gate_activation, "sigmoid")
    c_act = _act_name(activation, "tanh")
    helper = LayerHelper(name)
    input, mask = _rnn_prologue(input, batch_first, sequence_length)
    input_size = input.shape[2]
    direc_num = 2 if bidirectional else 1
    if init_hidden is not None:
        init_hidden = layers.reshape(
            init_hidden, shape=[num_layers, direc_num, -1, hidden_size])
    if init_cell is not None:
        init_cell = layers.reshape(
            init_cell, shape=[num_layers, direc_num, -1, hidden_size])

    def one_direction(rnn_input, rnn_mask, direc_index, dname):
        ws, bs = [], []
        for i in range(num_layers):
            layer_in = input_size if i == 0 else hidden_size
            pname = "%s_layers_%d" % (dname, i)
            ws.append(helper.create_parameter(
                attr=_per_param_attr(param_attr, pname, "w"),
                shape=[layer_in + hidden_size, 4 * hidden_size],
                dtype=dtype))
            bs.append(helper.create_parameter(
                attr=_per_param_attr(bias_attr, pname, "b"),
                shape=[4 * hidden_size], dtype=dtype, is_bias=True))

        def pick(init):
            if init is None:
                return None
            return layers.reshape(
                layers.slice(init, axes=[1], starts=[direc_index],
                             ends=[direc_index + 1]),
                shape=[num_layers, -1, hidden_size])

        h0, c0 = pick(init_hidden), pick(init_cell)
        out = helper.create_variable_for_type_inference(dtype)
        last_h = helper.create_variable_for_type_inference(dtype)
        last_c = helper.create_variable_for_type_inference(dtype)
        inputs = {"Input": [rnn_input], "Weight": ws, "Bias": bs}
        if h0 is not None:
            inputs["InitHidden"] = [h0]
        if c0 is not None:
            inputs["InitCell"] = [c0]
        if rnn_mask is not None:
            inputs["Mask"] = [rnn_mask]
        helper.append_op(
            type="basic_lstm_rnn",
            inputs=inputs,
            outputs={"Out": [out], "LastHidden": [last_h],
                     "LastCell": [last_c]},
            attrs={"hidden_size": hidden_size, "num_layers": num_layers,
                   "dropout_prob": float(dropout_prob or 0.0),
                   "is_test": False, "forget_bias": float(forget_bias),
                   "gate_activation": g_act, "activation": c_act},
        )
        return out, last_h, last_c

    fw_out, fw_last_h, fw_last_c = one_direction(input, mask, 0, "fw")
    if bidirectional:
        bw_in = layers.reverse(input, axis=[0])
        bw_mask = layers.reverse(mask, axis=[0]) if mask is not None else None
        bw_out, bw_last_h, bw_last_c = one_direction(bw_in, bw_mask, 1, "bw")
        bw_out = layers.reverse(bw_out, axis=[0])
        rnn_out = layers.concat([fw_out, bw_out], axis=2)
        last_hidden = layers.reshape(
            layers.concat([fw_last_h, bw_last_h], axis=1),
            shape=[num_layers * direc_num, -1, hidden_size])
        last_cell = layers.reshape(
            layers.concat([fw_last_c, bw_last_c], axis=1),
            shape=[num_layers * direc_num, -1, hidden_size])
    else:
        rnn_out, last_hidden, last_cell = fw_out, fw_last_h, fw_last_c
    if batch_first:
        rnn_out = layers.transpose(rnn_out, [1, 0, 2])
    return rnn_out, last_hidden, last_cell
