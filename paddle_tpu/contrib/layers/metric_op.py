"""contrib metric layers.

Parity: python/paddle/fluid/contrib/layers/metric_op.py:27
(ctr_metric_bundle) — CTR metric accumulators built from the same op
sequence as the reference (squared_l2_norm / l1_norm / reduce_sum into
persistable accumulators updated in place each step).
"""

from ...initializer import Constant
from ...layer_helper import LayerHelper

__all__ = ["ctr_metric_bundle"]


def ctr_metric_bundle(input, label):
    """CTR metric accumulators: returns (local_sqrerr, local_abserr,
    local_prob, local_q, local_pos_num, local_ins_num) — persistable sums
    updated every step; divide by instance number (and allreduce under
    distribution) to get RMSE/MAE/predicted-ctr/q exactly as the
    reference documents."""
    assert list(input.shape) == list(label.shape)
    helper = LayerHelper("ctr_metric_bundle")

    def acc():
        v = helper.create_global_variable(persistable=True, dtype="float32",
                                          shape=[1])
        helper.set_variable_initializer(v, Constant(value=0.0))
        return v

    local_abserr, local_sqrerr = acc(), acc()
    local_prob, local_q = acc(), acc()
    local_pos_num, local_ins_num = acc(), acc()

    def tmp(shape=(1,)):
        return helper.create_variable_for_type_inference("float32")

    tmp_res_elesub = tmp()
    tmp_res_sigmoid = tmp()
    tmp_ones = tmp()
    batch_sqrerr, batch_abserr = tmp(), tmp()
    batch_prob, batch_q = tmp(), tmp()
    batch_pos_num, batch_ins_num = tmp(), tmp()

    def op(type_, ins, outs, attrs=None):
        helper.append_op(type=type_, inputs=ins, outputs=outs,
                         attrs=attrs or {})

    op("elementwise_sub", {"X": [input], "Y": [label]},
       {"Out": [tmp_res_elesub]})
    op("squared_l2_norm", {"X": [tmp_res_elesub]}, {"Out": [batch_sqrerr]})
    op("elementwise_add", {"X": [batch_sqrerr], "Y": [local_sqrerr]},
       {"Out": [local_sqrerr]})
    op("l1_norm", {"X": [tmp_res_elesub]}, {"Out": [batch_abserr]})
    op("elementwise_add", {"X": [batch_abserr], "Y": [local_abserr]},
       {"Out": [local_abserr]})
    op("reduce_sum", {"X": [input]}, {"Out": [batch_prob]},
       {"reduce_all": True, "keep_dim": False})
    op("elementwise_add", {"X": [batch_prob], "Y": [local_prob]},
       {"Out": [local_prob]})
    op("sigmoid", {"X": [input]}, {"Out": [tmp_res_sigmoid]})
    op("reduce_sum", {"X": [tmp_res_sigmoid]}, {"Out": [batch_q]},
       {"reduce_all": True, "keep_dim": False})
    op("elementwise_add", {"X": [batch_q], "Y": [local_q]},
       {"Out": [local_q]})
    op("reduce_sum", {"X": [label]}, {"Out": [batch_pos_num]},
       {"reduce_all": True, "keep_dim": False})
    op("elementwise_add", {"X": [batch_pos_num], "Y": [local_pos_num]},
       {"Out": [local_pos_num]})
    op("fill_constant_batch_size_like", {"Input": [label]},
       {"Out": [tmp_ones]},
       {"shape": [-1, 1], "dtype": 5, "value": 1.0})
    op("reduce_sum", {"X": [tmp_ones]}, {"Out": [batch_ins_num]},
       {"reduce_all": True, "keep_dim": False})
    op("elementwise_add", {"X": [batch_ins_num], "Y": [local_ins_num]},
       {"Out": [local_ins_num]})
    return (local_sqrerr, local_abserr, local_prob, local_q, local_pos_num,
            local_ins_num)
