"""contrib experimental layer wrappers.

Parity: python/paddle/fluid/contrib/layers/nn.py:27 — the 8 wrappers over
ops that already exist in this repo's registry (fused_elemwise_activation,
var_conv_2d, match_matrix_tensor, sequence_topk_avg_pooling, tree_conv,
fused_embedding_seq_pool, multiclass_nms2, pyramid_hash).  The wrappers
reproduce the reference's parameter-creation shapes, op slots, attrs and
return contracts exactly; the op lowerings are the TPU-native ones.
"""

from ...layer_helper import LayerHelper

__all__ = [
    "fused_elemwise_activation",
    "sequence_topk_avg_pooling",
    "var_conv_2d",
    "match_matrix_tensor",
    "tree_conv",
    "fused_embedding_seq_pool",
    "multiclass_nms2",
    "search_pyramid_hash",
]


def _pair(v, name):
    if isinstance(v, (list, tuple)):
        if len(v) != 2:
            raise ValueError("%s must have two elements" % name)
        return [int(v[0]), int(v[1])]
    return [int(v), int(v)]


def fused_elemwise_activation(x, y, functor_list, axis=-1, scale=0.0,
                              save_intermediate_out=True):
    """out = Unary(Binary(x, y)) or Binary(x, Unary(y)) as one op
    (reference contrib/layers/nn.py:39; op: fused_elemwise_activation_op.cc).
    functor_list: two of {elementwise_add, elementwise_mul, scale, relu,
    tanh}, e.g. ['elementwise_add', 'relu']."""
    if isinstance(functor_list, str):
        functor_list = functor_list.split(",")
    if not isinstance(functor_list, list) or len(functor_list) != 2:
        raise ValueError(
            "functor_list should be a list of str, and the length should "
            "be 2.")
    helper = LayerHelper("fused_elemwise_activation")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    intermediate_out = helper.create_variable_for_type_inference(
        dtype=x.dtype)
    helper.append_op(
        type="fused_elemwise_activation",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out], "IntermediateOut": [intermediate_out]},
        attrs={"axis": axis, "scale": scale,
               "save_intermediate_out": save_intermediate_out,
               "functor_list": list(functor_list)},
    )
    return out


def var_conv_2d(input, row, col, input_channel, output_channel, filter_size,
                stride=1, param_attr=None, act=None, dtype="float32",
                name=None):
    """Variable-size 2-D convolution over per-sequence row/col extents
    (reference contrib/layers/nn.py:103; op var_conv_2d_op.cc)."""
    helper = LayerHelper("var_conv_2d", param_attr=param_attr, act=act,
                         name=name)
    filter_size = _pair(filter_size, "filter_size")
    stride = _pair(stride, "stride")
    filter_shape = [int(output_channel),
                    int(input_channel) * filter_size[0] * filter_size[1]]
    filter_param = helper.create_parameter(attr=param_attr,
                                           shape=filter_shape, dtype=dtype)
    conv_res = helper.create_variable_for_type_inference(dtype)
    tmp_res = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    helper.append_op(
        type="var_conv_2d",
        inputs={"X": [input], "ROW": [row], "COLUMN": [col],
                "W": [filter_param]},
        outputs={"Out": [conv_res], "Col": [tmp_res]},
        attrs={"InputChannel": int(input_channel),
               "OutputChannel": int(output_channel),
               "StrideH": stride[0], "StrideW": stride[1],
               "KernelH": filter_size[0], "KernelW": filter_size[1]},
    )
    return helper.append_activation(conv_res)


def match_matrix_tensor(x, y, channel_num, act=None, param_attr=None,
                        dtype="float32", name=None):
    """Semantic matching matrix x W y^T with a [h, channel_num, h]
    learnable W (reference contrib/layers/nn.py:219; op
    match_matrix_tensor_op.cc).  Returns (out, tmp)."""
    helper = LayerHelper("match_matrix_tensor", param_attr=param_attr,
                         act=act, name=name)
    x_shape, y_shape = list(x.shape), list(y.shape)
    assert (len(x_shape) == 2 and len(y_shape) == 2
            and x_shape[-1] == y_shape[-1])
    w = helper.create_parameter(
        attr=param_attr, shape=[x_shape[-1], int(channel_num), y_shape[-1]],
        dtype=dtype)
    mm_res = helper.create_variable_for_type_inference(dtype)
    tmp_res = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    helper.append_op(
        type="match_matrix_tensor",
        inputs={"X": [x], "Y": [y], "W": [w]},
        outputs={"Out": [mm_res], "Tmp": [tmp_res]},
        attrs={"dim_t": int(channel_num)},
    )
    return helper.append_activation(mm_res), tmp_res


def sequence_topk_avg_pooling(input, row, col, topks, channel_num):
    """Per-channel top-k average pooling over variable-size feature maps
    (reference contrib/layers/nn.py:302; op
    sequence_topk_avg_pooling_op.cc)."""
    helper = LayerHelper("sequence_topk_avg_pooling")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    pos = helper.create_variable_for_type_inference(
        dtype=input.dtype, stop_gradient=True)
    helper.append_op(
        type="sequence_topk_avg_pooling",
        inputs={"X": [input], "ROW": [row], "COLUMN": [col]},
        outputs={"Out": [out], "pos": [pos]},
        attrs={"topks": list(topks), "channel_num": int(channel_num)},
    )
    return out


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """Tree-based convolution (TBCNN) over node vectors + an edge set
    (reference contrib/layers/nn.py:370; op tree_conv_op.h)."""
    helper = LayerHelper("tree_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = nodes_vector.dtype
    feature_size = nodes_vector.shape[2]
    W = helper.create_parameter(
        attr=param_attr,
        shape=[feature_size, 3, int(output_size), int(num_filters)],
        dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="tree_conv",
        inputs={"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
                "Filter": [W]},
        outputs={"Out": [out]},
        attrs={"max_depth": int(max_depth)},
    )
    if bias_attr:
        pre_activation = helper.append_bias_op(out, dim_start=2)
    else:
        pre_activation = out
    return helper.append_activation(pre_activation)


def fused_embedding_seq_pool(input, size, is_sparse=False, padding_idx=None,
                             combiner="sum", param_attr=None,
                             dtype="float32"):
    """Fusion of lookup_table + sequence_pool(sum)
    (reference contrib/layers/nn.py:435; op
    fused_embedding_seq_pool_op.cc)."""
    helper = LayerHelper("fused_embedding_seq_pool", param_attr=param_attr)
    w = helper.create_parameter(attr=param_attr, shape=list(size),
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    padding_idx = (-1 if padding_idx is None
                   else padding_idx if padding_idx >= 0
                   else (int(size[0]) + padding_idx))
    helper.append_op(
        type="fused_embedding_seq_pool",
        inputs={"Ids": [input], "W": [w]},
        outputs={"Out": [out]},
        attrs={"is_sparse": is_sparse, "combiner": combiner,
               "padding_idx": padding_idx},
    )
    return out


def multiclass_nms2(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                    nms_threshold=0.3, normalized=True, nms_eta=1.0,
                    background_label=0, return_index=False, name=None):
    """multiclass_nms that can also return the kept indices
    (reference contrib/layers/nn.py:501; op multiclass_nms_op.cc)."""
    helper = LayerHelper("multiclass_nms2", name=name)
    output = helper.create_variable_for_type_inference(dtype=bboxes.dtype)
    index = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op(
        type="multiclass_nms2",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [output], "Index": [index]},
        attrs={"background_label": background_label,
               "score_threshold": score_threshold,
               "nms_top_k": nms_top_k, "nms_threshold": nms_threshold,
               "nms_eta": nms_eta, "keep_top_k": keep_top_k,
               "normalized": normalized},
    )
    output.stop_gradient = True
    index.stop_gradient = True
    if return_index:
        return output, index
    return output


def search_pyramid_hash(input, num_emb, space_len, pyramid_layer, rand_len,
                        drop_out_percent, is_training, use_filter,
                        white_list_len, black_list_len, seed, lr,
                        param_attr=None, param_attr_wl=None,
                        param_attr_bl=None, name=None, dtype="float32"):
    """Pyramid hash embedding (reference contrib/layers/nn.py:631; op
    pyramid_hash_op.h — deterministic bloom-filter hash embedding)."""
    helper = LayerHelper("search_pyramid_hash", name=name)
    w = helper.create_parameter(attr=param_attr,
                                shape=[space_len + rand_len, 1], dtype=dtype)
    w.stop_gradient = True
    inputs = {"X": [input], "W": [w]}
    if white_list_len > 0:
        wl = helper.create_parameter(attr=param_attr_wl,
                                     shape=[white_list_len, 1], dtype=dtype)
        wl.stop_gradient = True
        inputs["WhiteList"] = [wl]
    if black_list_len > 0:
        bl = helper.create_parameter(attr=param_attr_bl,
                                     shape=[black_list_len, 1], dtype=dtype)
        bl.stop_gradient = True
        inputs["BlackList"] = [bl]
    res = helper.create_variable_for_type_inference(dtype)
    drop_pos = helper.create_variable_for_type_inference(dtype)
    x_temp_out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="pyramid_hash",
        inputs=inputs,
        outputs={"Out": [res], "X_Temp_Out": [x_temp_out],
                 "DropPos": [drop_pos]},
        attrs={"num_emb": num_emb, "space_len": space_len,
               "pyramid_layer": pyramid_layer, "rand_len": rand_len,
               "drop_out_percent": drop_out_percent,
               "is_training": is_training, "use_filter": use_filter,
               "white_list_len": white_list_len,
               "black_list_len": black_list_len, "seed": seed, "lr": lr},
    )
    return res
