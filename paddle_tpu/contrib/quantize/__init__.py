"""contrib.quantize — the pre-slim program-transpiling QAT API.

Parity: python/paddle/fluid/contrib/quantize/__init__.py:15.
"""

from . import quantize_transpiler
from .quantize_transpiler import *  # noqa: F401,F403

__all__ = quantize_transpiler.__all__
