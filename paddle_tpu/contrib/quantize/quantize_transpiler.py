"""QuantizeTranspiler — the older program-transpiling QAT API.

Parity: python/paddle/fluid/contrib/quantize/quantize_transpiler.py:80.
The reference predates the slim pass family and rewrites the program in
place; here it is a thin, faithful facade over the same machinery the
slim API uses (contrib/slim/quantization/quantization_pass.py) — one
quantization implementation, two API generations, like the reference's
own later consolidation.
"""

import numpy as np

from ..slim.quantization.quantization_pass import (
    QuantizationFreezePass, QuantizationTransformPass)
from ... import framework

__all__ = ["QuantizeTranspiler"]

_QUANT_TYPES = ("abs_max", "range_abs_max", "moving_average_abs_max")


class QuantizeTranspiler(object):
    """Rewrite a fluid Program for quantization-aware training.

    training_transpile() inserts fake-quant/dequant ops in front of the
    quantizable ops (mul/matmul/conv2d/depthwise_conv2d);
    freeze_program() flips the trained quantizers to inference mode;
    convert_to_int8() rewrites the quantized weight persistables to int8
    in the scope (reference quantize_transpiler.py:349)."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type="abs_max",
                 weight_quantize_type="abs_max", window_size=10000,
                 moving_rate=0.9):
        if weight_quantize_type not in _QUANT_TYPES:
            raise ValueError(
                "Unknown weight_quantize_type: %r (supported: %s)"
                % (weight_quantize_type, list(_QUANT_TYPES)))
        if activation_quantize_type not in _QUANT_TYPES:
            raise ValueError(
                "Unknown activation_quantize_type: %r (supported: %s)"
                % (activation_quantize_type, list(_QUANT_TYPES)))
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.weight_quantize_type = weight_quantize_type
        self.activation_quantize_type = activation_quantize_type
        self.window_size = window_size
        self.moving_rate = moving_rate

    def training_transpile(self, program=None, startup_program=None):
        program = (framework.default_main_program()
                   if program is None else program)
        startup_program = (framework.default_startup_program()
                           if startup_program is None else startup_program)
        # the older API's abs_max defaults map onto the pass's
        # quantize-type knobs; weights quantize per-tensor here (the
        # reference transpiler has no channel-wise mode).  The weight type
        # must be the CONSTRUCTOR's — freeze_program uses the same field,
        # and training under abs_max while freezing under range_abs_max
        # would silently produce an inconsistent train/freeze pair
        pass_ = QuantizationTransformPass(
            weight_bits=self.weight_bits,
            activation_bits=self.activation_bits,
            moving_rate=self.moving_rate,
            activation_quantize_type=self.activation_quantize_type,
            weight_quantize_type=self.weight_quantize_type)
        return pass_.apply(program, startup_program, is_test=False)

    def freeze_program(self, program, place, scope=None):
        QuantizationFreezePass(
            scope=scope, place=place, weight_bits=self.weight_bits,
            activation_bits=self.activation_bits,
            weight_quantize_type=self.weight_quantize_type).apply(program)
        return program

    def convert_to_int8(self, program, place, scope=None):
        """Rewrite quantized weight persistables to int8 in `scope`
        (reference :349 convert_to_int8): w_int8 = round(w / scale *
        (2^(bits-1) - 1)) stored as int8, for weight-only int8 export."""
        from ...core.executor import global_scope

        scope = global_scope() if scope is None else scope
        bound = float(2 ** (self.weight_bits - 1) - 1)
        seen = set()
        for op in program.global_block().ops:
            if "quantize" not in op.type:
                continue
            for name in op.input("X"):
                v = program.global_block()._find_var_recursive(name)
                if (v is None or not getattr(v, "persistable", False)
                        or name in seen):
                    continue
                var = scope.find_var(name)
                if var is None:
                    continue
                w = np.asarray(var.get_tensor())
                scale = np.max(np.abs(w)) or 1.0
                q = np.clip(np.round(w / scale * bound), -bound - 1,
                            bound).astype(np.int8)
                var.get_tensor().set(q, place)
                seen.add(name)
        return program
