"""contrib package — experimental / incubating APIs.

Export surface mirrors the reference's contrib/__init__.py:17-50
(python/paddle/fluid/contrib): decoder, memory_usage_calc, op_frequence,
quantize, reader, slim, utils, extend_optimizer, model_stat,
mixed_precision, layers — every name in the reference's __all__ resolves
as fluid.contrib.<name> here — plus the deprecated trainer/inferencer
shims (contrib/trainer.py:34, inferencer.py:28).
"""

from . import decoder  # noqa: F401
from .decoder import *  # noqa: F401,F403
from . import memory_usage_calc  # noqa: F401
from .memory_usage_calc import *  # noqa: F401,F403
from . import op_frequence  # noqa: F401
from .op_frequence import *  # noqa: F401,F403
from . import quantize  # noqa: F401
from .quantize import *  # noqa: F401,F403
from . import reader  # noqa: F401
from .reader import *  # noqa: F401,F403
from . import slim  # noqa: F401
from . import utils  # noqa: F401
from .utils import *  # noqa: F401,F403
from . import extend_optimizer  # noqa: F401
from .extend_optimizer import *  # noqa: F401,F403
from . import model_stat  # noqa: F401
from . import mixed_precision  # noqa: F401
from . import layers  # noqa: F401
from .layers import *  # noqa: F401,F403
from . import trainer  # noqa: F401
from . import inferencer  # noqa: F401
from .trainer import Trainer  # noqa: F401
from .inferencer import Inferencer  # noqa: F401

__all__ = []
__all__ += decoder.__all__
__all__ += memory_usage_calc.__all__
__all__ += op_frequence.__all__
__all__ += quantize.__all__
__all__ += reader.__all__
__all__ += utils.__all__
__all__ += extend_optimizer.__all__
__all__ += ["mixed_precision"]
__all__ += layers.__all__
