"""Decoupled weight decay as an optimizer mixin (parity:
contrib/extend_optimizer/extend_optimizer_with_weight_decay.py:34-152).

`extend_with_decoupled_weight_decay(Adam)` returns an AdamW-style class:
after the base optimizer's update, each decayed parameter is additionally
shifted by ``-coeff * parameter_before_update`` (arXiv:1711.05101) via ops
appended to the program, so the decay runs inside the same compiled step.
"""

from ... import optimizer as _optimizer
from ...framework import Variable, name_scope

__all__ = ["extend_with_decoupled_weight_decay"]


class DecoupledWeightDecay(object):
    def __init__(self, coeff=0.0, apply_decay_param_fun=None, **kwargs):
        if not isinstance(coeff, (float, Variable)):
            raise TypeError("coeff should be float or Variable.")
        self._params_name = set()
        self._apply_decay_param_fun = apply_decay_param_fun
        self._coeff = coeff
        super(DecoupledWeightDecay, self).__init__(**kwargs)

    def _scale_parameters(self, params_and_grads):
        """Capture param * coeff BEFORE the optimizer update ops run."""
        if isinstance(self._coeff, float) and self._coeff == 0.0:
            return []
        from ... import layers

        scaled_params = []
        for param, grad in params_and_grads:
            if grad is None:
                continue
            if self._apply_decay_param_fun is not None \
                    and not self._apply_decay_param_fun(param.name):
                continue
            if param.name in self._params_name:
                raise RuntimeError(
                    "parameter %r decayed twice" % param.name)
            with name_scope("weight_decay"):
                scaled_params.append(
                    (param, grad, param * self._coeff))
            self._params_name.add(param.name)
        return scaled_params

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ... import layers

        params_grads = self.backward(
            loss=loss, startup_program=startup_program,
            parameter_list=parameter_list, no_grad_set=no_grad_set)
        scaled_params = self._scale_parameters(params_grads)
        optimize_ops = self.apply_optimize(
            loss=loss, params_grads=params_grads,
            startup_program=startup_program)
        # post-update decoupled decay: p = p_updated - coeff * p_before
        for param, grad, scaled in scaled_params:
            with name_scope("weight_decay"):
                updated = layers.elementwise_sub(x=param, y=scaled)
                layers.assign(input=updated, output=param)
        return optimize_ops, params_grads

    def __str__(self):
        return " ".join(["Weight Decay, params:",
                         ",".join(self._params_name)])


def extend_with_decoupled_weight_decay(base_optimizer):
    """Class decorator: returns `base_optimizer` with decoupled weight
    decay (new_parameter = optimized_parameter - coeff * old_parameter).

    Example::

        AdamW = fluid.contrib.extend_with_decoupled_weight_decay(
            fluid.optimizer.Adam)
        AdamW(learning_rate=0.1, weight_decay=0.01).minimize(cost)
    """
    if not (isinstance(base_optimizer, type)
            and issubclass(base_optimizer, _optimizer.Optimizer)):
        raise TypeError("The input(base_optimizer) should be a derived "
                        "class of Optimizer.")

    class OptimizerWithDecoupledWeightDecay(DecoupledWeightDecay,
                                            base_optimizer):
        def __init__(self, weight_decay, apply_decay_param_fun=None,
                     **kwargs):
            super(OptimizerWithDecoupledWeightDecay, self).__init__(
                weight_decay, apply_decay_param_fun, **kwargs)

    return OptimizerWithDecoupledWeightDecay
