"""Round-robin batch sharding for multi-process training.

Parity: python/paddle/fluid/contrib/reader/distributed_reader.py:21 —
each trainer keeps the batch whose round-robin slot matches its
PADDLE_TRAINER_ID, so N trainers consume disjoint batch streams from
the same underlying reader.
"""

import os

__all__ = ["distributed_batch_reader"]


def distributed_batch_reader(batch_reader):
    """Wrap a batch reader so each trainer yields only its 1-in-N share
    (read PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ID from the environment,
    like the launch utilities set them)."""
    trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    trainer_id = int(os.getenv("PADDLE_TRAINER_ID", 0))
    assert trainer_id < trainers_num

    def decorate_for_multi_process():
        if trainers_num > 1:
            print("start data reader (trainers_num: {}, trainer_id: {})"
                  .format(trainers_num, trainer_id))
        train_data, idx = None, 1
        for _batch_id, data in enumerate(batch_reader()):
            if trainers_num > 1:
                if idx == trainer_id + 1:
                    train_data = data
                if idx < trainers_num:
                    idx += 1
                else:
                    assert train_data is not None, \
                        "train data should not be None."
                    yield train_data
                    train_data, idx = None, 1
            else:
                yield data

    return decorate_for_multi_process
