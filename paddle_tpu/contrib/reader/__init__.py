"""contrib.reader (parity:
python/paddle/fluid/contrib/reader/__init__.py:15)."""

from . import distributed_reader
from .distributed_reader import *  # noqa: F401,F403

__all__ = list(distributed_reader.__all__)
