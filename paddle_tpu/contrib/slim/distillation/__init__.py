from .distiller import *  # noqa: F401,F403
from .distiller import __all__  # noqa: F401
