"""Knowledge distillation (reference contrib/slim/distillation/distiller.py:
L2Distiller:25, FSPDistiller, SoftLabelDistiller; distillation_strategy.py).

The reference merges the teacher graph into the student graph with a name
prefix and appends a combined loss; here `merge_teacher` clones the teacher
program's ops/vars (prefixed, frozen) into the student program, and each
distiller appends its loss ops and returns the loss variable."""

from ....framework import Parameter, Variable

__all__ = ["merge_teacher", "L2Distiller", "FSPDistiller",
           "SoftLabelDistiller", "DistillationStrategy"]


def merge_teacher(student_program, teacher_program, scope=None,
                  teacher_scope=None, prefix="teacher_", data_vars=None):
    """Clone the teacher's ops/vars into the student program under
    `prefix`, sharing the data (feed) vars; teacher vars are frozen
    (stop_gradient).  Teacher parameter values are copied into `scope`
    under their prefixed names when scopes are given.  Returns a dict
    mapping original teacher var names -> merged names."""
    sblock = student_program.global_block()
    tblock = teacher_program.global_block()
    data_vars = set(data_vars or
                    [v.name for v in tblock.vars.values() if v.is_data])
    rename = {}
    for name, var in tblock.vars.items():
        if name in data_vars:
            rename[name] = name  # shared input
            continue
        new = prefix + name
        rename[name] = new
        if sblock.has_var(new):
            continue
        nv = sblock.create_var(
            name=new, shape=var.shape, dtype=var.dtype,
            persistable=var.persistable, stop_gradient=True,
            type=var.type)
        if isinstance(var, Parameter):
            nv.persistable = True
    for op in tblock.ops:
        sblock.append_op(
            type=op.type,
            inputs={s: [rename.get(n, n) for n in ns]
                    for s, ns in op.inputs.items()},
            outputs={s: [rename.get(n, n) for n in ns]
                     for s, ns in op.outputs.items()},
            attrs=dict(op.attrs),
        )
    if scope is not None and teacher_scope is not None:
        import numpy as np

        for name, var in tblock.vars.items():
            sv = teacher_scope.find_var(name)
            if sv is not None and sv.get_tensor()._is_initialized():
                scope.var(rename[name]).set(
                    np.asarray(sv.get_tensor().numpy()))
    return rename


class L2Distiller(object):
    """L2 loss between a student and a teacher feature map
    (reference distiller.py:25)."""

    def __init__(self, student_feature_map, teacher_feature_map,
                 distillation_loss_weight=1):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.distillation_loss_weight = distillation_loss_weight

    def distiller_loss(self, program):
        from .... import layers

        block = program.global_block()
        s = block.var(self.student_feature_map)
        t = block.var(self.teacher_feature_map)
        diff = layers.elementwise_sub(s, t)
        loss = layers.reduce_mean(layers.square(diff))
        return layers.scale(loss, scale=float(
            self.distillation_loss_weight))


class FSPDistiller(object):
    """Flow-of-solution-procedure distillation: match student/teacher FSP
    matrices between layer pairs (reference distiller.py FSPDistiller)."""

    def __init__(self, student_pairs, teacher_pairs,
                 distillation_loss_weight=1):
        self.student_pairs = student_pairs
        self.teacher_pairs = teacher_pairs
        self.distillation_loss_weight = distillation_loss_weight

    def distiller_loss(self, program):
        from .... import layers

        block = program.global_block()
        losses = []
        for (sa, sb), (ta, tb) in zip(self.student_pairs,
                                      self.teacher_pairs):
            sm = layers.fsp_matrix(block.var(sa), block.var(sb))
            tm = layers.fsp_matrix(block.var(ta), block.var(tb))
            losses.append(layers.reduce_mean(
                layers.square(layers.elementwise_sub(sm, tm))))
        total = losses[0]
        for l in losses[1:]:
            total = layers.elementwise_add(total, l)
        return layers.scale(total, scale=float(
            self.distillation_loss_weight))


class SoftLabelDistiller(object):
    """Cross entropy between temperature-softened student and teacher
    logits (reference distiller.py SoftLabelDistiller)."""

    def __init__(self, student_feature_map, teacher_feature_map,
                 student_temperature=1.0, teacher_temperature=1.0,
                 distillation_loss_weight=1):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.student_temperature = student_temperature
        self.teacher_temperature = teacher_temperature
        self.distillation_loss_weight = distillation_loss_weight

    def distiller_loss(self, program):
        from .... import layers

        block = program.global_block()
        s = layers.scale(block.var(self.student_feature_map),
                         scale=1.0 / self.student_temperature)
        t = layers.scale(block.var(self.teacher_feature_map),
                         scale=1.0 / self.teacher_temperature)
        t_soft = layers.softmax(t)
        t_soft.stop_gradient = True
        ce = layers.softmax_with_cross_entropy(s, t_soft, soft_label=True)
        return layers.scale(layers.reduce_mean(ce), scale=float(
            self.distillation_loss_weight))


class DistillationStrategy(object):
    """Compose distillers into one loss added to the task loss
    (reference distillation_strategy.py)."""

    def __init__(self, distillers, task_loss_weight=1.0):
        self.distillers = distillers
        self.task_loss_weight = task_loss_weight

    def build_loss(self, program, task_loss=None):
        from .... import layers

        total = None
        for d in self.distillers:
            l = d.distiller_loss(program)
            total = l if total is None else layers.elementwise_add(total, l)
        if task_loss is not None:
            scaled = layers.scale(task_loss,
                                  scale=float(self.task_loss_weight))
            total = scaled if total is None else layers.elementwise_add(
                total, scaled)
        return total
