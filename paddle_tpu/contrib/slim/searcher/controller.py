"""Hyperparameter/architecture search controllers (parity:
contrib/slim/searcher/controller.py:28-150)."""

import math

import numpy as np

__all__ = ["EvolutionaryController", "SAController"]


class EvolutionaryController(object):
    """Abstract evolutionary-search controller."""

    def update(self, tokens, reward):
        """Record a (tokens, reward) observation."""
        raise NotImplementedError("Abstract method.")

    def reset(self, range_table, init_tokens, constrain_func=None):
        """Reset with a search-space range table (tokens[i] in
        [0, range_table[i])) and optional constraint callback."""
        raise NotImplementedError("Abstract method.")

    def next_tokens(self):
        """Propose the next solution."""
        raise NotImplementedError("Abstract method.")


class SAController(EvolutionaryController):
    """Simulated annealing: accept a worse solution with probability
    exp((reward - best_so_far) / T), T decaying geometrically per
    iteration (searcher/controller.py:59-150)."""

    def __init__(self, range_table=None, reduce_rate=0.85,
                 init_temperature=1024, max_iter_number=300, seed=None):
        super(SAController, self).__init__()
        self._range_table = range_table
        self._reduce_rate = reduce_rate
        self._init_temperature = init_temperature
        self._max_iter_number = max_iter_number
        self._reward = -1
        self._tokens = None
        self._constrain_func = None
        self._max_reward = -1
        self._best_tokens = None
        self._iter = 0
        self._rng = np.random.RandomState(seed)

    def __getstate__(self):
        return {k: v for k, v in self.__dict__.items()
                if k != "_constrain_func"}

    @property
    def best_tokens(self):
        return self._best_tokens

    @property
    def max_reward(self):
        return self._max_reward

    def reset(self, range_table, init_tokens, constrain_func=None):
        self._range_table = list(range_table)
        self._constrain_func = constrain_func
        self._tokens = list(init_tokens)
        self._iter = 0

    def update(self, tokens, reward):
        self._iter += 1
        temperature = self._init_temperature * (
            self._reduce_rate ** self._iter)
        if reward > self._reward or self._rng.random_sample() <= math.exp(
                (reward - self._reward) / max(temperature, 1e-10)):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self._max_reward:
            self._max_reward = reward
            self._best_tokens = list(tokens)

    def next_tokens(self, control_token=None):
        tokens = list(control_token) if control_token else \
            list(self._tokens)
        new_tokens = self._mutate(tokens)
        if self._constrain_func is None:
            return new_tokens
        for _ in range(self._max_iter_number):
            if self._constrain_func(new_tokens):
                return new_tokens
            new_tokens = list(tokens)
            idx = self._rng.randint(len(self._range_table))
            new_tokens[idx] = self._rng.randint(self._range_table[idx])
        return new_tokens

    def _mutate(self, tokens):
        new_tokens = list(tokens)
        idx = self._rng.randint(len(self._range_table))
        # shift to a DIFFERENT value in [0, range) (the +1 offset
        # guarantees a change)
        new_tokens[idx] = (
            new_tokens[idx] + self._rng.randint(
                max(self._range_table[idx] - 1, 1)) + 1
        ) % self._range_table[idx]
        return new_tokens
