from .controller import EvolutionaryController, SAController  # noqa: F401

__all__ = ["EvolutionaryController", "SAController"]
