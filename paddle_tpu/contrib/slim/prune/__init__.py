from .pruner import *  # noqa: F401,F403
from .pruner import __all__  # noqa: F401
