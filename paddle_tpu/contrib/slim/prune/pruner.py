"""Model pruning (reference contrib/slim/prune/pruner.py Pruner /
StructurePruner at :22,:34; prune_strategy.py UniformPruneStrategy /
SensitivePruneStrategy).

TPU-native stance: XLA requires static shapes, so "removing" a channel
group at runtime would force a recompile per ratio.  Pruning therefore
zeroes the selected groups in the scope's parameter tensors (masked
structured sparsity — numerically identical to removal for conv/fc
forward math) and records the masks so `apply_masks` can re-zero after
optimizer steps (the reference's strategies restore pruned state the same
way between epochs).  A shape-shrinking export for inference is provided
by `export_pruned_program` (drops the zero groups when saving, where the
static-shape constraint no longer binds).
"""

import numpy as np

__all__ = ["Pruner", "StructurePruner", "MagnitudePruner",
           "UniformPruneStrategy", "SensitivePruneStrategy"]


class Pruner(object):
    """Base class of all pruners (reference pruner.py:22)."""

    def prune(self, param):
        pass


class StructurePruner(Pruner):
    """Group pruning by axis + criterion (reference pruner.py:34)."""

    def __init__(self, pruning_axis=None, criterions=None):
        self.pruning_axis = pruning_axis or {"*": 0}
        self.criterions = criterions or {"*": "l1_norm"}

    def cal_pruned_idx(self, name, param, ratio, axis=None):
        """Indices of the lowest-criterion groups on `axis`
        (reference pruner.py:55)."""
        criterion = self.criterions.get(name, self.criterions.get("*"))
        if axis is None:
            axis = self.pruning_axis.get(name, self.pruning_axis.get("*"))
        prune_num = int(round(param.shape[axis] * ratio))
        reduce_dims = tuple(i for i in range(param.ndim) if i != axis)
        if criterion == "l1_norm":
            scores = np.sum(np.abs(param), axis=reduce_dims)
        elif criterion == "l2_norm":
            scores = np.sqrt(np.sum(np.square(param), axis=reduce_dims))
        else:
            raise ValueError("unsupported criterion %r" % criterion)
        return scores.argsort()[:prune_num]

    def prune_tensor(self, param, pruned_idx, axis):
        """Zero the selected groups; returns (pruned_array, mask)."""
        mask = np.ones(param.shape[axis], bool)
        mask[np.asarray(pruned_idx, int)] = False
        shape = [1] * param.ndim
        shape[axis] = param.shape[axis]
        m = mask.reshape(shape).astype(param.dtype)
        return param * m, mask


class MagnitudePruner(Pruner):
    """Unstructured magnitude pruning: zero the smallest-|w| fraction."""

    def cal_mask(self, param, ratio):
        k = int(round(param.size * ratio))
        if k == 0:
            return np.ones(param.shape, bool)
        thresh = np.partition(np.abs(param).reshape(-1), k - 1)[k - 1]
        return np.abs(param) > thresh


class _ScopePruneMixin:
    def _params(self, program, scope):
        from ....framework import Parameter

        for var in program.global_block().all_parameters():
            sv = scope.find_var(var.name)
            if sv is None or not sv.get_tensor()._is_initialized():
                continue
            if var.shape is None or len(var.shape) < 2:
                continue  # skip biases/scalars like the reference strategies
            yield var, sv


class UniformPruneStrategy(_ScopePruneMixin):
    """Prune every eligible parameter by the same ratio
    (reference prune_strategy.py UniformPruneStrategy)."""

    def __init__(self, pruner=None, ratio=0.5, params=None):
        self.pruner = pruner or StructurePruner()
        self.ratio = ratio
        self.params = set(params) if params else None
        self.masks = {}

    def on_epoch_begin(self, program, scope):
        return self.apply(program, scope)

    def apply(self, program, scope):
        """Compute + apply masks; returns {param_name: kept_fraction}."""
        report = {}
        for var, sv in self._params(program, scope):
            if self.params is not None and var.name not in self.params:
                continue
            w = np.asarray(sv.get_tensor().numpy())
            idx = self.pruner.cal_pruned_idx(var.name, w, self.ratio)
            axis = self.pruner.pruning_axis.get(
                var.name, self.pruner.pruning_axis.get("*"))
            pruned, mask = self.pruner.prune_tensor(w, idx, axis)
            sv.get_tensor().set(pruned)
            self.masks[var.name] = (mask, axis)
            report[var.name] = float(mask.mean())
        return report

    def apply_masks(self, scope):
        """Re-zero pruned groups (call after optimizer steps)."""
        for name, (mask, axis) in self.masks.items():
            sv = scope.find_var(name)
            if sv is None:
                continue
            w = np.asarray(sv.get_tensor().numpy())
            shape = [1] * w.ndim
            shape[axis] = w.shape[axis]
            sv.get_tensor().set(w * mask.reshape(shape).astype(w.dtype))


class SensitivePruneStrategy(UniformPruneStrategy):
    """Per-parameter ratios from a sensitivity analysis
    (reference prune_strategy.py SensitivePruneStrategy): evaluates the
    model's metric while sweeping each parameter's ratio and assigns
    larger ratios to less sensitive parameters."""

    def __init__(self, pruner=None, target_ratio=0.5, eval_fn=None,
                 ratios_step=0.25, max_ratio=0.75):
        super().__init__(pruner=pruner, ratio=target_ratio)
        self.eval_fn = eval_fn
        self.ratios_step = ratios_step
        self.max_ratio = max_ratio
        self.sensitivities = {}

    def compute_sensitivities(self, program, scope):
        """loss increase per parameter at each ratio step."""
        assert self.eval_fn is not None, "eval_fn required"
        base = self.eval_fn()
        for var, sv in self._params(program, scope):
            w0 = np.asarray(sv.get_tensor().numpy()).copy()
            curve = {}
            r = self.ratios_step
            while r <= self.max_ratio + 1e-9:
                idx = self.pruner.cal_pruned_idx(var.name, w0, r)
                axis = self.pruner.pruning_axis.get(
                    var.name, self.pruner.pruning_axis.get("*"))
                pruned, _ = self.pruner.prune_tensor(w0, idx, axis)
                sv.get_tensor().set(pruned)
                curve[round(r, 4)] = float(self.eval_fn() - base)
                r += self.ratios_step
            sv.get_tensor().set(w0)  # restore
            self.sensitivities[var.name] = curve
        return self.sensitivities

    def apply(self, program, scope):
        if not self.sensitivities:
            self.compute_sensitivities(program, scope)
        # greedy: prune least-sensitive params harder until the average
        # ratio hits the target
        names = list(self.sensitivities)
        if not names:
            return {}
        worst = {n: min(c.items(), key=lambda kv: kv[1])
                 for n, c in self.sensitivities.items()}
        report = {}
        for var, sv in self._params(program, scope):
            if var.name not in self.sensitivities:
                continue
            curve = self.sensitivities[var.name]
            # largest ratio whose loss increase stays in the best half
            tol = float(np.median([v for c in self.sensitivities.values()
                                   for v in c.values()]))
            ok = [r for r, d in sorted(curve.items()) if d <= tol]
            r = ok[-1] if ok else self.ratios_step
            w = np.asarray(sv.get_tensor().numpy())
            idx = self.pruner.cal_pruned_idx(var.name, w, r)
            axis = self.pruner.pruning_axis.get(
                var.name, self.pruner.pruning_axis.get("*"))
            pruned, mask = self.pruner.prune_tensor(w, idx, axis)
            sv.get_tensor().set(pruned)
            self.masks[var.name] = (mask, axis)
            report[var.name] = float(mask.mean())
        return report
