"""Quantization-aware-training program rewriting (parity:
fluid/contrib/slim/quantization/quantization_pass.py
QuantizationTransformPass / QuantizationFreezePass).

The reference rewrites an ir::Graph; here the pass rewrites the Program's
op list directly: for every quantizable op (mul/matmul/conv2d/
depthwise_conv2d), the activation input is routed through a
fake_quantize_moving_average_abs_max op and the weight input through
fake_channel_wise_quantize_abs_max (weight_quantize_type=
"channel_wise_abs_max", the default) or per-tensor fake_quantize_abs_max
(any other weight type — QuantizeTranspiler's "abs_max" lands here) —
forward simulates int8, backward is straight-through, weights stay float
(QAT).
"""

from ....framework import OP_ROLE_KEY, OpRole
from ....initializer import Constant
from ....utils import unique_name

QUANTIZABLE = ("mul", "matmul", "conv2d", "depthwise_conv2d")


class QuantizationTransformPass:
    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8, moving_rate=0.9,
                 activation_quantize_type="moving_average_abs_max",
                 weight_quantize_type="channel_wise_abs_max",
                 quantizable_op_type=QUANTIZABLE):
        self._weight_bits = weight_bits
        self._activation_bits = activation_bits
        self._moving_rate = moving_rate
        self._ops = tuple(quantizable_op_type)
        # "channel_wise_abs_max" keeps a scale per output channel; anything
        # else quantizes weights per-tensor (weights are re-read each step,
        # so the range/moving-average variants reduce to abs_max for them)
        self._weight_quantize_type = weight_quantize_type

    def apply(self, program, startup_program=None, is_test=False):
        """Insert fake-quant ops in front of every quantizable op's inputs.
        Returns the number of rewritten ops."""
        block = program.global_block()
        quantized = {}  # original name -> quantized name
        new_ops = []
        n = 0
        for op in list(block.ops):
            if op.type in self._ops and not (
                    int(op.attr(OP_ROLE_KEY) or 0) & OpRole.Backward):
                n += 1
                for slot in ("X", "Y", "Input", "Filter"):
                    names = op.input(slot)
                    if not names:
                        continue
                    name = names[0]
                    v = block._find_var_recursive(name)
                    if v is None or v.dtype not in ("float32", "bfloat16",
                                                    None):
                        continue
                    if name not in quantized:
                        # weight vs activation by persistability (the
                        # reference's rule) — a matmul(act, act) must NOT
                        # take the channel-wise weight path
                        is_weight = bool(getattr(v, "persistable", False))
                        qname = unique_name.generate(name + ".quantized")
                        qv = block.create_var(name=qname, dtype=v.dtype,
                                              shape=v.shape)
                        if is_weight:
                            scale = block.create_var(
                                name=qname + ".scale", dtype="float32")
                            if (self._weight_quantize_type
                                    == "channel_wise_abs_max"):
                                qop = _make_op(
                                    block,
                                    "fake_channel_wise_quantize_abs_max",
                                    {"X": [name]},
                                    {"Out": [qname],
                                     "OutScale": [scale.name]},
                                    {"bit_length": self._weight_bits,
                                     "quant_axis": 0})
                            else:
                                qop = _make_op(
                                    block, "fake_quantize_abs_max",
                                    {"X": [name]},
                                    {"Out": [qname],
                                     "OutScale": [scale.name]},
                                    {"bit_length": self._weight_bits})
                        else:
                            def mkstate(suffix, init):
                                sv = block.create_var(
                                    name=unique_name.generate(
                                        name + suffix),
                                    dtype="float32", shape=(1,),
                                    persistable=True)
                                sv.stop_gradient = True
                                Constant(init)(sv, startup_program
                                               .global_block()
                                               if startup_program else None)
                                return sv

                            # separate running scale / accumulator / state
                            # (aliasing them breaks the moving average:
                            # scale = accum/state must not feed accum back
                            # into the scale slot)
                            in_scale = mkstate(".in_scale", 1.0)
                            accum = mkstate(".accum", 1.0)
                            state = mkstate(".state", 1.0)
                            scale = block.create_var(
                                name=qname + ".scale", dtype="float32")
                            qop = _make_op(
                                block,
                                "fake_quantize_moving_average_abs_max",
                                {"X": [name], "InScale": [in_scale.name],
                                 "InAccum": [accum.name],
                                 "InState": [state.name]},
                                {"Out": [qname], "OutScale": [in_scale.name],
                                 "OutAccum": [accum.name],
                                 "OutState": [state.name]},
                                {"bit_length": self._activation_bits,
                                 "moving_rate": self._moving_rate,
                                 "is_test": is_test})
                        new_ops.append((op, qop))
                        quantized[name] = qname
                    op.inputs[slot] = [quantized[name]]
        # splice each quant op right before its consumer
        for consumer, qop in new_ops:
            idx = block.ops.index(consumer)
            block.ops.insert(idx, qop)
        program._bump_version()
        return n


class QuantizationFreezePass:
    """Post-training freeze (reference QuantizationFreezePass): on this
    backend the fake-quant ops already simulate the int grid in forward, so
    freezing only flips moving-average quantizers to is_test=True."""

    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8, weight_quantize_type="abs_max"):
        pass

    def apply(self, program):
        for op in program.global_block().ops:
            if op.type == "fake_quantize_moving_average_abs_max":
                op.attrs["is_test"] = True
        program._bump_version()


def _make_op(block, type, inputs, outputs, attrs):
    """Build an Operator without appending (spliced later)."""
    from ....framework import Operator

    return Operator(block, type, inputs, outputs, attrs)
