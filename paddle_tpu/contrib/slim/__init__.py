"""contrib.slim: model compression (parity: fluid/contrib/slim/)."""

from . import distillation  # noqa: F401
from . import prune  # noqa: F401
from . import quantization  # noqa: F401
