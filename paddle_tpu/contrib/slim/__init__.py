"""contrib.slim: model compression (parity: fluid/contrib/slim/)."""

from . import quantization  # noqa: F401
