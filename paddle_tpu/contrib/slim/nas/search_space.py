"""NAS search-space interface (parity: contrib/slim/nas/search_space.py).

A SearchSpace maps integer token vectors to concrete (Program, metrics)
tuples; the controller explores token space."""

__all__ = ["SearchSpace"]


class SearchSpace(object):
    """Abstract search space for neural architecture search."""

    def init_tokens(self):
        """Initial token vector."""
        raise NotImplementedError("Abstract method.")

    def range_table(self):
        """Per-position token ranges: tokens[i] in [0, range_table()[i])."""
        raise NotImplementedError("Abstract method.")

    def create_net(self, tokens):
        """tokens -> (startup_program, train_program, eval_program,
        train_metrics, test_metrics)."""
        raise NotImplementedError("Abstract method.")

    def get_model_latency(self, program):
        """Measured (or estimated) latency of a candidate program — the
        LightNAS constraint signal."""
        raise NotImplementedError("Abstract method.")
