from .controller_server import ControllerServer  # noqa: F401
from .light_nas_strategy import LightNASStrategy  # noqa: F401
from .search_agent import SearchAgent  # noqa: F401
from .search_space import SearchSpace  # noqa: F401

__all__ = ["SearchSpace", "ControllerServer", "SearchAgent",
           "LightNASStrategy"]
