"""Socket server wrapping a search controller (parity:
contrib/slim/nas/controller_server.py:28-107).

Protocol (newline-stripped UTF-8, one request per connection):
    "next_tokens"                  -> "t0,t1,..."
    "<key>\\t<tokens>\\t<reward>"  -> updates, replies next tokens
"""

import socket
import threading

__all__ = ["ControllerServer"]


class ControllerServer(object):
    def __init__(self, controller=None, address=("", 0),
                 max_client_num=100, search_steps=None, key="light-nas"):
        self._controller = controller
        self._address = address
        self._max_client_num = max_client_num
        self._search_steps = search_steps
        self._closed = False
        self._ip, self._port = address
        self._key = key
        self._socket = None
        self._thread = None

    def start(self):
        self._socket = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._socket.bind(self._address)
        self._socket.listen(self._max_client_num)
        self._socket.settimeout(0.5)  # poll so close() can stop accept()
        self._ip, self._port = self._socket.getsockname()[:2]
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self._thread

    def close(self):
        self._closed = True
        if self._thread is not None:
            self._thread.join(timeout=5)

    def port(self):
        return self._port

    def ip(self):
        return self._ip

    def run(self):
        while not self._closed and (
                self._search_steps is None
                or self._controller._iter < self._search_steps):
            try:
                conn, _addr = self._socket.accept()
            except socket.timeout:
                continue
            try:
                message = conn.recv(1024).decode().strip("\n")
                if message == "next_tokens":
                    tokens = self._controller.next_tokens()
                else:
                    parts = message.split("\t")
                    if len(parts) < 3 or parts[0] != self._key:
                        continue  # noise / wrong key: drop
                    tokens = [int(t) for t in parts[1].split(",")]
                    self._controller.update(tokens, float(parts[2]))
                    tokens = self._controller.next_tokens()
                conn.send(",".join(str(t) for t in tokens).encode())
            except (ValueError, OSError):
                # malformed numbers / client hangups must not kill the
                # server thread (the search would hang on the next recv)
                continue
            finally:
                conn.close()
        self._socket.close()
