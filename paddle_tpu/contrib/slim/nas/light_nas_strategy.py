"""Light-NAS search strategy (parity:
contrib/slim/nas/light_nas_strategy.py:34-196).

The reference version plugs into its slim Compressor epoch callbacks
(on_compression_begin / on_epoch_begin / on_epoch_end) and talks to the
shared controller through a SearchAgent.  This framework's slim package
has no epoch-callback Compressor; the same search loop is exposed
directly: `search()` iterates propose -> constrain (flops/latency retry
with a min-flops fallback, the reference's _max_try_times loop) ->
train/evaluate -> report reward (zeroed when the winning candidate
violates constraints, as the reference does on_epoch_end)."""

import socket

from ..searcher import SAController
from .controller_server import ControllerServer
from .search_agent import SearchAgent

__all__ = ["LightNASStrategy"]


class LightNASStrategy(object):
    def __init__(self, controller=None, search_steps=10,
                 target_flops=629145600, target_latency=0,
                 metric_name="top1_acc", server_ip="127.0.0.1",
                 server_port=0, is_server=True, max_client_num=100,
                 max_try_times=100, key="light-nas"):
        self._controller = controller or SAController()
        self._search_steps = search_steps
        self._max_flops = target_flops
        self._max_latency = target_latency
        self._metric_name = metric_name
        self._server_ip = server_ip or socket.gethostbyname(
            socket.gethostname())
        self._server_port = server_port
        self._is_server = is_server
        self._max_client_num = max_client_num
        self._max_try_times = max_try_times
        self._key = key
        self._server = None
        self._search_agent = None

    def __getstate__(self):
        """Sockets can't be pickled (reference __getstate__)."""
        return {k: v for k, v in self.__dict__.items()
                if k not in ("_search_agent", "_server")}

    # -- lifecycle -----------------------------------------------------------
    def start(self, search_space):
        """Reset the controller and bring up server + agent."""
        self._current_tokens = search_space.init_tokens()
        self._controller.reset(search_space.range_table(),
                               self._current_tokens, None)
        if self._is_server:
            self._server = ControllerServer(
                controller=self._controller,
                address=(self._server_ip, self._server_port),
                max_client_num=self._max_client_num,
                search_steps=None, key=self._key)
            self._server.start()
            self._server_port = self._server.port()
        self._search_agent = SearchAgent(
            self._server_ip, self._server_port, key=self._key)

    def stop(self):
        if self._server is not None:
            self._server.close()
            self._server = None

    # -- one search round ----------------------------------------------------
    def propose(self, search_space, flops_fn, latency_fn=None):
        """Find a candidate satisfying the constraints, retrying through
        the controller with the min-flops tokens as mutation base (the
        reference's on_epoch_begin loop)."""
        min_flops, min_tokens = -1, None
        for _ in range(self._max_try_times):
            net = search_space.create_net(self._current_tokens)
            flops = flops_fn(net)
            if min_flops < 0 or flops < min_flops:
                min_flops, min_tokens = flops, list(self._current_tokens)
            latency = 0
            if self._max_latency > 0:
                latency = (latency_fn or
                           search_space.get_model_latency)(net)
            if flops > self._max_flops or (self._max_latency > 0
                                           and latency > self._max_latency):
                self._current_tokens = self._controller.next_tokens(
                    min_tokens)
            else:
                return self._current_tokens, net
        return self._current_tokens, net

    def report(self, reward, flops=None, latency=None):
        """Send the evaluated reward (zeroed on constraint violation, per
        the reference on_epoch_end) and adopt the next proposal."""
        if flops is not None and flops > self._max_flops:
            reward = 0.0
        if self._max_latency > 0 and latency is not None \
                and latency > self._max_latency:
            reward = 0.0
        self._current_tokens = self._search_agent.update(
            self._current_tokens, reward)
        return self._current_tokens

    # -- full loop -----------------------------------------------------------
    def search(self, search_space, eval_fn, flops_fn, latency_fn=None):
        """Run `search_steps` rounds: propose -> eval_fn(net) -> report.
        Returns (best_tokens, best_reward) from the controller."""
        self.start(search_space)
        try:
            for _ in range(self._search_steps):
                tokens, net = self.propose(search_space, flops_fn,
                                           latency_fn)
                reward = eval_fn(net)
                latency = None
                if self._max_latency > 0:
                    latency = (latency_fn
                               or search_space.get_model_latency)(net)
                self.report(reward, flops=flops_fn(net),
                            latency=latency)
            return self._controller.best_tokens, \
                self._controller.max_reward
        finally:
            self.stop()
