"""Client side of the NAS controller protocol (parity:
contrib/slim/nas/search_agent.py:25-67)."""

import socket

__all__ = ["SearchAgent"]


class SearchAgent(object):
    def __init__(self, server_ip=None, server_port=None, key="light-nas"):
        self.server_ip = server_ip
        self.server_port = server_port
        self._key = key

    def _roundtrip(self, payload):
        client = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            client.connect((self.server_ip, self.server_port))
            client.send(payload.encode())
            reply = client.recv(1024).decode().strip("\n")
        finally:
            client.close()
        return [int(t) for t in reply.split(",")]

    def update(self, tokens, reward):
        """Report (tokens, reward); returns the controller's next
        proposal."""
        return self._roundtrip("%s\t%s\t%s" % (
            self._key, ",".join(str(t) for t in tokens), reward))

    def next_tokens(self):
        return self._roundtrip("next_tokens")
