"""Estimate a program's activation/parameter memory (parity:
contrib/memory_usage_calc.py:46-120 `memory_usage`).

Sums dense-var bytes over the global block, expanding one batch (-1) dim
per var by `batch_size`; returns (lower, upper, unit) with the reference's
5%-10% overhead band.  Under XLA the estimate is an upper bound on live
HBM (the compiler reuses buffers aggressively), which is exactly how the
reference documents its own number ("estimate usage").
"""

from ..framework import Program

__all__ = ["memory_usage"]

_DTYPE_SIZE = {
    "float16": 2, "bfloat16": 2, "float32": 4, "float64": 8,
    "int16": 2, "int32": 4, "int64": 8, "bool": 1, "uint8": 1, "int8": 1,
}


def memory_usage(program, batch_size):
    """Returns (min_total, max_total, unit_str) for `program` at
    `batch_size`."""
    if not isinstance(program, Program):
        raise TypeError(
            "Calculating Memory Usage requires Program as its Parameter."
            "But you passed in %s" % type(program))
    if batch_size <= 0:
        raise ValueError("The batch size need to be positive.")

    from ..framework import dtype_to_np

    block = program.global_block()
    total = 0.0
    seen = {"@EMPTY@"}
    # every dense var in the block: op outputs (activations) AND vars with
    # no producer here — parameters and feed/data vars.  (The reference
    # iterates only op outputs, which silently drops parameter bytes when
    # the program carries no init/feed ops; counting all block vars keeps
    # the estimate an upper bound as documented.)
    names = [n for op in block.ops for n in op.output_arg_names]
    names += list(getattr(block, "vars", {}).keys())
    for name in names:
        if name in seen:
            continue
        seen.add(name)
        var = block._find_var_recursive(name)
        if var is None or var.shape is None or var.dtype is None:
            continue
        count = 1
        neg_seen = 0
        for d in var.shape:
            if d is None or d < 0:
                if neg_seen >= 1:
                    raise ValueError(
                        "Var %s has more than one negtive dim." % name)
                neg_seen += 1
                count *= batch_size * (1 if d is None else -d)
            else:
                count *= d
        npdt = dtype_to_np(var.dtype)
        total += count * _DTYPE_SIZE.get(
            getattr(npdt, "__name__", str(npdt)), 4)

    unit = "B"
    if total > 1024:
        total /= 1024.0
        unit = "KB"
        if total > 1024:
            total /= 1024.0
            unit = "MB"
    return total * 1.05, total * 1.1, unit
