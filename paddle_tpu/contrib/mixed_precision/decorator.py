"""Mixed-precision optimizer decorator.

Parity: contrib/mixed_precision/decorator.py (decorate at :216,
OptimizerWithMixedPrecision at :27).  TPU-native policy: instead of the
reference's fp16 program-rewrite, the program is flagged for **bf16 MXU
compute** (matmul/conv lowerings read the flag; the MXU accumulates in f32
in hardware) — numerically robust on TPU without loss scaling.  Static and
dynamic loss scaling (reference decorator.py:112-185) are implemented
branchlessly (mask arithmetic instead of control-flow ops): on overflow the
unscaled grads are zeroed — making the update a near-no-op — and the scale
backs off by decr_ratio; after incr_every_n_steps clean steps it grows by
incr_ratio.
"""

from ...framework import default_main_program
from ...initializer import Constant
from ...utils import unique_name
from .fp16_lists import AutoMixedPrecisionLists

__all__ = ["decorate", "OptimizerWithMixedPrecision"]


class OptimizerWithMixedPrecision:
    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=1.0,
                 use_dynamic_loss_scaling=False, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, incr_ratio=2.0, decr_ratio=0.8):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._init_loss_scaling = float(init_loss_scaling)
        self._use_dynamic_loss_scaling = use_dynamic_loss_scaling
        self._incr_every_n_steps = incr_every_n_steps
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._loss_scaling_var = None
        self._scaled_loss = None

    def get_loss_scaling(self):
        return self._loss_scaling_var

    def get_scaled_loss(self):
        return self._scaled_loss

    # -- helpers -------------------------------------------------------------
    def _create_scale_var(self, block):
        var = block.create_var(
            name=unique_name.generate("loss_scaling"),
            shape=(1,), dtype="float32", persistable=True)
        var.stop_gradient = True
        Constant(self._init_loss_scaling)(var)
        self._loss_scaling_var = var
        good = block.create_var(
            name=unique_name.generate("good_steps"),
            shape=(1,), dtype="float32", persistable=True)
        good.stop_gradient = True
        Constant(0.0)(good)
        self._good_steps_var = good
        return var

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        from ... import layers

        program = loss.block.program
        block = program.global_block()
        program._amp_bf16 = True  # bf16 MXU policy for all matmul/conv

        dynamic = self._use_dynamic_loss_scaling
        static_scale = self._init_loss_scaling != 1.0 and not dynamic

        if dynamic:
            scale_var = self._create_scale_var(block)
            self._scaled_loss = layers.elementwise_mul(loss, scale_var)
        elif static_scale:
            self._scaled_loss = layers.scale(loss,
                                             scale=self._init_loss_scaling)
        else:
            self._scaled_loss = loss

        params_grads = self._optimizer.backward(
            self._scaled_loss, startup_program, parameter_list, no_grad_set,
            callbacks)

        if not (dynamic or static_scale):
            return params_grads

        with program._backward_role_guard():
            grads = [g for _, g in params_grads if g is not None]
            if dynamic:
                # isfinite op is duplicable over X: one fused all-finite check
                fin = block.create_var(
                    name=unique_name.generate("all_grads_finite"),
                    shape=(1,), dtype="bool")
                block.append_op(type="isfinite", inputs={"X": grads},
                                outputs={"Out": [fin]})
                fin_f = layers.cast(fin, "float32")
                inv_scale = layers.elementwise_div(
                    fin_f, self._loss_scaling_var)  # 0 on overflow
                unscaled = []
                for p, g in params_grads:
                    if g is None:
                        unscaled.append((p, g))
                        continue
                    unscaled.append((p, layers.elementwise_mul(g, inv_scale)))
                self._append_scale_update(fin_f)
                return unscaled
            # static
            unscaled = []
            for p, g in params_grads:
                if g is None:
                    unscaled.append((p, g))
                    continue
                unscaled.append(
                    (p, layers.scale(g, scale=1.0 / self._init_loss_scaling)))
            return unscaled

    def _append_scale_update(self, fin_f):
        """good' = (good+1)*fin; scale' = fin*(good'>=N ? scale*incr : scale)
        + (1-fin)*scale*decr; good'' = good' mod-reset at N."""
        from ... import layers

        scale_var = self._loss_scaling_var
        good = self._good_steps_var
        one_minus = layers.scale(fin_f, scale=-1.0, bias=1.0)
        good_next = layers.elementwise_mul(
            layers.scale(good, bias=1.0), fin_f)
        from ...layers import tensor as ltensor

        n = ltensor.fill_constant([1], "float32",
                                  float(self._incr_every_n_steps))
        reached = layers.cast(good_next >= n, "float32")
        not_reached = layers.scale(reached, scale=-1.0, bias=1.0)
        grown = layers.scale(scale_var, scale=self._incr_ratio)
        shrunk = layers.scale(scale_var, scale=self._decr_ratio)
        keep_or_grow = layers.elementwise_add(
            layers.elementwise_mul(grown, reached),
            layers.elementwise_mul(scale_var, not_reached))
        new_scale = layers.elementwise_add(
            layers.elementwise_mul(keep_or_grow, fin_f),
            layers.elementwise_mul(shrunk, one_minus))
        new_good = layers.elementwise_mul(good_next, not_reached)
        block = scale_var.block
        block.append_op(type="assign", inputs={"X": [new_scale]},
                        outputs={"Out": [scale_var]})
        block.append_op(type="assign", inputs={"X": [new_good]},
                        outputs={"Out": [good]})

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def apply_optimize(self, loss, startup_program, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    def __getattr__(self, item):
        return getattr(self._optimizer, item)


def decorate(optimizer, amp_lists=None, init_loss_scaling=1.0,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=False):
    """Wrap an optimizer for mixed-precision training (reference
    decorator.py:216)."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio, decr_ratio)
