"""Op lists for mixed precision (parity:
contrib/mixed_precision/fp16_lists.py).  On TPU the policy is bf16 compute
inside the MXU ops (white list) with f32 accumulation — black/gray lists are
kept for API parity and for the explicit cast-rewrite mode."""

white_list = {"conv2d", "matmul", "mul", "depthwise_conv2d"}

black_list = {
    "exp", "square", "log", "mean", "sum", "cos_sim",
    "softmax", "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
    "cross_entropy", "cross_entropy2", "layer_norm",
}

gray_list = {
    "elementwise_add", "elementwise_sub", "elementwise_mul", "elementwise_div",
    "elementwise_max", "elementwise_min", "elementwise_pow", "elementwise_mod",
    "batch_norm", "tanh", "sigmoid", "relu", "relu6", "leaky_relu", "gelu",
    "dropout", "pool2d", "transpose2", "reshape2", "concat", "split", "slice",
    "scale", "cast", "stack", "squeeze2", "unsqueeze2", "top_k", "flatten2",
    "lookup_table", "lookup_table_v2", "gather", "pad",
}


class AutoMixedPrecisionLists:
    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(white_list)
        self.black_list = set(black_list)
        self.gray_list = set(gray_list)
        if custom_white_list:
            self.white_list |= set(custom_white_list)
            self.black_list -= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
            self.white_list -= set(custom_black_list)
