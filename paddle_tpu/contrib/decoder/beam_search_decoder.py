"""StateCell/TrainingDecoder/BeamSearchDecoder (parity:
contrib/decoder/beam_search_decoder.py:43-842).

The reference builds these on LoD machinery: ragged beams via
sequence_expand, a dynamic While loop, and lod_reset plumbing.  The
TPU-native design keeps the SAME API but rides the padded dense beam state
this framework uses everywhere (ops/beam_search.py: [batch, beam] dense
tensors, finished beams pinned on end_id):

- StateCell: identical contract — `inputs`/`states` dicts, a
  `@state_cell.state_updater` decorator, compute_state/get_state/
  set_state/update_states.
- TrainingDecoder: teacher-forced decoding over StaticRNN (lax.scan under
  jit), states as RNN memories.
- BeamSearchDecoder: `decode()` unrolls `max_len` dense beam steps
  (static shapes — XLA compiles one fused module; the reference's While +
  early_stop dissolves into the finished-beam mask, which freezes ended
  beams exactly like the reference's shrinking LoD), then backtracks with
  the beam_search_decode op.
"""

import contextlib

from ... import layers
from ...framework import Variable

__all__ = ["InitState", "StateCell", "TrainingDecoder",
           "BeamSearchDecoder"]


class _DecoderType:
    TRAINING = 1
    BEAM_SEARCH = 2


class InitState(object):
    """Initial hidden state: an explicit variable, or a constant tensor
    shaped like `init_boot` (reference beam_search_decoder.py:43-99)."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                "init_boot must be provided to infer the shape of "
                "InitState .\n")
        else:
            self._init = layers.fill_constant_batch_size_like(
                input=init_boot, value=value, shape=shape or [-1, 1],
                dtype=dtype)
        self._shape = shape
        self._value = value
        self._need_reorder = need_reorder
        self._dtype = dtype

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class _MemoryState(object):
    """Training-mode state: a StaticRNN memory."""

    def __init__(self, state_name, rnn_obj, init_state):
        self._state_name = state_name
        self._rnn_obj = rnn_obj
        self._state_mem = self._rnn_obj.memory(init=init_state.value)

    def get_state(self):
        return self._state_mem

    def update_state(self, state):
        self._rnn_obj.update_memory(self._state_mem, state)


class _DenseState(object):
    """Beam-search-mode state: a plain variable chained across the
    unrolled steps (the reference's _ArrayState tensor-array becomes
    direct SSA chaining under the static unroll)."""

    def __init__(self, state_name, init_state):
        self._state_name = state_name
        self._var = init_state.value

    def get_state(self):
        return self._var

    def update_state(self, state):
        self._var = state


class StateCell(object):
    """Holds the decoder's hidden states and the updater that advances
    them one step (reference beam_search_decoder.py:159-384).

    Args:
        inputs: dict name -> Variable|None; None entries are filled per
            step via compute_state(inputs=...).
        states: dict name -> InitState.
        out_state: name of the state to expose as the step output.
    """

    def __init__(self, inputs, states, out_state, name=None):
        self._inputs = dict(inputs)
        self._cur_states = {}
        self._state_names = []
        self._states_holder = {}
        for state_name, state in states.items():
            if not isinstance(state, InitState):
                raise ValueError("state must be an InitState object.")
            self._cur_states[state_name] = state
            self._state_names.append(state_name)
        self._out_state = out_state
        self._state_updater = None
        self._cur_decoder_obj = None
        self._switched_decoder = False
        self._in_decoder = False

    def _enter_decoder(self, decoder_obj):
        if self._in_decoder:
            raise ValueError("StateCell has already entered a decoder.")
        self._in_decoder = True
        self._cur_decoder_obj = decoder_obj
        self._switched_decoder = False

    def _leave_decoder(self, decoder_obj):
        if not self._in_decoder or self._cur_decoder_obj is not decoder_obj:
            raise ValueError(
                "StateCell not in decoder %r" % decoder_obj)
        self._in_decoder = False
        self._cur_decoder_obj = None
        self._switched_decoder = False

    def _switch_decoder(self):
        if not self._in_decoder:
            raise ValueError("StateCell must be in a decoder.")
        if self._switched_decoder:
            raise ValueError("StateCell already switched.")
        for state_name in self._state_names:
            init = self._cur_states[state_name]
            if not isinstance(init, InitState):
                raise ValueError("init state diverged before switch")
            if self._cur_decoder_obj.type == _DecoderType.TRAINING:
                holder = _MemoryState(state_name,
                                      self._cur_decoder_obj.dynamic_rnn,
                                      init)
            else:
                holder = _DenseState(state_name, init)
            self._states_holder[state_name] = holder
            self._cur_states[state_name] = holder.get_state()
        self._switched_decoder = True

    def get_state(self, state_name):
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        if state_name not in self._cur_states:
            raise ValueError("Unknown state %s." % state_name)
        return self._cur_states[state_name]

    def get_input(self, input_name):
        if input_name not in self._inputs or \
                self._inputs[input_name] is None:
            raise ValueError("Invalid input %s." % input_name)
        return self._inputs[input_name]

    def set_state(self, state_name, state_value):
        self._cur_states[state_name] = state_value

    def state_updater(self, updater):
        self._state_updater = updater

        def _decorator(state_cell):
            if state_cell is not self:
                raise TypeError("updater is bound to another cell")
            updater(state_cell)

        return _decorator

    def compute_state(self, inputs):
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        for input_name, input_value in inputs.items():
            if input_name not in self._inputs:
                raise ValueError("Unknown input %s." % input_name)
            self._inputs[input_name] = input_value
        self._state_updater(self)

    def update_states(self):
        if self._in_decoder and not self._switched_decoder:
            raise ValueError("update_states before compute_state")
        for state_name, holder in self._states_holder.items():
            holder.update_state(self._cur_states[state_name])
            self._cur_states[state_name] = holder.get_state()

    def out_state(self):
        return self._cur_states[self._out_state]


class TrainingDecoder(object):
    """Teacher-forced decoder (reference beam_search_decoder.py:384-520):
    per-step logic inside ``with decoder.block():`` over a StaticRNN."""

    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell, name=None):
        self._state_cell = state_cell
        self._status = TrainingDecoder.BEFORE_DECODER
        self.dynamic_rnn = layers.StaticRNN()
        self._type = _DecoderType.TRAINING
        self._state_cell._enter_decoder(self)

    @property
    def state_cell(self):
        self._assert_in_decoder_block("state_cell")
        return self._state_cell

    @property
    def type(self):
        return self._type

    @contextlib.contextmanager
    def block(self):
        if self._status != TrainingDecoder.BEFORE_DECODER:
            raise ValueError("decoder.block() can only be invoked once")
        self._status = TrainingDecoder.IN_DECODER
        with self.dynamic_rnn.step():
            yield
        self._status = TrainingDecoder.AFTER_DECODER
        self._state_cell._leave_decoder(self)

    def step_input(self, x):
        """x: [B, T, D] teacher sequence -> per-step [B, D]."""
        self._assert_in_decoder_block("step_input")
        return self.dynamic_rnn.step_input(x)

    def static_input(self, x):
        self._assert_in_decoder_block("static_input")
        return x

    def output(self, *outputs):
        self._assert_in_decoder_block("output")
        self.dynamic_rnn.step_output(*outputs)

    def __call__(self, *args, **kwargs):
        if self._status != TrainingDecoder.AFTER_DECODER:
            raise ValueError("Output of training decoder can only be "
                             "visited outside the block.")
        return self.dynamic_rnn(*args, **kwargs)

    def _assert_in_decoder_block(self, method):
        if self._status != TrainingDecoder.IN_DECODER:
            raise ValueError("%s should be invoked inside block of "
                             "TrainingDecoder object." % method)


class BeamSearchDecoder(object):
    """Dense static beam search (reference beam_search_decoder.py:520-842).

    Same constructor/`decode()`/`__call__` contract; internally the beams
    are the padded [batch, beam] dense state of ops/beam_search.py, the
    generation loop unrolls to `max_len` (finished beams are frozen on
    end_id by the beam_search op — the dense analog of the reference's
    early_stop/While), and the result is backtracked with
    beam_search_decode.
    """

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict=None, topk_size=50,
                 sparse_emb=True, max_len=100, beam_size=1, end_id=1,
                 name=None):
        self._state_cell = state_cell
        self._type = _DecoderType.BEAM_SEARCH
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = target_dict_dim
        self._word_dim = word_dim
        self._input_var_dict = dict(input_var_dict or {})
        self._topk_size = topk_size
        self._sparse_emb = sparse_emb
        self._max_len = max_len
        self._beam_size = beam_size
        self._end_id = end_id
        self._name = name or "beam_search_decoder"
        self._decoded = False
        self._result = None
        self._state_cell._enter_decoder(self)

    @property
    def state_cell(self):
        return self._state_cell

    @property
    def type(self):
        return self._type

    def decode(self):
        """Build the unrolled dense beam-search graph."""
        if self._decoded:
            raise ValueError("decode() can only be invoked once")
        import numpy as np

        K = self._beam_size
        # dense init: ids/scores [B, K]
        pre_ids = layers.reshape(self._init_ids, shape=[-1, K])
        pre_scores = layers.reshape(self._init_scores, shape=[-1, K])
        # seed beams 1..K-1 at -inf (beam 0 only at step 0): with the
        # conventional all-zeros init_scores every beam would otherwise be
        # identical and decode K duplicate greedy sequences (same protocol
        # as layers/rnn.py BeamSearchDecoder's logp seeding).  Built as an
        # outer product so a dynamic batch dim works.
        from ...layers import tensor as ltensor

        ones_col = ltensor.fill_constant_batch_size_like(
            pre_scores, [-1, 1], "float32", 1.0)
        beam_bias = ltensor.assign(
            np.array([[0.0] + [-1e9] * (K - 1)], "float32"))
        pre_scores = layers.elementwise_add(
            pre_scores, layers.matmul(ones_col, beam_bias))

        # beam-expand every state: [B, D] -> [B*K, D]
        for state_name in self._state_cell._state_names:
            st = self._state_cell.get_state(state_name)
            ex = layers.expand(layers.unsqueeze(st, axes=[1]),
                               expand_times=[1, K, 1])
            self._state_cell.set_state(
                state_name, layers.reshape(ex, shape=[-1, st.shape[-1]]))
        self._state_cell.update_states()

        step_ids, step_parents, step_scores = [], [], []
        for _ in range(self._max_len):
            prev_ids_flat = layers.reshape(pre_ids, shape=[-1, 1])
            from ...param_attr import ParamAttr

            emb = layers.embedding(
                input=prev_ids_flat,
                size=[self._target_dict_dim, self._word_dim],
                dtype="float32", is_sparse=self._sparse_emb,
                param_attr=ParamAttr(name=self._name + "_emb"))
            emb = layers.reshape(emb, shape=[-1, self._word_dim])

            feed_dict = {}
            for name, var in self._input_var_dict.items():
                if name not in self._state_cell._inputs:
                    raise ValueError(
                        "Variable %s not found in StateCell!\n" % name)
                feed_dict[name] = var
            for input_name in self._state_cell._inputs:
                if input_name not in feed_dict:
                    feed_dict[input_name] = emb

            self._state_cell.compute_state(inputs=feed_dict)
            current_state = self._state_cell.out_state()
            scores = layers.fc(
                current_state, self._target_dict_dim, act="softmax",
                param_attr=ParamAttr(name=self._name + "_fc_w"),
                bias_attr=ParamAttr(name=self._name + "_fc_b"))
            log_scores = layers.reshape(
                layers.log(scores), shape=[-1, K, self._target_dict_dim])
            if self._topk_size < self._target_dict_dim:
                # reference pre-prunes with topk before beam_search; the
                # dense analog masks everything below each beam's top-k
                # threshold to -inf (same candidate set)
                topk_vals, _ = layers.topk(log_scores, self._topk_size)
                thresh = layers.slice(
                    topk_vals, axes=[2],
                    starts=[self._topk_size - 1],
                    ends=[self._topk_size])           # [B, K, 1]
                keep = layers.cast(
                    layers.greater_equal(log_scores, thresh), "float32")
                log_scores = layers.elementwise_add(
                    layers.elementwise_mul(log_scores, keep),
                    layers.scale(keep, scale=1e9, bias=-1e9))
            # axis=0: align pre_scores [B, K] to log_scores' leading dims
            # (the reference's accu_scores add uses the same axis=0)
            accu = layers.elementwise_add(log_scores, pre_scores, axis=0)
            sel_ids, sel_scores, parent_idx = layers.beam_search(
                pre_ids, pre_scores, None, accu, K, self._end_id)
            # reorder states by the winning parents
            for state_name in self._state_cell._state_names:
                st = self._state_cell.get_state(state_name)
                st_k = layers.reshape(st, shape=[-1, K, st.shape[-1]])
                picked = self._gather_beams(st_k, parent_idx, K)
                new_st = layers.reshape(picked,
                                        shape=[-1, st.shape[-1]])
                # the one-hot gather erases a concrete B*K dim; restore
                # it so later fc shape unification sees matched batches
                if st.shape is not None:
                    new_st.shape = tuple(st.shape)
                self._state_cell.set_state(state_name, new_st)
            self._state_cell.update_states()

            step_ids.append(sel_ids)
            step_parents.append(parent_idx)
            step_scores.append(sel_scores)
            pre_ids, pre_scores = sel_ids, sel_scores

        ids_arr = layers.stack(step_ids, axis=0)        # [T, B, K]
        parents_arr = layers.stack(step_parents, axis=0)
        scores_arr = layers.stack(step_scores, axis=0)
        self._result = layers.beam_search_decode(
            ids_arr, parents_arr, scores=scores_arr,
            beam_size=K, end_id=self._end_id)
        self._decoded = True
        self._state_cell._leave_decoder(self)

    @staticmethod
    def _gather_beams(state_k, parent_idx, beam_size):
        """state_k [B, K, D], parent_idx [B, K] int -> state rows picked
        per batch by parent index.  Delegates to the shared one-hot-matmul
        gather (layers/rnn.py _batched_gather), which needs no static
        batch dim."""
        from ...layers.rnn import _batched_gather

        return _batched_gather(state_k, parent_idx)

    def early_stop(self):
        """Dense design: finished beams are already frozen on end_id by
        the beam_search op; per-step early exit dissolves (the unrolled
        tail is identity on finished beams)."""

    def __call__(self):
        if not self._decoded:
            raise ValueError("decode() must be called before the decoder")
        return self._result
