"""Deprecated high-level Trainer API.

Parity: python/paddle/fluid/contrib/trainer.py:34 (deprecated upstream in
favor of the Executor/fleet APIs, kept for user-code compatibility).
A compact but functional implementation: the event classes, the
epoch/step training loop with event callbacks, test(), save_params(),
save/load/clean_checkpoint and CheckpointConfig over this repo's
Executor + io machinery.
"""

import os
import shutil

from .. import framework, io, optimizer as _optimizer_mod
from ..core.executor import Executor, scope_guard
from ..core.scope import Scope
from ..data_feeder import DataFeeder
from ..framework import CPUPlace, Program, program_guard

__all__ = [
    "BeginEpochEvent", "EndEpochEvent", "BeginStepEvent", "EndStepEvent",
    "CheckpointConfig", "Trainer", "save_checkpoint", "load_checkpoint",
    "clean_checkpoint",
]


class BeginEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent(object):
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent(object):
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig(object):
    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10):
        self.checkpoint_dir = checkpoint_dir or os.getcwd()
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(int(epoch_interval), 1)
        self.step_interval = max(int(step_interval), 1)
        self.epoch_id = 0
        self.step_id = 0
        self.load_serial = None


def check_and_get_place(place):
    if place is None:
        return CPUPlace()
    return place


class Trainer(object):
    """train_func() builds the forward and returns the loss (first return
    value); optimizer_func() returns the Optimizer.  train() runs the
    epoch/step loop, posting Begin/End Epoch/Step events to
    event_handler exactly like the reference."""

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None):
        self.__stop = False
        self.parallel = parallel
        self.checkpoint_cfg = checkpoint_config
        self.place = check_and_get_place(place)
        self.scope = Scope()
        self.startup_program = Program()
        self.train_program = Program()
        from ..utils import unique_name

        with program_guard(self.train_program, self.startup_program):
            # fresh name scope so a later Inferencer's infer_func (also
            # guarded) recreates the same parameter names
            with unique_name.guard():
                loss = train_func()
                if isinstance(loss, (list, tuple)):
                    self.train_func_outputs = list(loss)
                    loss = loss[0]
                else:
                    self.train_func_outputs = [loss]
                self.loss = loss
                self.test_program = self.train_program.clone(for_test=True)
                opt = optimizer_func()
                if not isinstance(opt, _optimizer_mod.Optimizer):
                    raise TypeError(
                        "The optimizer should be an instance of Optimizer")
                opt.minimize(loss)
        self.exe = Executor(self.place)
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
        if param_path and os.path.isdir(param_path):
            with scope_guard(self.scope):
                io.load_persistables(self.exe, param_path,
                                     main_program=self.train_program)

    def stop(self):
        """Ask the training loop to stop after the current step."""
        self.__stop = True

    def train(self, num_epochs, event_handler, reader=None, feed_order=None):
        feeder = DataFeeder(feed_list=[
            self.train_program.global_block().var(n)
            for n in (feed_order or [])
        ], place=self.place) if feed_order else None
        with scope_guard(self.scope):
            for epoch_id in range(num_epochs):
                event_handler(BeginEpochEvent(epoch_id))
                for step_id, data in enumerate(reader()):
                    if self.__stop:
                        return
                    begin_event = BeginStepEvent(epoch_id, step_id)
                    event_handler(begin_event)
                    fetch = (self.train_func_outputs
                             if begin_event.fetch_metrics else [])
                    metrics = self.exe.run(
                        self.train_program,
                        feed=feeder.feed(data) if feeder else data,
                        fetch_list=fetch)
                    event_handler(EndStepEvent(epoch_id, step_id, metrics))
                    if (self.checkpoint_cfg
                            and step_id % self.checkpoint_cfg.step_interval
                            == 0):
                        self._save_checkpoint(epoch_id, step_id)
                event_handler(EndEpochEvent(epoch_id))

    def test(self, reader, feed_order):
        feeder = DataFeeder(feed_list=[
            self.train_program.global_block().var(n) for n in feed_order
        ], place=self.place)
        accumulated = [0.0] * len(self.train_func_outputs)
        count = 0
        with scope_guard(self.scope):
            for data in reader():
                outs = self.exe.run(self.test_program,
                                    feed=feeder.feed(data),
                                    fetch_list=self.train_func_outputs)
                accumulated = [a + float(o[0]) for a, o in
                               zip(accumulated, outs)]
                count += 1
        return [a / max(count, 1) for a in accumulated]

    def save_params(self, param_path):
        with scope_guard(self.scope):
            io.save_persistables(self.exe, param_path,
                                 main_program=self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes):
        with scope_guard(self.scope):
            io.save_inference_model(
                param_path, feeded_var_names,
                [self.train_func_outputs[i] for i in target_var_indexes],
                self.exe, program=self.test_program)

    def _save_checkpoint(self, epoch_id, step_id):
        cfg = self.checkpoint_cfg
        if epoch_id % cfg.epoch_interval != 0:
            return
        serial_dir = os.path.join(cfg.checkpoint_dir,
                                  "checkpoint_%d_%d" % (epoch_id, step_id))
        save_checkpoint(self.exe, serial_dir, self.train_program)
        def ckpt_key(d):
            try:  # numeric (epoch, step): 'checkpoint_10_0' > 'checkpoint_9_0'
                _, e, st = d.split("_")
                return (int(e), int(st))
            except ValueError:
                return (-1, -1)

        existing = sorted(
            (d for d in os.listdir(cfg.checkpoint_dir)
             if d.startswith("checkpoint_")), key=ckpt_key)
        while len(existing) > cfg.max_num_checkpoints:
            shutil.rmtree(os.path.join(cfg.checkpoint_dir, existing.pop(0)),
                          ignore_errors=True)


def build_feed_var_list(program, feed_order):
    if feed_order is None:
        feed_order = []
    if isinstance(feed_order, dict):
        feed_order = [k for k, _ in
                      sorted(feed_order.items(), key=lambda kv: kv[1])]
    return [program.global_block().var(name) for name in feed_order]


def save_checkpoint(executor, checkpoint_dir, main_program=None):
    """Persist all persistables of main_program under checkpoint_dir
    (reference trainer.py:663)."""
    os.makedirs(checkpoint_dir, exist_ok=True)
    io.save_persistables(executor, checkpoint_dir,
                         main_program=main_program)


def load_checkpoint(executor, checkpoint_dir, main_program=None):
    """Restore persistables saved by save_checkpoint
    (reference trainer.py:763)."""
    io.load_persistables(executor, checkpoint_dir,
                         main_program=main_program)


def clean_checkpoint(checkpoint_dir, delete_dir=False):
    if checkpoint_dir is None:
        raise ValueError("'checkpoint_dir' should not be None")
    for d in os.listdir(checkpoint_dir):
        if d.startswith("checkpoint_"):
            shutil.rmtree(os.path.join(checkpoint_dir, d),
                          ignore_errors=True)
    if delete_dir and not os.listdir(checkpoint_dir):
        os.rmdir(checkpoint_dir)
