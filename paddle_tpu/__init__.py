"""paddle_tpu: a TPU-native framework with the Fluid capability surface.

Usage mirrors the reference (``import paddle.fluid as fluid`` becomes
``import paddle_tpu as fluid``): build a Program with layers, run it with an
Executor on CPUPlace/TPUPlace.  Execution lowers whole blocks to XLA via JAX.
"""

import jax as _jax

try:
    # Make every in-trace random draw a pure function of (key, global
    # element offset): the legacy threefry lowering re-derives its
    # counter per SHARD under GSPMD, so the same program draws a
    # different dropout mask once a mesh shards its operands (the
    # dp4xtp2 ~0.5%-rel drift — ROADMAP "TP dropout stream alignment").
    # Global-offset counters make the draw sharding-invariant.
    _jax.config.update("jax_threefry_partitionable", True)
except AttributeError:  # newer jax: partitionable is the only mode
    pass

from . import framework
from .framework import (
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    Program,
    TPUPlace,
    Variable,
    cpu_places,
    cuda_places,
    default_main_program,
    default_startup_program,
    in_dygraph_mode,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    name_scope,
    program_guard,
    tpu_places,
    require_version,
    load_op_library,
    core,
)
from . import distribute_lookup_table
from .core.scope import LoDTensorArray
from .core.executor import Executor, global_scope, scope_guard
from .core.scope import Scope
from .compiler import BuildStrategy, CompiledProgram, ExecutionStrategy
from .param_attr import ParamAttr, WeightNormParamAttr
from .backward import append_backward, gradients
from . import layers
from . import nets
from . import input
from .input import one_hot, embedding
from . import lod_tensor
from .lod_tensor import create_lod_tensor, create_random_int_lodtensor
from . import average
from . import evaluator
from . import install_check
from . import debugger
from . import parallel_executor
from .parallel_executor import ParallelExecutor
from . import initializer
from . import optimizer
from . import regularizer
from . import clip
from . import backward
from . import contrib
from . import transpiler
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig
from . import incubate
from . import distributed
from . import unique_name_compat as unique_name  # noqa: F401
from .data_feeder import DataFeeder
from . import io
from .io import save_inference_model, load_inference_model
from .io import save, load, load_program_state, set_program_state
from .reader import DataLoader, PyReader
from .dataset import DatasetFactory
from . import dataset
from . import datasets
from . import dygraph
from . import metrics
from . import profiler
from .core import telemetry
from .core import tracing
from . import flags
from . import parallel
from .flags import set_flags, get_flags
from . import inference
from .inference import AnalysisConfig, create_paddle_predictor
from . import reader  # DataLoader module; also re-exports the decorators
from .reader_decorator import batch
from .core.scope import TpuTensor as LoDTensor  # reference core.LoDTensor
from . import compat_modules as _compat_modules
_compat_modules.wire_aliases()

__version__ = "0.1.0"


def data(name, shape, dtype="float32", lod_level=0):
    """fluid.data — batch dim must be given explicitly (often -1)."""
    return layers.data(
        name=name, shape=shape, dtype=dtype, lod_level=lod_level,
        append_batch_size=False,
    )


class DataFeedDesc:
    """Parsed data-feed description (reference data_feed.proto +
    python/paddle/fluid/data_feed_desc.py).  Reads the prototxt slot config
    the reference uses (name/type/dense flags under multi_slot_desc) into a
    plain object the Dataset facade consumes."""

    def __init__(self, proto_file=None):
        self.proto_file = proto_file
        self.batch_size = 32
        self.pipe_command = "cat"
        self.slots = []  # [{"name","type","is_dense","is_used"}]
        if proto_file:
            self._parse(proto_file)

    def _parse(self, path):
        import re

        text = open(path).read()
        self.slots = []
        for m in re.finditer(r"slots\s*\{([^}]*)\}", text):
            body = m.group(1)

            def field(key, default=None):
                fm = re.search(r"%s\s*:\s*(\S+)" % key, body)
                return fm.group(1).strip('"') if fm else default

            self.slots.append({
                "name": field("name", ""),
                "type": field("type", "float"),
                "is_dense": field("is_dense", "false") == "true",
                "is_used": field("is_used", "false") == "true",
            })
        bm = re.search(r"batch_size\s*:\s*(\d+)", text)
        if bm:
            self.batch_size = int(bm.group(1))

    def set_batch_size(self, bs):
        self.batch_size = int(bs)

    def set_dense_slots(self, names):
        for s in self.slots:
            if s["name"] in names:
                s["is_dense"] = True

    def set_use_slots(self, names):
        for s in self.slots:
            if s["name"] in names:
                s["is_used"] = True

    def desc(self):
        lines = ["batch_size: %d" % self.batch_size,
                 'pipe_command: "%s"' % self.pipe_command,
                 "multi_slot_desc {"]
        for s in self.slots:
            lines += ["  slots {",
                      '    name: "%s"' % s["name"],
                      '    type: "%s"' % s["type"],
                      "    is_dense: %s" % str(s["is_dense"]).lower(),
                      "    is_used: %s" % str(s["is_used"]).lower(),
                      "  }"]
        lines.append("}")
        return "\n".join(lines)
