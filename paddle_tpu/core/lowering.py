"""Block capture: lower a whole Block's op list into ONE pure JAX function.

This replaces the reference's op-at-a-time interpreter
(``paddle/fluid/framework/executor.cc:448`` — `for op in ops: op->Run`) with
whole-block staging: every op's registered lowering is traced into a single
XLA computation which `jax.jit` compiles once per (program, shapes) key.  This
is the TPU-idiomatic execution model — XLA fuses across op boundaries, plans
HBM, and overlaps collectives; per-op dispatch only exists in dygraph mode.
"""

import jax
import jax.numpy as jnp

from .registry import get_op_def, _lower_attrs

__all__ = ["LowerCtx", "BlockPlan", "analyze_block", "analyze_param_carry",
           "build_block_fn"]


class LowerCtx:
    """Per-op context handed to lowerings.

    Carries the PRNG key (functional randomness — TPU-native replacement for
    the reference's per-device curand generators), the op desc being lowered,
    and mesh/axis info when lowering inside a shard_map (manual collectives).
    """

    def __init__(self, rng_key=None, op=None, block=None, mesh=None,
                 axis_names=(), mode="traced", runner=None, env=None,
                 data_axis=None):
        self._rng_key = rng_key
        self._rng_n = 0
        self.op = op
        self.block = block
        self.mesh = mesh
        self.axis_names = tuple(axis_names)
        self.mode = mode  # "traced" | "abstract" | "eager"
        # which mesh axis (if any) shards the BATCH dim of feeds —
        # sequence-parallel ops must not mistake it for a sequence axis
        self.data_axis = data_axis
        self.runner = runner  # BlockRunner for ops with sub-blocks
        # live name->value environment of the enclosing block trace; used by
        # control-flow ops (while/conditional_block) whose sub-blocks read
        # outer variables (analog of the reference's kid-scope chain,
        # paddle/fluid/framework/scope.h:46)
        self.env = env

    def run_sub_block(self, block_idx, env, base_key=None):
        """Run every op of a sub-block against `env` (in place)."""
        block = self.block.program.block(block_idx)
        for i, op in enumerate(block.ops):
            key = None
            if base_key is not None:
                key = jax.random.fold_in(base_key, i)
            run_op(op, env, key, mesh=self.mesh, axis_names=self.axis_names)

    def rng(self):
        if self._rng_key is None:
            if self.mode == "abstract":
                return jax.random.key(0)
            raise RuntimeError(
                "op %s requested randomness but no PRNG key is available"
                % (self.op.type if self.op else "?")
            )
        k = jax.random.fold_in(self._rng_key, self._rng_n)
        self._rng_n += 1
        return k

    def amp_bf16(self):
        """True when the program requests the bf16 mixed-precision policy
        (set by paddle_tpu.contrib.mixed_precision.decorate)."""
        blk = self.block
        prog = blk.program if blk is not None else None
        return bool(getattr(prog, "_amp_bf16", False))

    @classmethod
    def abstract(cls, n_rng=0):
        return cls(mode="abstract")


def _iter_runtime_ops(block):
    for op in block.ops:
        if op.type in ("feed", "fetch"):
            continue
        yield op


def analyze_block(block, feed_names):
    """Liveness analysis: which names must come from the scope (external),
    and which persistables are (re)written and must be stored back."""
    feed = set(feed_names)
    written = set()
    external = []
    external_set = set()
    for op in _iter_runtime_ops(block):
        for name in op.input_arg_names:
            if not name:
                continue
            if name in feed or name in written or name in external_set:
                continue
            if name.endswith("@GRAD") or "@GRAD@" in name:
                # grad var not yet produced: implicit zeros (handled by the
                # grad lowering), never an external scope read
                continue
            v = block._find_var_recursive(name)
            if v is not None and getattr(v, "type", None) == "LOD_TENSOR_ARRAY":
                # tensor arrays are trace-local (Python lists in the env),
                # never scope-resident; first write creates them
                continue
            external.append(name)
            external_set.add(name)
        for name in op.output_arg_names:
            if name:
                written.add(name)
    persist_written = []
    for op in _iter_runtime_ops(block):
        for name in op.output_arg_names:
            if not name or name in feed:
                continue
            v = block._find_var_recursive(name)
            if v is not None and v.persistable and name not in persist_written:
                persist_written.append(name)
    return external, written, persist_written


class BlockPlan:
    """Compiled execution plan for one block + feed/fetch signature."""

    def __init__(self, block, feed_names, fetch_names, allow_carry=False):
        self.block = block
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        ext, written, persist_written = analyze_block(block, feed_names)
        self.external = ext
        self.persist_written = persist_written
        # external names that get overwritten -> donatable (read-write)
        self.rw_names = [n for n in ext if n in set(persist_written)]
        rw = set(self.rw_names)
        self.ro_names = [n for n in ext if n not in rw]
        # layout-matched param carry (FLAGS_layout_match_params): persistent
        # f32 weights whose every read is a bf16 matmul/conv consumption
        # enter the compiled step as bf16 arrays pinned across steps
        self.carry_names = (
            analyze_param_carry(block, self.feed_names, fetch_names,
                                self.ro_names, self.rw_names)
            if allow_carry else [])
        if self.carry_names:
            carried = set(self.carry_names)
            # read-only carried params drop out of the f32 argument list
            # entirely: the trace only ever sees their bf16 carry copy
            self.ro_names = [n for n in self.ro_names if n not in carried]


# forward op types whose lowerings consume their (weight) operands in bf16
# under the AMP policy — the set a carried param may be read by.  The
# synthesized `<type>_grad` ops replay the forward via jax.vjp, so they
# consume the same bf16 value and yield a bf16 cotangent (the same value
# the old astype-vjp upcast produced, so the optimizer's astype(f32) is
# bitwise-identical to the per-step-cast scheme).
_CARRY_CONSUMERS = frozenset((
    "mul", "matmul", "matmul_v2", "conv2d", "depthwise_conv2d",
))

# optimizer op types: their "Param" slot must read the f32 MASTER value
# (redirected to <name>@MASTER by _gather_slot), never the bf16 carry
_OPTIMIZER_TYPES = frozenset((
    "sgd", "momentum", "adam", "adamax", "adagrad", "decayed_adagrad",
    "adadelta", "rmsprop", "lars_momentum", "lamb", "ftrl", "dpsgd",
    "fused_sgd", "fused_momentum", "fused_adam",
))

# ops with sub-blocks read outer vars through ctx.env without appearing in
# the top-level input scan — carry analysis cannot see those reads
_SUBBLOCK_OPS = frozenset((
    "while", "conditional_block", "recurrent", "py_func",
))

_MASTER_SUFFIX = "@MASTER"


def analyze_param_carry(block, feed_names, fetch_names, ro_names, rw_names):
    """Names of persistable f32 params safe to pin in bf16 across steps.

    Eligible: every reader is either (a) an optimizer op reading the param
    via its "Param" slot (redirected to the f32 master inside the trace) or
    (b) one forward op in _CARRY_CONSUMERS plus at most one matching grad
    op (whose vjp replay consumes the identical bf16 value); the only
    writer, if any, is that optimizer's in-place ParamOut.  Feed/fetch
    targets and blocks containing sub-block ops are excluded — a fetched
    param must come back f32, and sub-blocks read outer vars invisibly to
    this scan.  The single-forward-consumer rule keeps gradient
    accumulation out of scope: two bf16 branch grads would sum in bf16
    where the per-step-cast scheme summed their f32 upcasts."""
    import numpy as np

    from ..framework import dtype_to_np

    if any(op.type in _SUBBLOCK_OPS for op in block.ops):
        return []
    prog = block.program
    if not getattr(prog, "_amp_bf16", False):
        return []
    candidates = [n for n in list(ro_names) + list(rw_names)
                  if n not in set(feed_names) and n not in set(fetch_names)]
    readers = {}
    writers = {}
    for op in _iter_runtime_ops(block):
        for n in op.input_arg_names:
            if n:
                readers.setdefault(n, []).append(op)
        for n in op.output_arg_names:
            if n:
                writers.setdefault(n, []).append(op)
    out = []
    for n in candidates:
        v = block._find_var_recursive(n)
        if v is None or not v.persistable or v.shape is None:
            continue
        try:
            if v.dtype is None or dtype_to_np(v.dtype) != np.float32:
                continue
        except Exception:
            continue
        n_fwd = n_grad = 0
        ok = True
        for op in readers.get(n, ()):  # classify every reader
            if (op.type in _OPTIMIZER_TYPES
                    and n in op.input("Param")):
                continue  # master read (redirected inside the trace)
            if op.type in _CARRY_CONSUMERS:
                n_fwd += 1
            elif (op.type.endswith("_grad")
                    and op.type[:-5] in _CARRY_CONSUMERS):
                n_grad += 1
            else:
                ok = False
                break
        if not ok or n_fwd != 1 or n_grad > 1:
            continue
        for op in writers.get(n, ()):  # only in-place optimizer ParamOut
            if not (op.type in _OPTIMIZER_TYPES
                    and n in op.output("ParamOut")):
                ok = False
                break
        if ok:
            out.append(n)
    return out


def _gather_slot(opdef, op, slot, env):
    names = op.input(slot)
    duplicable = slot in opdef.duplicable_inputs
    optional = (
        slot in opdef.optional_inputs
        or slot.startswith("GRAD@")
        or slot.startswith("Out@")
    )
    # layout-matched carry: an optimizer's Param slot must read the f32
    # MASTER value, not the bf16 carry copy the forward/grad ops consume.
    # Only optimizer ops have a "Param" input slot, and carry eligibility
    # already guarantees every other reader wants the bf16 value.
    master = slot == "Param"
    vals = []
    for n in names:
        if not n:
            vals.append(None)
            continue
        if master and (n + _MASTER_SUFFIX) in env:
            vals.append(env[n + _MASTER_SUFFIX])
        elif n in env:
            vals.append(env[n])
        elif optional or n.endswith("@GRAD") or "@GRAD@" in n:
            vals.append(None)
        else:
            raise KeyError(
                "op %s input %s=%r is not initialized (not fed, not in scope, "
                "not produced by a prior op)" % (op.type, slot, n)
            )
    if duplicable:
        return vals
    if not vals:
        return None
    return vals[0]


def _scatter_slot(opdef, op, slot, value, env):
    names = op.output(slot)
    if not names:
        return
    duplicable = slot in opdef.duplicable_outputs
    if duplicable:
        items = list(value) if value is not None else [None] * len(names)
    else:
        items = [value]
    for n, v in zip(names, items):
        if n and v is not None:
            env[n] = v


_AXIS_OPS = frozenset((
    "c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
    "c_allreduce_prod", "c_broadcast", "c_allgather", "c_reducescatter",
    "c_shard_slice", "c_allreduce_qsum", "c_reducescatter_q",
    "c_allgather_q",
    "allreduce", "broadcast",
))


def _any_tracer(args):
    for a in args:
        if isinstance(a, (list, tuple)):
            if _any_tracer(a):
                return True
        elif isinstance(a, jax.core.Tracer):
            return True
    return False


def _constrain_replicated(a, sharding):
    """Pin traced op inputs to a replicated layout (deterministic mode).

    Under GSPMD the partitioner picks per-op shardings, and shard-shape-
    dependent kernels (Eigen gemm tiling, fused FMA grouping) reassociate
    f32 sums relative to the single-device program.  Forcing every op to
    consume replicated operands makes the mesh trace reduce in exactly the
    single-device order — bitwise parity, at gather-bandwidth cost.  Only
    tracers are constrained; concrete compile-time constants pass through
    untouched so constant folding keeps working."""
    if isinstance(a, (list, tuple)):
        return type(a)(_constrain_replicated(x, sharding) for x in a)
    if isinstance(a, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(a, sharding)
    return a


def run_op(op, env, rng_key, mesh=None, axis_names=(), runner=None,
           data_axis=None):
    """Lower one op: gather inputs from env, call the lowering, scatter
    outputs back into env."""
    from .registry import record_executed

    opdef = get_op_def(op.type)
    record_executed(op.type)
    args = [_gather_slot(opdef, op, s, env) for s in opdef.input_slots]
    if mesh is not None and not axis_names and op.type not in _AXIS_OPS:
        from .. import flags as _flags

        if _flags.flag("deterministic_reduction"):
            # GSPMD mesh path: replicate every traced operand so sharded
            # and single-device programs sum f32 in the same order (the
            # dp-grad all-reduce becomes gather-then-reduce in canonical
            # order).  Param/feed shardings at the block boundary are
            # untouched — storage stays sharded.
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(mesh, PartitionSpec())
            args = [_constrain_replicated(a, repl) for a in args]
    ctx = LowerCtx(rng_key=rng_key, op=op, block=op.block, mesh=mesh,
                   axis_names=axis_names, runner=runner, env=env,
                   data_axis=data_axis)
    # Constant folding at trace time: ops whose inputs are all trace-time
    # constants evaluate eagerly.  This keeps loop counters / bounds concrete
    # so `while` can unroll and tensor arrays can grow (ops/control_flow.py).
    # Collectives are excluded (lax.axis_index & co. need the enclosing
    # shard_map trace), as are rng-consuming ops when a key is present (the
    # key is usually traced anyway).
    if (op.type not in _AXIS_OPS
            and (opdef.n_rng == 0 or rng_key is None)
            and not _any_tracer(args)
            and jax.process_count() == 1):
        # multi-process excluded: compile-time-eval arrays get committed
        # with shardings spanning non-addressable devices, which cannot be
        # closed over as constants in the per-process trace
        with jax.ensure_compile_time_eval():
            out = opdef.lower(ctx, *args, **_lower_attrs(op.attrs))
    else:
        out = opdef.lower(ctx, *args, **_lower_attrs(op.attrs))
    if (len(opdef.output_slots) == 1
            and opdef.output_slots[0] in opdef.duplicable_outputs
            and isinstance(out, list)):
        # a bare list from a single-duplicable-output lowering IS the item
        # list — wrap unconditionally so a 1-element list is not mistaken
        # for a positional slot tuple (unstack with num=1, c_sync_comm)
        out = (out,)
    if len(opdef.output_slots) == 1 and not isinstance(out, (tuple, list)):
        out = (out,)
    elif isinstance(out, list):
        out = tuple(out)
    if len(opdef.output_slots) == 1 and len(out) != 1:
        # single duplicable output returned as tuple of items
        out = (list(out),)
    for slot, val in zip(opdef.output_slots, out):
        _scatter_slot(opdef, op, slot, val, env)


def shard_map_compat(f, mesh, in_specs, out_specs):
    """jax.shard_map across the API rename (new: check_vma; old
    jax.experimental.shard_map: check_rep).  Single shim shared by the SPMD
    executor and paddle_tpu.parallel."""
    try:
        from jax import shard_map as _new

        return _new(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    check_vma=False)
    except ImportError:
        from jax.experimental.shard_map import shard_map as _old

        return _old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    check_rep=False)


def has_collective_ops(block):
    """True if the block contains program-level collectives (fleet/transpiler
    path) that require manual SPMD (shard_map) execution."""
    return any(op.type in _AXIS_OPS for op in block.ops)


def build_spmd_block_fn(plan, mesh, axis="data"):
    """Lower the block for per-rank execution under shard_map: every op runs
    on its shard, collectives (c_*) ride the mesh axis via lax.psum & co.

    This is the TPU-native analog of the reference's one-process-per-GPU
    fleet-collective runtime (transpiler/collective.py + NCCL): rank =
    position along the mesh axis, feeds are batch-sharded, parameters
    replicated.  Fetches come back stacked along the axis (shape [nranks,
    ...] per rank-local value, concatenated on dim 0).
    """
    from jax.sharding import PartitionSpec as P

    block = plan.block
    fetch_names = plan.fetch_names
    persist_written = plan.persist_written

    def _var_spec(name):
        # var-level sharding annotation (tuple of axis names / None per
        # dim, stamped by the ZeRO-1 transpiler) -> PartitionSpec; axis
        # names the mesh does not carry degrade to replicated dims
        v = block._find_var_recursive(name)
        ann = getattr(v, "sharding", None) if v is not None else None
        if not ann:
            return P()
        return P(*[a if a == axis else None for a in ann])

    def local(feeds, params_ro, params_rw, rng):
        # param carry is disabled under SPMD (plan.carry_names empty): the
        # shard_map in/out specs are built per-name and the donation
        # aliasing story differs — carry is a single-process optimization
        env = {}
        env.update(params_ro)
        env.update(params_rw)
        env.update(feeds)
        one_rank = mesh.shape[axis] == 1
        rank = None if one_rank else jax.lax.axis_index(axis)
        for i, op in enumerate(_iter_runtime_ops(block)):
            key = None
            if rng is not None:
                key = jax.random.fold_in(rng, i)
                if rank is not None:  # distinct dropout masks per rank
                    key = jax.random.fold_in(key, rank)
            run_op(op, env, key, mesh=mesh, axis_names=(axis,),
                   data_axis=axis)
        fetches = [env[n] for n in fetch_names]
        updated = {n: env[n] for n in persist_written if n in env}
        return fetches, updated

    nranks = mesh.shape[axis]

    def fn(feeds, params_ro, params_rw, rng):
        feed_specs = {}
        for n, v in feeds.items():
            if v.ndim >= 1 and v.shape[0] % nranks == 0:
                feed_specs[n] = P(axis, *([None] * (v.ndim - 1)))
            else:
                feed_specs[n] = P()  # 0-d / non-divisible: replicate
        param_ro_specs = {n: _var_spec(n) for n in params_ro}
        param_rw_specs = {n: _var_spec(n) for n in params_rw}
        # persist_written defaults to replicated: grads are allreduced before
        # any optimizer write, so params stay bitwise-identical across ranks.
        # Rank-local persistable state (e.g. non-sync batch_norm running
        # stats) resolves to one rank's value — same semantics as the
        # reference's DP, where device-0's copy is the one saved
        # (parallel_executor.cc BCastParamsToDevices / save from scope 0).
        # ZeRO-1 optimizer slots carry a var-level `sharding` annotation
        # (axis-name tuple), which maps straight onto the mesh axis here so
        # each rank holds only its 1/nranks slot shard.
        out_specs = ([P(axis)] * len(fetch_names),
                     {n: _var_spec(n) for n in persist_written})
        sm = shard_map_compat(
            local,
            mesh,
            (feed_specs, param_ro_specs, param_rw_specs, P()),
            out_specs,
        )
        return sm(feeds, params_ro, params_rw, rng)

    return fn


def build_block_fn(plan, mesh=None, axis_names=()):
    """Return fn(feeds, params_ro, params_rw, params_carry, rng) ->
    (fetches, updated_rw, updated_carry).

    feeds/params are dicts name->array. `rng` is a jax PRNG key; op i uses
    fold_in(rng, i) so randomness is deterministic per (seed, step, op).

    `params_carry` holds the bf16 layout-matched copies of carried params
    (plan.carry_names): inside the trace the f32 master of a carried
    read-write param moves to <name>@MASTER (read only by the optimizer's
    Param slot via _gather_slot) while every forward/grad op reads the bf16
    carry under the original name.  The returned `updated_carry` is the
    next step's carry dict: the f32 ParamOut refreshed to bf16 (the convert
    fuses into the update kernel), or the unchanged donated input for
    read-only carries (aliased, zero-copy)."""
    block = plan.block
    fetch_names = plan.fetch_names
    persist_written = plan.persist_written
    carry_names = list(getattr(plan, "carry_names", ()))

    def fn(feeds, params_ro, params_rw, params_carry, rng):
        env = {}
        env.update(params_ro)
        env.update(params_rw)
        for n in carry_names:
            if n in env:  # rw-carried: keep the f32 master under @MASTER
                env[n + _MASTER_SUFFIX] = env.pop(n)
        env.update(params_carry)
        env.update(feeds)
        for i, op in enumerate(_iter_runtime_ops(block)):
            key = jax.random.fold_in(rng, i) if rng is not None else None
            run_op(op, env, key, mesh=mesh, axis_names=axis_names)
        fetches = []
        for n in fetch_names:
            if n not in env:
                raise KeyError("fetch target %r was never produced" % n)
            fetches.append(env[n])
        updated = {n: env[n] for n in persist_written if n in env}
        updated_carry = {}
        for n in carry_names:
            if (n + "@PALLAS_BF16") in env:
                # the Pallas fused-opt kernel already cast ParamOut to bf16
                # inside its VMEM pass (ops/optimizer_ops.py stash) —
                # bitwise-identical to the astype below, minus one full
                # elementwise pass over the parameter bytes
                updated_carry[n] = env[n + "@PALLAS_BF16"]
                continue
            v = env[n]  # f32 new master after ParamOut, else the bf16 carry
            if v.dtype != jnp.bfloat16:
                v = v.astype(jnp.bfloat16)
            updated_carry[n] = v
        return fetches, updated, updated_carry

    return fn
