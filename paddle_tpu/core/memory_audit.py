"""HBM footprint auditor: attribute a compiled step's live-buffer peak to
program variables (FLAGS_hbm_audit; tools/profile_bert_step.py --audit).

XLA's ``compiled.memory_analysis()`` reports the executable's buffer
budget — argument / output / temp / alias bytes — but not which *program
var* each argument byte belongs to.  This module pairs that analysis with
the BlockPlan's name->array mapping so the report reads in model terms:
which params ride f32 vs the bf16 carry, which feeds dominate, and how much
of the peak is activation temp (the remat lever) vs resident state (the
donation lever).

The audit runs through the AOT path (``jit(fn).lower(...).compile()``),
which does NOT share jax's call-site executable cache — with the flag on,
each cache entry compiles twice.  That is acceptable for a diagnostic flag
that defaults off.
"""

import logging

import numpy as np

__all__ = ["memory_report", "format_report", "maybe_audit"]


def _nbytes(x):
    try:
        return int(np.prod(x.shape)) * x.dtype.itemsize
    except Exception:
        return 0


def _nbytes_replica(x):
    """Bytes this array occupies PER REPLICA: a mesh-sharded jax.Array
    (e.g. a ZeRO-1 optimizer slot annotated with Variable.sharding) only
    materializes its shard_shape slice on each device; replicated arrays
    cost full size everywhere."""
    try:
        sh = getattr(x, "sharding", None)
        if sh is not None and hasattr(sh, "shard_shape"):
            shp = sh.shard_shape(x.shape)
            return int(np.prod(shp)) * x.dtype.itemsize
    except Exception:
        pass
    return _nbytes(x)


def _analysis_dict(ma):
    out = {}
    for k in ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes"):
        v = getattr(ma, k, None)
        if v is not None:
            out[k] = int(v)
    return out


def memory_report(jfn, feeds, params_ro, params_rw, params_carry, rng,
                  plan=None):
    """Compile `jfn` AOT for this signature and return a dict report:
    XLA's memory_analysis totals plus per-variable argument attribution
    (name, bytes, dtype, class) sorted largest-first."""
    lowered = jfn.lower(feeds, params_ro, params_rw, params_carry, rng)
    compiled = lowered.compile()
    try:
        ma = compiled.memory_analysis()
        analysis = _analysis_dict(ma) if ma is not None else {}
    except Exception as e:  # backend without the query (older PJRT)
        analysis = {"error": str(e)}
    groups = (("feed", feeds), ("param_ro", params_ro),
              ("param_rw", params_rw), ("carry_bf16", params_carry))
    by_var = []
    totals = {}
    totals_replica = {}
    for cls, d in groups:
        sub = sub_r = 0
        for n, v in d.items():
            b = _nbytes(v)
            br = _nbytes_replica(v)
            sub += b
            sub_r += br
            by_var.append({"name": n, "class": cls, "bytes": b,
                           "bytes_per_replica": br,
                           "dtype": str(getattr(v, "dtype", "?")),
                           "shape": list(getattr(v, "shape", ()))})
        totals[cls] = sub
        totals_replica[cls] = sub_r
    by_var.sort(key=lambda r: -r["bytes"])
    report = {
        "analysis": analysis,
        "arg_bytes_by_class": totals,
        "arg_bytes_per_replica_by_class": totals_replica,
        "vars": by_var,
    }
    if plan is not None:
        report["carry_names"] = list(getattr(plan, "carry_names", ()))
        # what the carry saves: carried params would otherwise enter f32
        # AND pay a per-step bf16 convert copy inside the program
        report["carry_saved_bytes"] = sum(
            r["bytes"] for r in by_var if r["class"] == "carry_bf16")
    return report


def _fmt_mb(b):
    return "%.1f MB" % (b / 1e6)


def format_report(report, top=12):
    lines = []
    a = report.get("analysis", {})
    if a and "error" not in a:
        lines.append(
            "hbm_audit: args=%s output=%s temp=%s alias=%s" % (
                _fmt_mb(a.get("argument_size_in_bytes", 0)),
                _fmt_mb(a.get("output_size_in_bytes", 0)),
                _fmt_mb(a.get("temp_size_in_bytes", 0)),
                _fmt_mb(a.get("alias_size_in_bytes", 0))))
        peak = (a.get("argument_size_in_bytes", 0)
                + a.get("output_size_in_bytes", 0)
                + a.get("temp_size_in_bytes", 0)
                - a.get("alias_size_in_bytes", 0))
        lines.append("hbm_audit: upper-bound live peak ~%s "
                     "(args+outputs+temp-aliased)" % _fmt_mb(peak))
    elif a:
        lines.append("hbm_audit: memory_analysis unavailable: %s"
                     % a.get("error"))
    cls = report.get("arg_bytes_by_class", {})
    lines.append("hbm_audit: by class  " + "  ".join(
        "%s=%s" % (k, _fmt_mb(v)) for k, v in sorted(cls.items())))
    cls_r = report.get("arg_bytes_per_replica_by_class", {})
    if cls_r and cls_r != cls:
        # sharded state (ZeRO-1 slots): what each replica materializes
        lines.append("hbm_audit: per replica  " + "  ".join(
            "%s=%s" % (k, _fmt_mb(v)) for k, v in sorted(cls_r.items())))
    if report.get("carry_names"):
        lines.append(
            "hbm_audit: %d params ride the bf16 carry (%s resident bf16 "
            "instead of a per-step f32->bf16 copy)" % (
                len(report["carry_names"]),
                _fmt_mb(report.get("carry_saved_bytes", 0))))
    for r in report.get("vars", [])[:top]:
        lines.append("hbm_audit:   %-40s %10s  %-10s %s" % (
            r["name"][:40], _fmt_mb(r["bytes"]), r["dtype"],
            "x".join(str(s) for s in r["shape"])))
    return "\n".join(lines)


_audited = set()


def maybe_audit(entry, feeds, params_ro, params_rw, params_carry, rng,
                log=None):
    """Audit one _CompiledPlan at most once (keyed by the entry object);
    called from Executor.run when FLAGS_hbm_audit is set."""
    key = id(entry)
    if key in _audited:
        return None
    _audited.add(key)
    try:
        # entry.jfn may be an AOT Compiled (no .lower); the jit wrapper is
        # kept on the entry for exactly this re-lower
        jfn = getattr(entry, "jit_fn", None) or entry.jfn
        report = memory_report(jfn, feeds, params_ro, params_rw,
                               params_carry, rng, plan=entry.plan)
    except Exception as e:
        logging.warning("hbm_audit failed: %s", e)
        return None
    text = format_report(report)
    (log or logging.warning)(text)
    return report
