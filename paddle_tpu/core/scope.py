"""Scope: hierarchical name -> Variable-value map.

TPU-native analog of ``paddle/fluid/framework/scope.h:46``.  Values are
``TpuTensor``s wrapping either a host numpy array or a device ``jax.Array``
(device residency is managed by the executor / PJRT, not by a custom
allocator — HBM allocation is XLA's job on TPU).
"""

import numpy as np

__all__ = ["Scope", "TpuTensor"]


class TpuTensor:
    """Value holder: numpy array (host) or jax.Array (device), plus LoD
    metadata for API parity with LoDTensor (lod_tensor.h:104)."""

    __slots__ = ("_value", "_lod")

    def __init__(self, value=None):
        self._value = value
        self._lod = []

    def set(self, value, place=None):
        self._value = value

    def get(self):
        return self._value

    def numpy(self):
        if self._value is None:
            raise RuntimeError("tensor is uninitialized")
        return np.asarray(self._value)

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def _is_initialized(self):
        return self._value is not None

    # -- LoD (level-of-detail) metadata for variable-length sequences.
    # On TPU actual ragged execution is replaced by padding+masks; the lod
    # carried here preserves the reference API (set_lod/lod/recursive_sequence_lengths).
    def set_lod(self, lod):
        self._lod = [list(l) for l in lod]

    def lod(self):
        return self._lod

    def set_recursive_sequence_lengths(self, lengths):
        lod = []
        for lens in lengths:
            offsets = [0]
            for l in lens:
                offsets.append(offsets[-1] + l)
            lod.append(offsets)
        self._lod = lod

    def recursive_sequence_lengths(self):
        return [[b - a for a, b in zip(l, l[1:])] for l in self._lod]

    def shape(self):
        return list(np.shape(self._value)) if self._value is not None else []


class _ScopeVar:
    __slots__ = ("name", "tensor")

    def __init__(self, name):
        self.name = name
        self.tensor = TpuTensor()

    def get_tensor(self):
        return self.tensor

    def set(self, value):
        self.tensor.set(value)


class Scope:
    def __init__(self, parent=None):
        self._vars = {}
        self.parent = parent
        self._kids = []
        # executor bookkeeping: per-scope RNG step counter
        self._rng_counter = 0

    def var(self, name):
        """Find or create a variable in THIS scope."""
        v = self._vars.get(name)
        if v is None:
            v = _ScopeVar(name)
            self._vars[name] = v
        return v

    def find_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def erase(self, name):
        self._vars.pop(name, None)

    def new_scope(self):
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids = []

    def local_var_names(self):
        return list(self._vars)
