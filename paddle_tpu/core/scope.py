"""Scope: hierarchical name -> Variable-value map.

TPU-native analog of ``paddle/fluid/framework/scope.h:46``.  Values are
``TpuTensor``s wrapping either a host numpy array or a device ``jax.Array``
(device residency is managed by the executor / PJRT, not by a custom
allocator — HBM allocation is XLA's job on TPU).
"""

import numpy as np

__all__ = ["Scope", "TpuTensor", "SelectedRows", "LoDTensorArray"]


class TpuTensor:
    """Value holder: numpy array (host) or jax.Array (device), plus LoD
    metadata for API parity with LoDTensor (lod_tensor.h:104)."""

    __slots__ = ("_value", "_lod")

    def __init__(self, value=None):
        self._value = value
        self._lod = []

    def set(self, value, place=None):
        self._value = value

    def get(self):
        return self._value

    def numpy(self):
        if self._value is None:
            raise RuntimeError("tensor is uninitialized")
        return np.asarray(self._value)

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def _is_initialized(self):
        return self._value is not None

    # -- LoD (level-of-detail) metadata for variable-length sequences.
    # On TPU actual ragged execution is replaced by padding+masks; the lod
    # carried here preserves the reference API (set_lod/lod/recursive_sequence_lengths).
    def set_lod(self, lod):
        self._lod = [list(l) for l in lod]

    def lod(self):
        return self._lod

    def set_recursive_sequence_lengths(self, lengths):
        lod = []
        for lens in lengths:
            offsets = [0]
            for l in lens:
                offsets.append(offsets[-1] + l)
            lod.append(offsets)
        self._lod = lod

    def recursive_sequence_lengths(self):
        return [[b - a for a, b in zip(l, l[1:])] for l in self._lod]

    def shape(self):
        return list(np.shape(self._value)) if self._value is not None else []


class SelectedRows:
    """Sparse row-set tensor (API parity: framework/selected_rows.h:32).

    On XLA the gradient math is dense (SURVEY §2.1 Tensor-stack note), so
    SelectedRows is a host-side view: `rows` are the touched indices into a
    conceptual [height, ...] tensor whose values live in `get_tensor()`.
    `to_dense()` scatters into the dense shape; `from_dense` compacts the
    nonzero rows (the executor's sparse-grad consumers — sgd/adagrad on
    is_sparse embeddings — accept either form)."""

    def __init__(self, rows=None, height=0):
        self._rows = list(rows or [])
        self._height = int(height)
        self._tensor = TpuTensor()

    def rows(self):
        return list(self._rows)

    def set_rows(self, rows):
        self._rows = [int(r) for r in rows]

    def height(self):
        return self._height

    def set_height(self, h):
        self._height = int(h)

    def get_tensor(self):
        return self._tensor

    def sync_index(self):  # reference API no-op (index is the rows list)
        return None

    def to_dense(self):
        vals = self._tensor.numpy()
        rows = np.asarray(self._rows, np.int64)
        if rows.size and (rows.min() < 0 or rows.max() >= self._height):
            raise ValueError(
                "SelectedRows row ids out of range [0, %d)" % self._height)
        dense = np.zeros((self._height,) + vals.shape[1:], vals.dtype)
        np.add.at(dense, rows, vals)
        return dense

    @staticmethod
    def from_dense(arr):
        arr = np.asarray(arr)
        nz = np.nonzero(np.any(arr.reshape(arr.shape[0], -1) != 0, axis=1))[0]
        sr = SelectedRows(rows=nz.tolist(), height=arr.shape[0])
        sr.get_tensor().set(arr[nz])
        return sr


class _ScopeVar:
    __slots__ = ("name", "tensor", "_selected_rows")

    def __init__(self, name):
        self.name = name
        self.tensor = TpuTensor()
        self._selected_rows = None

    def get_tensor(self):
        return self.tensor

    def get_selected_rows(self):
        if self._selected_rows is None:
            self._selected_rows = SelectedRows()
            self._selected_rows._tensor = self.tensor
        return self._selected_rows

    def set(self, value):
        self.tensor.set(value)


class Scope:
    def __init__(self, parent=None):
        self._vars = {}
        self.parent = parent
        self._kids = []
        # executor bookkeeping: per-scope RNG step counter
        self._rng_counter = 0

    def var(self, name):
        """Find or create a variable in THIS scope."""
        v = self._vars.get(name)
        if v is None:
            v = _ScopeVar(name)
            self._vars[name] = v
        return v

    def find_var(self, name):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s.parent
        return None

    def erase(self, name):
        self._vars.pop(name, None)

    def new_scope(self):
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids = []

    def local_var_names(self):
        return list(self._vars)


class LoDTensorArray(list):
    """A resizable array of LoDTensors (reference core.LoDTensorArray,
    pybind.cc binding over std::vector<LoDTensor>; used by array_write /
    array_read and the dynamic-RNN memory API).  Plain values are wrapped
    into TpuTensor on append for drop-in use with exe.run feeds."""

    def append(self, value):
        if not isinstance(value, TpuTensor):
            t = TpuTensor()
            t.set(value)
            value = t
        super().append(value)
