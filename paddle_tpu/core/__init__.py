from .executor import Executor, global_scope, scope_guard
from .registry import register_op, get_op_def, has_op_def, all_op_types
from .scope import Scope, SelectedRows, TpuTensor, LoDTensorArray

# reference pybind-core aliases (fluid.core.LoDTensor etc.)
LoDTensor = TpuTensor
