"""Two-tier persistent compilation cache (FLAGS_compile_cache_dir).

The reference framework compiles a Program once and reuses the executor
across steps; this port re-pays trace + lower + XLA compile on every
process start and every elastic epoch.  With a cache dir set, that cost is
paid once per (program, flags, world, shapes) key and then amortized across
processes, restarts, and elastic re-quorums:

  tier A  ``<dir>/xla``  JAX's native persistent XLA cache
          (``jax_compilation_cache_dir``): dedupes backend compiles of
          identical HLO, even across different framework-level keys.
  tier B  ``<dir>/aot``  framework-level serialized executables
          (``jax.experimental.serialize_executable``): a hit skips trace +
          lower + compile entirely and hands the executor a ready
          ``Compiled`` it can call.

Tier-B layout: one directory per key, written with the checkpoint
machinery's crash-safe idiom (``LocalFS.atomic_write_dir`` temp-then-rename
plus a ``_SUCCESS`` manifest written last, carrying a per-file crc32):

  <dir>/aot/<sha256 key>/
      executable.bin   serialized XLA executable (PJRT wire format)
      trees.pkl        pickled (in_tree, out_tree) PyTreeDefs
      _SUCCESS         json manifest: format/jax/backend versions, meta,
                       per-file crc32 — absent or mismatched => the entry
                       never loads (a torn write degrades to a recompile)

Keys are CONTENT hashes — ``Program.to_dict()`` (so a re-built or
re-transpiled program with identical IR hits, regardless of ``_uid``), the
trace-affecting flag fingerprint, the ``_collective_meta`` world, feed
shapes/dtypes, fetch names, mesh axes, and the jax version + backend
platform (an upgraded jaxlib must never deserialize a stale executable).

Invalidation is by construction: anything that changes the executable
changes the key; anything that changes the serialization contract fails
the manifest check.  Eviction is size-capped LRU over entry mtimes
(``FLAGS_compile_cache_max_bytes``; a load touches its entry).
"""

import hashlib
import json
import logging
import os
import pickle
import shutil
import time
import zlib

import numpy as np

from .. import flags as _flags
from . import telemetry as _tm

__all__ = [
    "enabled", "cache_dir", "aot_dir", "xla_dir", "enable_xla_cache",
    "program_fingerprint", "artifact_key", "raw_artifact_key", "load",
    "store", "invalidate",
    "entries", "stats", "clear", "evict_to_cap",
]

FORMAT = 1
_SUCCESS = "_SUCCESS"
_FILES = ("executable.bin", "trees.pkl")


def cache_dir():
    return _flags.flag("compile_cache_dir") or ""


def enabled():
    return bool(cache_dir())


def aot_dir():
    return os.path.join(cache_dir(), "aot")


def xla_dir():
    return os.path.join(cache_dir(), "xla")


# -- tier A: JAX's native persistent XLA cache -------------------------------

_xla_wired = [None]


def enable_xla_cache():
    """Point jax_compilation_cache_dir at <dir>/xla (idempotent; re-wires
    if the flag changes).  Called from the executor's compile-miss path so
    a flag set after Executor construction still takes effect."""
    d = cache_dir()
    if not d or _xla_wired[0] == d:
        return bool(d)
    import jax

    try:
        os.makedirs(xla_dir(), exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", xla_dir())
        # cache everything: the defaults skip sub-second compiles, which is
        # exactly the CPU-tier test population
        for knob, val in (("jax_persistent_cache_min_entry_size_bytes", -1),
                          ("jax_persistent_cache_min_compile_time_secs", 0)):
            try:
                jax.config.update(knob, val)
            except Exception:
                pass  # knob not present in this jax
        _xla_wired[0] = d
        return True
    except Exception as e:
        logging.warning("compile_cache: could not enable XLA cache: %s", e)
        return False


# -- keys --------------------------------------------------------------------

def _json_default(o):
    if isinstance(o, np.ndarray):
        # hash large embedded constants exactly — str() would elide
        return ["__nd__", o.dtype.str, list(o.shape),
                hashlib.sha256(np.ascontiguousarray(o).tobytes()).hexdigest()]
    if isinstance(o, (np.integer, np.floating, np.bool_)):
        return o.item()
    if isinstance(o, bytes):
        return o.hex()
    return str(o)


_fp_memo = {}


def program_fingerprint(program):
    """sha256 of the program's canonical to_dict() json — stable across
    processes (unlike ``_uid``), memoized per (uid, version)."""
    k = (program._uid, program.version)
    hit = _fp_memo.get(k)
    if hit is not None:
        return hit
    blob = json.dumps(program.to_dict(), sort_keys=True,
                      separators=(",", ":"), default=_json_default)
    h = hashlib.sha256(blob.encode()).hexdigest()
    if len(_fp_memo) > 1024:
        _fp_memo.clear()
    _fp_memo[k] = h
    return h


def artifact_key(program, feed_sig, fetch_names, trace_flags, mesh_sig=None,
                 extra=None):
    """Content key for one executable.  ``feed_sig`` is the sorted
    (name, shape, dtype-str) tuple the executor already builds; ``mesh_sig``
    must describe axis names/sizes only (never device ids — an executable
    serialized in one world must be loadable by the re-initialized backend
    of the next, where ids are reassigned)."""
    import jax

    cmeta = getattr(program, "_collective_meta", None)
    world = None
    if cmeta:
        world = {k: cmeta.get(k)
                 for k in ("nranks", "mode", "allreduce_dtype", "nrings")}
    payload = {
        "format": FORMAT,
        "program": program_fingerprint(program),
        "feeds": [list(map(str, (n, tuple(s), d))) for n, s, d in feed_sig],
        "fetch": [str(f) for f in fetch_names],
        "flags": [list(map(str, kv)) for kv in trace_flags],
        "mesh": mesh_sig,
        "world": world,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "extra": extra,
    }
    blob = json.dumps(payload, sort_keys=True, default=_json_default)
    return hashlib.sha256(blob.encode()).hexdigest()


def raw_artifact_key(kind, payload):
    """Content key for a non-Program executable (the decode-serving
    CarriedStepFn path): ``payload`` is any JSON-able description of
    everything that affects the compiled artifact — model weight
    fingerprint, cache geometry, argument signature, trace flags.  The
    jax version + backend are folded in for the same reason as
    ``artifact_key``."""
    import jax

    blob = json.dumps({"format": FORMAT, "kind": str(kind),
                       "payload": payload, "jax": jax.__version__,
                       "backend": jax.default_backend()},
                      sort_keys=True, default=_json_default)
    return hashlib.sha256(blob.encode()).hexdigest()


# -- tier B store/load -------------------------------------------------------

def _crc(data):
    return zlib.crc32(data) & 0xFFFFFFFF


def _entry_names(root):
    if not os.path.isdir(root):
        return []
    return sorted(n for n in os.listdir(root)
                  if "._tmp." not in n and
                  os.path.isdir(os.path.join(root, n)))


def _entry_bytes(path):
    total = 0
    try:
        for n in os.listdir(path):
            try:
                total += os.path.getsize(os.path.join(path, n))
            except OSError:
                pass
    except OSError:
        pass
    return total


def _read_manifest(path):
    try:
        with open(os.path.join(path, _SUCCESS)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def store(key, payload, in_tree, out_tree, meta=None):
    """Write one serialized executable under its key (atomic, manifest
    last), then evict down to FLAGS_compile_cache_max_bytes.  Returns True
    when the entry is on disk (pre-existing counts); never raises."""
    if not enabled():
        return False
    import jax

    from ..utils.fs import LocalFS

    path = os.path.join(aot_dir(), key)
    if os.path.exists(os.path.join(path, _SUCCESS)):
        return True
    try:
        os.makedirs(aot_dir(), exist_ok=True)
        trees = pickle.dumps((in_tree, out_tree),
                             protocol=pickle.HIGHEST_PROTOCOL)
        blobs = {"executable.bin": bytes(payload), "trees.pkl": trees}
        with LocalFS().atomic_write_dir(path) as tmp:
            for name, data in blobs.items():
                with open(os.path.join(tmp, name), "wb") as f:
                    f.write(data)
            manifest = {
                "format": FORMAT,
                "key": key,
                "jax": jax.__version__,
                "backend": jax.default_backend(),
                "created": time.time(),
                "meta": meta or {},
                "files": {n: _crc(d) for n, d in blobs.items()},
            }
            with open(os.path.join(tmp, _SUCCESS), "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
        nbytes = sum(len(d) for d in blobs.values())
        _tm.inc("compile_cache_store_total")
        _tm.inc("compile_cache_bytes_written_total", nbytes)
        evict_to_cap()
        return True
    except Exception as e:
        logging.warning("compile_cache: store %s failed: %s", key[:12], e)
        _tm.inc("compile_cache_errors_total", kind="store")
        return False


def invalidate(key):
    """Drop one tier-B entry (defective or superseded) so the next store
    rewrites it instead of skipping on the surviving _SUCCESS marker."""
    try:
        shutil.rmtree(os.path.join(aot_dir(), key))
        return True
    except OSError:
        return False


def _defect(key, kind):
    _tm.inc("compile_cache_disk_miss_total")
    _tm.inc("compile_cache_errors_total", kind=kind)
    # delete the bad entry NOW: store() skips keys whose _SUCCESS exists
    # (concurrent-writer dedup), so a corrupt-but-manifested entry would
    # otherwise force a recompile in every future process
    invalidate(key)
    return None


def load(key):
    """-> {"payload", "in_tree", "out_tree", "manifest"} or None.  Any
    defect — missing/torn manifest, format or jax/backend version mismatch,
    crc mismatch, unpicklable trees — counts an error by kind, deletes the
    entry, and returns None (the caller recompiles and re-stores)."""
    if not enabled():
        return None
    import jax

    path = os.path.join(aot_dir(), key)
    if not os.path.isdir(path):
        _tm.inc("compile_cache_disk_miss_total")
        return None
    man = _read_manifest(path)
    if man is None:
        return _defect(key, "manifest")
    if (man.get("format") != FORMAT or man.get("jax") != jax.__version__
            or man.get("backend") != jax.default_backend()):
        return _defect(key, "version")
    blobs = {}
    for name in _FILES:
        try:
            with open(os.path.join(path, name), "rb") as f:
                blobs[name] = f.read()
        except OSError:
            return _defect(key, "missing")
        if _crc(blobs[name]) != man.get("files", {}).get(name):
            return _defect(key, "crc")
    try:
        in_tree, out_tree = pickle.loads(blobs["trees.pkl"])
    except Exception:
        return _defect(key, "trees")
    try:
        os.utime(path)  # LRU touch
    except OSError:
        pass
    _tm.inc("compile_cache_disk_hit_total")
    _tm.inc("compile_cache_bytes_read_total",
            sum(len(b) for b in blobs.values()))
    return {"payload": blobs["executable.bin"], "in_tree": in_tree,
            "out_tree": out_tree, "manifest": man}


# -- maintenance / CLI surface ----------------------------------------------

def entries():
    """One record per tier-B entry: key, bytes, validity, created/last_used
    timestamps, stored meta.  Sorted least-recently-used first."""
    root = aot_dir()
    out = []
    for name in _entry_names(root):
        path = os.path.join(root, name)
        man = _read_manifest(path)
        try:
            last_used = os.stat(path).st_mtime
        except OSError:
            last_used = 0.0
        out.append({
            "key": name,
            "bytes": _entry_bytes(path),
            "valid": man is not None,
            "created": (man or {}).get("created"),
            "last_used": last_used,
            "jax": (man or {}).get("jax"),
            "meta": (man or {}).get("meta") or {},
        })
    out.sort(key=lambda r: r["last_used"])
    return out


def stats():
    ents = entries()
    total = sum(r["bytes"] for r in ents)
    xla_files = xla_bytes = 0
    if os.path.isdir(xla_dir()):
        for dirpath, _dirs, files in os.walk(xla_dir()):
            for f in files:
                xla_files += 1
                try:
                    xla_bytes += os.path.getsize(os.path.join(dirpath, f))
                except OSError:
                    pass
    return {
        "dir": cache_dir(),
        "enabled": enabled(),
        "aot_entries": len(ents),
        "aot_valid": sum(1 for r in ents if r["valid"]),
        "aot_bytes": total,
        "max_bytes": int(_flags.flag("compile_cache_max_bytes") or 0),
        "xla_files": xla_files,
        "xla_bytes": xla_bytes,
    }


def evict_to_cap():
    """LRU-evict tier-B entries until the total fits
    FLAGS_compile_cache_max_bytes (<=0 disables).  Invalid entries go
    first regardless of age."""
    cap = int(_flags.flag("compile_cache_max_bytes") or 0)
    if cap <= 0 or not enabled():
        return 0
    ents = entries()
    total = sum(r["bytes"] for r in ents)
    if total <= cap:
        return 0
    # invalid first, then least-recently-used
    ents.sort(key=lambda r: (r["valid"], r["last_used"]))
    evicted = 0
    for r in ents:
        if total <= cap:
            break
        path = os.path.join(aot_dir(), r["key"])
        try:
            shutil.rmtree(path)
            total -= r["bytes"]
            evicted += 1
        except OSError:
            pass
    if evicted:
        _tm.inc("compile_cache_evictions_total", evicted)
        _tm.set_gauge("compile_cache_size_bytes", total)
    return evicted


def clear():
    """Wipe both tiers (the cache dir itself survives).  -> entries
    removed."""
    n = 0
    root = aot_dir()
    for name in _entry_names(root):
        try:
            shutil.rmtree(os.path.join(root, name))
            n += 1
        except OSError:
            pass
    if os.path.isdir(xla_dir()):
        try:
            shutil.rmtree(xla_dir())
            n += 1
        except OSError:
            pass
    # a cleared dir must re-wire tier A on next use (the dir was deleted)
    _xla_wired[0] = None
    return n
