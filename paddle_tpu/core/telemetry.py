"""Unified runtime telemetry: metrics registry + structured step-event log.

The reference framework answers "why was step N slow" with the profiler's
RecordEvent tables (platform/profiler.h) and ad-hoc VLOG counters scattered
through the distributed runtime.  Here the runtime keeps ONE process-wide
registry of counters, gauges, and histograms (with labels), plus a JSONL
step-event log, so step/compile/retry/eviction history is attributable
after the fact:

- gating: ``FLAGS_telemetry`` (off by default) with the same guard pattern
  as ``profiler.is_profiler_enabled`` — every public mutator early-returns
  when the flag is off, so instrumented call sites cost one dict lookup in
  production.  ``FLAGS_telemetry_dir`` selects where the JSONL stream and
  ``dump()`` snapshots land; with no dir, events stay in a bounded
  in-memory ring.
- export: ``dump()`` writes a Prometheus-style text file (metrics.prom)
  and a JSON snapshot (metrics.json); pservers publish the snapshot under
  the ``__metrics__`` RPC key (``publish_rpc``) so trainers and
  tools/metrics_dump.py can scrape a live server.
- instrumented layers: core/executor.py (step wall time, compile time,
  cache hit/miss, donation, feed/fetch bytes, bf16 carry hits, hbm-audit
  fold), distributed/ps.py + native/rpc.py (send/retry/dedupe-drop,
  heartbeat misses, evictions), utils/fault_injection.py (fired faults),
  io.py CheckpointManager (save/restore durations).
- fleet merge: every histogram also counts into fixed log-spaced bucket
  bounds (``HIST_BUCKET_BOUNDS``, shared across processes), exported as
  cumulative ``buckets`` vectors in every snapshot — replicas merge by
  elementwise sum (``merge_hist_snapshots``) and fleet-exact percentiles
  come from ``bucket_percentile``.  A bounded time-series ring
  (``series_record``/``series``/``series_rate``, fed by the 1s
  publisher) makes windowed rates counter deltas instead of lifetime
  averages; serving/fleetmon.py builds the fleet aggregation + SLO
  burn-rate plane on both.
"""

import atexit
import bisect
import json
import math
import os
import threading
import time

__all__ = [
    "enabled", "inc", "set_gauge", "observe", "event", "set_info",
    "record_step", "snapshot", "counter_total", "label_sets",
    "prometheus_text", "dump", "maybe_dump", "reset", "publish_rpc",
    "start_publisher", "decode_snapshot", "scrape", "METRICS_RPC_KEY",
    "HIST_BUCKET_BOUNDS", "bucket_percentile", "merge_hist_snapshots",
    "cumulative_to_deltas", "series", "series_record", "series_rate",
    "rate_from_samples",
]

METRICS_RPC_KEY = "__metrics__"

# histogram observations kept for percentile estimation; beyond the cap the
# sample set is decimated (every other kept) so long runs stay bounded
_HIST_SAMPLE_CAP = 8192
_EVENT_RING_CAP = 4096


def _log_bounds(lo, hi, growth):
    out, v = [], float(lo)
    while v < hi:
        out.append(round(v, 4))
        v *= growth
    out.append(float(hi))
    return tuple(out)


# Fixed log-spaced bucket upper bounds (ms), shared by EVERY histogram in
# every process: 0.05 ms .. 2 min at 1.25x growth (~67 buckets + overflow).
# Because the bounds are process-independent constants, bucket count
# vectors from different replicas merge by elementwise sum, and any
# consumer can recover a fleet-exact percentile to within one bucket
# width (<= 25% relative) from the merged cumulative counts — unlike the
# decimated sample lists, which cannot be merged.
HIST_BUCKET_BOUNDS = _log_bounds(0.05, 120000.0, 1.25)

_lock = threading.RLock()
_counters = {}     # (name, labels) -> float
_gauges = {}       # (name, labels) -> float
_hists = {}        # (name, labels) -> _Hist
_info = {}         # one-off structured payloads (e.g. memory_audit report)
_events = []       # bounded in-memory ring of event dicts
_event_seq = {}    # kind -> next sequence number
_event_sink = [None, None]  # (path, open file handle) for the JSONL stream
_series = []       # bounded ring of timestamped counter/gauge samples


def _flags():
    from .. import flags

    return flags


def enabled():
    """One flag read — the profiler.is_profiler_enabled guard pattern."""
    return bool(_flags().flag("telemetry"))


def telemetry_dir():
    return _flags().flag("telemetry_dir") or ""


class _Hist:
    __slots__ = ("count", "sum", "min", "max", "samples", "buckets",
                 "_sorted")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.samples = []
        # per-bucket (non-cumulative) observation counts over the fixed
        # HIST_BUCKET_BOUNDS; last slot is the +Inf overflow bucket.
        # Never decimated — merges across replicas stay exact.
        self.buckets = [0] * (len(HIST_BUCKET_BOUNDS) + 1)
        self._sorted = None       # cached sorted view, invalidated on add

    def add(self, v):
        v = float(v)
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        self.buckets[bisect.bisect_left(HIST_BUCKET_BOUNDS, v)] += 1
        self.samples.append(v)
        if len(self.samples) > _HIST_SAMPLE_CAP:
            del self.samples[::2]
        self._sorted = None

    def percentile(self, q):
        if not self.samples:
            return 0.0
        if self._sorted is None:
            self._sorted = sorted(self.samples)
        s = self._sorted
        i = min(int(q * len(s)), len(s) - 1)
        return s[i]

    def cumulative(self):
        """Prometheus-style cumulative bucket counts (last == count)."""
        out, run = [], 0
        for c in self.buckets:
            run += c
            out.append(run)
        return out

    def merge(self, other):
        """Fold another histogram in EXACTLY: counts, sums, and bucket
        vectors add; min/max fold.  Samples are appended (then decimated
        to the cap) so the local percentile estimate stays usable, but
        the bucket vector — the mergeable truth — is never decimated."""
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        for i, c in enumerate(other.buckets):
            self.buckets[i] += c
        self.samples.extend(other.samples)
        while len(self.samples) > _HIST_SAMPLE_CAP:
            del self.samples[::2]
        self._sorted = None
        return self


def bucket_percentile(cum_buckets, q, bounds=None):
    """Percentile from cumulative bucket counts: the upper bound of the
    bucket holding the rank-``q`` observation — within one bucket width
    of the true sample percentile, and exact across merges (bucket
    vectors sum where sample lists cannot)."""
    bounds = bounds or HIST_BUCKET_BOUNDS
    total = int(cum_buckets[-1]) if cum_buckets else 0
    if total <= 0:
        return 0.0
    # same rank convention as _Hist.percentile: s[min(int(q*n), n-1)]
    rank = min(int(q * total), total - 1) + 1
    for i, c in enumerate(cum_buckets):
        if c >= rank:
            return bounds[min(i, len(bounds) - 1)]
    return bounds[-1]


def cumulative_to_deltas(cum_buckets):
    """Cumulative bucket vector -> per-bucket counts (inverse of
    ``_Hist.cumulative``); deltas from different replicas sum directly."""
    out, prev = [], 0
    for c in cum_buckets:
        c = int(c)
        out.append(c - prev)
        prev = c
    return out


def merge_hist_snapshots(hists, bounds=None):
    """Merge per-replica histogram dump dicts (the ``snapshot()`` /
    ``scrape()`` shape) into one fleet-exact dict: count/sum/buckets
    sum, min/max fold, percentiles recomputed from the merged cumulative
    buckets.  Entries without bucket vectors (pre-merge snapshots)
    degrade to the conservative worst-replica percentile."""
    bounds = bounds or HIST_BUCKET_BOUNDS
    out = {"count": 0, "sum": 0.0, "min": float("inf"),
           "max": float("-inf")}
    merged = [0] * (len(bounds) + 1)
    have_buckets = True
    worst = {"p50": 0.0, "p90": 0.0, "p99": 0.0}
    for h in hists:
        if not h:
            continue
        out["count"] += int(h.get("count", 0))
        out["sum"] += float(h.get("sum", 0.0))
        if h.get("count"):
            out["min"] = min(out["min"], float(h.get("min", 0.0)))
            out["max"] = max(out["max"], float(h.get("max", 0.0)))
        for p in worst:
            worst[p] = max(worst[p], float(h.get(p, 0.0)))
        cum = h.get("buckets")
        if cum is None:
            have_buckets = False
        else:
            prev = 0
            for i, c in enumerate(cum[:len(merged)]):
                merged[i] += int(c) - prev
                prev = int(c)
    if out["count"] <= 0:
        out["min"] = out["max"] = 0.0
    if have_buckets:
        cum, run = [], 0
        for c in merged:
            run += c
            cum.append(run)
        out["buckets"] = cum
        for p, q in (("p50", 0.50), ("p90", 0.90), ("p99", 0.99)):
            out[p] = bucket_percentile(cum, q, bounds)
    else:
        out.update(worst)
    return out


def _key(name, labels):
    return (name, tuple(sorted(labels.items())) if labels else ())


def _flat(name, labels):
    if not labels:
        return name
    return "%s{%s}" % (name, ",".join("%s=%s" % kv for kv in labels))


# -- mutators (no-ops when FLAGS_telemetry is off) ---------------------------

def inc(name, value=1, **labels):
    if not enabled():
        return
    k = _key(name, labels)
    with _lock:
        _counters[k] = _counters.get(k, 0) + value


def set_gauge(name, value, **labels):
    if not enabled():
        return
    with _lock:
        _gauges[_key(name, labels)] = float(value)


def observe(name, value, **labels):
    if not enabled():
        return
    k = _key(name, labels)
    with _lock:
        h = _hists.get(k)
        if h is None:
            h = _hists[k] = _Hist()
        h.add(value)


def set_info(key, value):
    """Attach a one-off structured payload (folded into the JSON dump) —
    e.g. the FLAGS_hbm_audit memory report."""
    if not enabled():
        return
    with _lock:
        _info[key] = value


def event(kind, **fields):
    """Append one structured event to the JSONL step log.  Events stream to
    ``<FLAGS_telemetry_dir>/steps.jsonl`` when a dir is set; a bounded
    in-memory ring keeps the tail either way."""
    if not enabled():
        return
    with _lock:
        seq = _event_seq.get(kind, 0)
        _event_seq[kind] = seq + 1
        rec = {"ev": kind, "seq": seq, "t": round(time.time(), 6)}
        rec.update(fields)
        _events.append(rec)
        if len(_events) > _EVENT_RING_CAP:
            del _events[: len(_events) - _EVENT_RING_CAP]
        d = telemetry_dir()
        if d:
            fh = _event_fh(d)
            if fh is not None:
                fh.write(json.dumps(rec) + "\n")
                fh.flush()


class _RotatingFile:
    """Append-only JSONL stream with size-bounded rotate-and-keep-one:
    when the file would exceed ``FLAGS_telemetry_max_bytes`` (or the
    explicit ``max_bytes``), it is renamed to ``<path>.1`` (replacing any
    previous generation) and writing restarts on a fresh file — long
    fleet soaks stay disk-bounded at ~2x the cap.  Shared by the
    steps.jsonl event stream and the tracing trace-<pid>.jsonl sink."""

    __slots__ = ("path", "_fh", "_size", "_max")

    def __init__(self, path, max_bytes=None):
        self.path = path
        self._max = max_bytes
        self._fh = open(path, "a")
        self._size = self._fh.tell()

    def _limit(self):
        if self._max is not None:
            return int(self._max)
        v = _flags().flag("telemetry_max_bytes")
        return int(v) if v else 0

    def write(self, s):
        if self._fh is None:
            return
        limit = self._limit()
        if limit > 0 and self._size > 0 and self._size + len(s) > limit:
            try:
                self._fh.close()
                os.replace(self.path, self.path + ".1")
                self._fh = open(self.path, "a")
                self._size = 0
            except OSError:
                pass
        try:
            self._fh.write(s)
            self._size += len(s)
        except (OSError, ValueError):
            pass

    def flush(self):
        try:
            if self._fh is not None:
                self._fh.flush()
        except (OSError, ValueError):
            pass

    def close(self):
        try:
            if self._fh is not None:
                self._fh.close()
        except (OSError, ValueError):
            pass
        self._fh = None


def _event_fh(d):
    path = os.path.join(d, "steps.jsonl")
    if _event_sink[0] != path:
        if _event_sink[1] is not None:
            _event_sink[1].close()
        try:
            os.makedirs(d, exist_ok=True)
            _event_sink[0] = path
            _event_sink[1] = _RotatingFile(path)
        except OSError:
            _event_sink[0] = _event_sink[1] = None
    return _event_sink[1]


def record_step(wall_ms, cache_hit, compile_ms=None, donated=0,
                feed_bytes=0, fetch_bytes=0, carry_hits=0, carry_converts=0):
    """One executor step: bundle the counter/histogram updates plus the
    step event so the hot path pays a single enabled() check."""
    if not enabled():
        return
    inc("executor_steps_total")
    inc("executor_cache_hit_total" if cache_hit
        else "executor_cache_miss_total")
    observe("executor_step_ms", wall_ms)
    fields = {"wall_ms": round(wall_ms, 3), "cache_hit": bool(cache_hit)}
    if compile_ms is not None:
        observe("executor_compile_ms", compile_ms)
        fields["compile_ms"] = round(compile_ms, 3)
    if donated:
        inc("executor_donated_buffers_total", donated)
        fields["donated"] = donated
    if feed_bytes:
        inc("executor_feed_bytes_total", feed_bytes)
        fields["feed_bytes"] = feed_bytes
    if fetch_bytes:
        inc("executor_fetch_bytes_total", fetch_bytes)
        fields["fetch_bytes"] = fetch_bytes
    if carry_hits:
        inc("executor_carry_hit_total", carry_hits)
        fields["carry_hits"] = carry_hits
    if carry_converts:
        inc("executor_carry_convert_total", carry_converts)
        fields["carry_converts"] = carry_converts
    event("step", **fields)


# -- read side ---------------------------------------------------------------

def _finite(v):
    """inf/-inf/nan would emit non-standard JSON from dump() — clamp to
    0.0 (empty histograms carry +/-inf min/max sentinels)."""
    v = float(v)
    return round(v, 3) if math.isfinite(v) else 0.0


def snapshot():
    """Flat JSON-ready view: counters/gauges keyed ``name`` or
    ``name{k=v,...}``; histograms as count/sum/min/max/p50/p90/p99 plus
    the cumulative ``buckets`` vector over the shared
    ``bucket_bounds`` (top-level, emitted once) so any consumer can
    merge replicas exactly and recompute fleet percentiles."""
    with _lock:
        out = {
            "counters": {_flat(n, l): v for (n, l), v in _counters.items()},
            "gauges": {_flat(n, l): v for (n, l), v in _gauges.items()},
            "histograms": {
                _flat(n, l): {
                    "count": h.count,
                    "sum": _finite(h.sum),
                    "min": _finite(h.min) if h.count else 0.0,
                    "max": _finite(h.max) if h.count else 0.0,
                    "p50": _finite(h.percentile(0.50)),
                    "p90": _finite(h.percentile(0.90)),
                    "p99": _finite(h.percentile(0.99)),
                    "buckets": h.cumulative(),
                }
                for (n, l), h in _hists.items()
            },
            "events_logged": dict(_event_seq),
            "bucket_bounds": list(HIST_BUCKET_BOUNDS),
        }
        if _info:
            out["info"] = dict(_info)
        return out


def counter_total(name):
    """Sum of a counter across all label sets (0.0 when never touched)."""
    with _lock:
        return float(sum(v for (n, _), v in _counters.items() if n == name))


def label_sets(name, kind="counter"):
    """Every live label set of a counter/gauge family, as
    ``[(flat_key, {label: value}), ...]`` — consumers that window rates
    per label (per-tier shed/s, per-namespace hit/s) enumerate through
    this instead of re-parsing flat keys."""
    src = _counters if kind == "counter" else _gauges
    with _lock:
        return [(_flat(n, l), dict(l)) for (n, l) in src if n == name]


# -- time-series ring --------------------------------------------------------

def _series_cap():
    v = _flags().flag("telemetry_series_cap")
    return int(v) if v else 1024


def series_record(now=None):
    """Append one timestamped counter/gauge sample to the bounded
    in-process ring (the 1s publisher calls this every tick).  Windowed
    RATES — shed/s, tokens/s, cache-miss/s — fall out as counter deltas
    between ring samples instead of lifetime averages."""
    if not enabled():
        return None
    with _lock:
        rec = {"t": float(now if now is not None else time.time()),
               "counters": {_flat(n, l): float(v)
                            for (n, l), v in _counters.items()},
               "gauges": {_flat(n, l): v for (n, l), v in _gauges.items()}}
        _series.append(rec)
        cap = _series_cap()
        if len(_series) > cap:
            del _series[: len(_series) - cap]
        return rec


def series(window_s=None, now=None):
    """The ring's samples (oldest first), optionally only those within
    the trailing ``window_s`` seconds."""
    with _lock:
        if window_s is None:
            return list(_series)
        cut = float(now if now is not None else time.time()) - \
            float(window_s)
        return [s for s in _series if s["t"] >= cut]


def rate_from_samples(samples, window_s=None, now=None):
    """Reset-safe per-second rate from ``[(t, value), ...]`` counter
    samples: positive deltas between consecutive samples sum; a value
    DROP (replica restart zeroed the counter) contributes the post-reset
    value instead of a negative delta — the Prometheus ``rate()``
    counter-reset rule."""
    pts = [(float(t), float(v)) for t, v in samples]
    if window_s is not None:
        cut = float(now if now is not None else time.time()) - \
            float(window_s)
        inside = [i for i, (t, _) in enumerate(pts) if t >= cut]
        if len(inside) >= 2:
            pts = pts[inside[0]:]
        elif inside:
            # a single in-window sample has no delta — reach back to
            # one pre-cut sample as the baseline
            pts = pts[max(inside[0] - 1, 0):]
        else:
            pts = pts[-1:]
    if len(pts) < 2:
        return 0.0
    total = 0.0
    for (_, prev), (_, cur) in zip(pts, pts[1:]):
        d = cur - prev
        total += cur if d < 0 else d
    span = pts[-1][0] - pts[0][0]
    return total / span if span > 0 else 0.0


def series_rate(flat_name, window_s, now=None):
    """Windowed per-second rate of one flat counter key from the ring."""
    with _lock:
        pts = [(s["t"], s["counters"].get(flat_name, 0.0))
               for s in _series]
    return rate_from_samples(pts, window_s, now=now)


def prometheus_text(snap=None):
    """Prometheus exposition format: counters/gauges verbatim, histograms
    as summaries (quantile labels + _sum/_count)."""
    snap = snap if snap is not None else snapshot()

    def split(flat):
        if "{" in flat:
            name, rest = flat.split("{", 1)
            return name, rest.rstrip("}")
        return flat, ""

    def fmt(name, extra_labels, value):
        lbl = ",".join(x for x in extra_labels if x)
        return "%s%s %s" % (name, "{%s}" % lbl if lbl else "", value)

    lines = []
    for kind, d in (("counter", snap.get("counters", {})),
                    ("gauge", snap.get("gauges", {}))):
        seen = set()
        for flat in sorted(d):
            name, lbls = split(flat)
            if name not in seen:
                seen.add(name)
                lines.append("# TYPE %s %s" % (name, kind))
            labeled = ",".join('%s="%s"' % tuple(kv.split("=", 1))
                               for kv in lbls.split(",") if kv)
            lines.append(fmt(name, [labeled], d[flat]))
    seen = set()
    for flat in sorted(snap.get("histograms", {})):
        name, lbls = split(flat)
        h = snap["histograms"][flat]
        labeled = ",".join('%s="%s"' % tuple(kv.split("=", 1))
                           for kv in lbls.split(",") if kv)
        if name not in seen:
            seen.add(name)
            lines.append("# TYPE %s summary" % name)
        for q in ("0.5", "0.9", "0.99"):
            lines.append(fmt(name, [labeled, 'quantile="%s"' % q],
                             h["p" + q.replace("0.", "").ljust(2, "0")]))
        lines.append(fmt(name + "_sum", [labeled], h["sum"]))
        lines.append(fmt(name + "_count", [labeled], h["count"]))
    return "\n".join(lines) + "\n"


def dump(dirname=None):
    """Write metrics.json + metrics.prom under `dirname` (default:
    FLAGS_telemetry_dir).  Returns (json_path, prom_path)."""
    d = dirname or telemetry_dir()
    if not d:
        raise ValueError(
            "telemetry.dump() needs a directory (argument or "
            "FLAGS_telemetry_dir)")
    os.makedirs(d, exist_ok=True)
    snap = snapshot()
    jpath = os.path.join(d, "metrics.json")
    ppath = os.path.join(d, "metrics.prom")
    with open(jpath, "w") as f:
        json.dump(snap, f, indent=1, default=str)
    with open(ppath, "w") as f:
        f.write(prometheus_text(snap))
    return jpath, ppath


def maybe_dump():
    """dump() iff telemetry is on and a dir is configured — the end-of-run
    hook (Executor.close + atexit)."""
    if enabled() and telemetry_dir():
        try:
            dump()
        except OSError:
            pass


def reset():
    """Clear the registry and the event stream (tests)."""
    with _lock:
        _counters.clear()
        _gauges.clear()
        _hists.clear()
        _info.clear()
        _events.clear()
        _event_seq.clear()
        _series.clear()
        if _event_sink[1] is not None:
            _event_sink[1].close()
        _event_sink[0] = _event_sink[1] = None


# -- distributed scrape ------------------------------------------------------

def publish_rpc(server, key=METRICS_RPC_KEY):
    """Publish the current snapshot on a pserver's variable store so any
    RpcClient can GET it (the pserver __metrics__ RPC)."""
    if not enabled():
        return
    import numpy as np

    buf = json.dumps(snapshot(), default=str).encode("utf-8")
    server.set_var(key, np.frombuffer(buf, dtype=np.uint8).copy())


class PublisherHandle(threading.Event):
    """Stop handle for the publisher daemon: an Event (``set()`` alone
    keeps the legacy contract working) that also knows its thread, so
    shutdown can ``stop()`` — set AND join — instead of leaking the
    thread into the next test.  Idempotent: double-stop is a no-op."""

    def __init__(self):
        super(PublisherHandle, self).__init__()
        self.thread = None

    def stop(self, timeout=5.0):
        self.set()
        t = self.thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout)
        self.thread = None


def start_publisher(server, interval_s=1.0, key=METRICS_RPC_KEY,
                    stop_event=None, on_publish=None):
    """Republish the snapshot on `server` every `interval_s` so scrapes
    always read a fresh view (publish_rpc is one-shot).  Returns a
    PublisherHandle — call ``.stop()`` to end AND join the daemon thread
    (``.set()`` alone still ends it, legacy contract).  The serving
    frontend uses this for its __metrics__ endpoint.

    Every tick also appends a sample to the time-series ring
    (``series_record``) BEFORE publishing, so windowed rates are
    derivable on every replica for free; ``on_publish`` (optional) runs
    between the two — derived per-window gauges set there (per-tier
    shed/s, per-namespace hit rate) ride the same republish."""
    stop = PublisherHandle()

    def tick():
        series_record()
        if on_publish is not None:
            try:
                on_publish()
            except Exception:
                pass               # a derived gauge must never kill the
                                   # publisher
        publish_rpc(server, key=key)

    def loop():
        while not stop.wait(interval_s):
            if stop_event is not None and stop_event.is_set():
                return
            try:
                tick()
            except Exception:
                return  # server shut down under us

    tick()
    t = threading.Thread(target=loop, name="telemetry-publisher",
                         daemon=True)
    stop.thread = t
    t.start()
    return stop


def decode_snapshot(arr):
    """Inverse of publish_rpc's encoding (uint8 JSON bytes -> dict)."""
    import numpy as np

    return json.loads(np.asarray(arr, dtype=np.uint8).tobytes().decode(
        "utf-8"))


def scrape(endpoint, timeout=10.0, key=METRICS_RPC_KEY):
    """GET a live pserver's metrics snapshot (tools/metrics_dump.py
    --scrape).  Fails fast when the server runs with telemetry off (the
    key is never published, so the bounded-deadline GET errors)."""
    from ..native.rpc import RpcClient

    client = RpcClient(endpoint, connect_timeout=timeout,
                       rpc_deadline=timeout, retry_times=0)
    try:
        return decode_snapshot(client.get_var(key))
    finally:
        client.close()


atexit.register(maybe_dump)
