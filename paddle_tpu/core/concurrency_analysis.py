"""Static concurrency analyzer for the Python runtime (CC1xx rules).

The program verifiers (`core/analysis.py`, `core/world_analysis.py`) prove
graph invariants before anything runs; this module gives the thread-heavy
Python runtime that grew around them (serving engine, kvxfer sender,
janitors, autoscaler, fleetmon, checkpoint writer, elastic heartbeats)
the same treatment.  Pure AST analysis — nothing is imported or executed.

Rules (see ``CC_RULES``):

  CC101  lock-order inversion: the per-class lock inventory plus the
         acquisition graph (nested ``with``/``acquire`` sites, propagated
         through resolvable calls) must be acyclic AND consistent with
         every declared ``LOCK_ORDER`` table.
  CC102  blocking call while holding a lock (RPC send/recv/probe,
         ``time.sleep``, ``subprocess``, file I/O, ``Thread.join``,
         executor compile/step) — waivable inline.
  CC103  guarded-attribute escape: attribute written under a class's own
         lock in some methods but read/written lock-free in code
         reachable from a ``Thread(target=...)`` entry point.
  CC104  ``Condition.wait`` without an enclosing ``while`` predicate-
         recheck loop.
  CC105  callback invoked under a lock that its registration site
         declares fired-unlocked (``UNLOCKED_CALLBACKS`` registries —
         the ``on_evict`` "AFTER lock release" contract).
  CC106  ``Thread(...)`` started without ``daemon=True`` or a tracked
         ``join()`` path.

Machine-readable registries (module-level literals, merged package-wide):

  LOCK_ORDER = (("PrefixCache._lock", "BlockAllocator._lock"),)
  UNLOCKED_CALLBACKS = ("BlockAllocator.on_evict",)

Lock identities are ``ClassName._attr`` for instance locks and
``modstem._name`` for module-level locks.

Inline waivers (spell the rule id literally, e.g. CC102)::

  self._stepfn(feed)   # threadlint: waive CC1xx <why this is safe>

A waiver comment on the finding's line (or the line directly above it)
downgrades the finding; the report lists every waiver it used and the
run exits clean only when all error/warning findings are waived.
"""

import ast
import os
import re

from .analysis import ERROR, WARNING, INFO

__all__ = [
    "CC_RULES", "ThreadDiagnostic", "ThreadLintReport",
    "analyze_paths", "report_telemetry",
]

# rule id -> one-line catalog entry (README "Static checking" renders this;
# core/analysis.py RULES carries the same entries for the shared catalog)
CC_RULES = {
    "CC101": "lock-order inversion (acquisition-graph cycle or declared "
             "LOCK_ORDER violated)",
    "CC102": "blocking call (RPC, sleep, subprocess, file I/O, join, "
             "compile/step) while holding a lock",
    "CC103": "attribute guarded by a lock in some methods but accessed "
             "lock-free on a thread path",
    "CC104": "Condition.wait without an enclosing while predicate-recheck "
             "loop",
    "CC105": "callback declared fired-unlocked invoked while holding the "
             "owner's lock",
    "CC106": "Thread started without daemon=True or a tracked join() path",
}

_WAIVE_RE = re.compile(
    r"#\s*threadlint:\s*waive\s+(CC\d{3})(?:\s+(.*?))?\s*$")
_EXPECT_RE = re.compile(r"#\s*threadlint-expect:\s*(CC\d{3})")

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}

# ("any", name) call hints are resolved to a class method only when the
# name is defined by exactly ONE class in the analyzed set AND is not one
# of these generic names (builtin-collection / stdlib-object methods that
# would otherwise mis-resolve `d.get(...)` to some class's `get`)
_GENERIC_METHODS = frozenset((
    "get", "pop", "append", "appendleft", "popleft", "add", "remove",
    "clear", "update", "items", "keys", "values", "join", "split", "read",
    "write", "close", "open", "send", "recv", "encode", "decode", "copy",
    "sort", "extend", "discard", "popitem", "setdefault", "wait", "set",
    "acquire", "release", "notify", "notify_all", "start", "run", "put",
    "get_nowait", "put_nowait", "flush", "next", "submit", "result",
    "shutdown", "is_set", "is_alive", "index", "count", "insert",
    "reverse", "strip", "format", "sleep", "exists", "mkdirs", "ls_dir",
    "stop", "tick", "check", "handle", "poll", "serve", "reset", "save",
    "restore", "load", "dump", "name", "kill", "size", "push", "drain",
))

_RPC_METHODS = frozenset((
    "send_var", "get_var", "probe", "barrier", "send_complete",
    "send_expect_now",
))
_EXECUTOR_BLOCKING = frozenset((
    "stepfn", "warmup", "verifyfn", "rolloutfn", "ingestfn"))


def _dotted(node):
    """Attribute/Name chain -> "a.b.c", or None for anything fancier."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last_comp(func):
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _is_self_attr(node):
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self")


def _ctor_kind(call):
    """'lock'/'rlock'/'condition'/'event'/'thread' for a recognized
    threading-object constructor call, else None."""
    name = _last_comp(call.func)
    if name in _LOCK_CTORS:
        return _LOCK_CTORS[name]
    if name == "Event":
        return "event"
    if name == "Thread":
        return "thread"
    return None


# ---------------------------------------------------------------------------
# diagnostics / report
# ---------------------------------------------------------------------------

class ThreadDiagnostic:
    """One structured finding: severity, rule id, file:line, fix."""

    __slots__ = ("severity", "rule", "message", "path", "line", "func",
                 "suggestion", "waived", "waive_reason")

    def __init__(self, severity, rule, message, path, line, func=None,
                 suggestion=None):
        self.severity = severity
        self.rule = rule
        self.message = message
        self.path = path
        self.line = line
        self.func = func
        self.suggestion = suggestion
        self.waived = False
        self.waive_reason = None

    def location(self):
        where = "%s:%s" % (self.path, self.line)
        if self.func:
            where += " in %s" % self.func
        return where

    def format(self):
        line = "%s %s [%s]: %s" % (
            self.rule, "waived" if self.waived else self.severity.upper(),
            self.location(), self.message)
        if self.waived and self.waive_reason:
            line += "\n    waiver: %s" % self.waive_reason
        elif self.suggestion:
            line += "\n    fix: %s" % self.suggestion
        return line

    def to_dict(self):
        return {"severity": self.severity, "rule": self.rule,
                "message": self.message, "path": self.path,
                "line": self.line, "func": self.func,
                "suggestion": self.suggestion, "waived": self.waived,
                "waive_reason": self.waive_reason}

    def __repr__(self):
        return "ThreadDiagnostic(%s, %s, %s)" % (
            self.rule, self.severity, self.location())


class ThreadLintReport:
    """Ordered diagnostic list with severity views, waiver accounting and
    a readable render (mirrors core.analysis.VerifyReport)."""

    def __init__(self, diagnostics=(), label="paddle_tpu"):
        self.diagnostics = list(diagnostics)
        self.label = label
        self.unused_waivers = []   # [(path, line, rule, reason)]

    def add(self, *args, **kwargs):
        self.diagnostics.append(ThreadDiagnostic(*args, **kwargs))

    def extend(self, other):
        self.diagnostics.extend(other.diagnostics)

    @property
    def errors(self):
        return [d for d in self.diagnostics
                if d.severity == ERROR and not d.waived]

    @property
    def warnings(self):
        return [d for d in self.diagnostics
                if d.severity == WARNING and not d.waived]

    @property
    def infos(self):
        return [d for d in self.diagnostics
                if d.severity == INFO and not d.waived]

    @property
    def waived(self):
        return [d for d in self.diagnostics if d.waived]

    def by_rule(self, rule):
        return [d for d in self.diagnostics if d.rule == rule]

    @property
    def ok(self):
        """No unwaived errors and no unwaived warnings."""
        return not self.errors and not self.warnings

    def format(self, max_items=80, include_info=True):
        shown = [d for d in self.diagnostics
                 if include_info or d.severity != INFO]
        head = ("concurrency check of %s: %d error(s), %d warning(s), "
                "%d info, %d waived" % (
                    self.label, len(self.errors), len(self.warnings),
                    len(self.infos), len(self.waived)))
        lines = [head]
        for d in shown[:max_items]:
            lines.append("  " + d.format().replace("\n", "\n  "))
        if len(shown) > max_items:
            lines.append("  ... %d more" % (len(shown) - max_items))
        if self.waived:
            lines.append("waivers in effect:")
            for d in self.waived:
                lines.append("  %s %s: %s" % (
                    d.rule, d.location(), d.waive_reason or "(no reason)"))
        for path, line, rule, _reason in self.unused_waivers:
            lines.append("  note: unused waiver for %s at %s:%d"
                         % (rule, path, line))
        return "\n".join(lines)

    def to_dict(self):
        return {"label": self.label, "ok": self.ok,
                "findings": [d.to_dict() for d in self.diagnostics],
                "unused_waivers": [list(w) for w in self.unused_waivers]}

    def __repr__(self):
        return "<ThreadLintReport %s: %dE/%dW/%dI/%dX>" % (
            self.label, len(self.errors), len(self.warnings),
            len(self.infos), len(self.waived))


# ---------------------------------------------------------------------------
# pass A: module inventory
# ---------------------------------------------------------------------------

class _ClassInfo:
    def __init__(self, name, module):
        self.name = name
        self.module = module
        self.locks = {}         # attr -> kind (lock|rlock|condition)
        self.events = set()     # Event-typed attrs
        self.thread_attrs = set()
        self.methods = {}       # name -> _FuncInfo
        self.is_thread_subclass = False
        self.daemon_subclass = False
        self.joined_attrs = set()     # self.X.join(...) seen anywhere
        self.thread_entries = set()   # method/nested qualnames run on threads


class _FuncInfo:
    def __init__(self, name, node, module, cls=None, parent=None):
        self.name = name
        self.node = node
        self.module = module
        self.cls = cls
        self.parent = parent            # enclosing _FuncInfo for closures
        self.nested = {}
        self.qualname = (
            (cls.name + "." if cls else "")
            + (parent.name + "." if parent and parent is not cls else "")
            + name)
        # filled by pass B
        self.local_acquires = {}        # lock_id -> line
        self.edges = []                 # (held_id, acquired_id, line)
        self.blocking = []              # (line, desc, held tuple, deep_only)
        self.calls = []                 # (kind, name, line, held tuple)
        self.cond_waits = []            # (lock_id, line, in_while, held tup)
        self.attr_writes = []           # (attr, line, own_held, any_held)
        self.attr_reads = []
        self.thread_ctors = []          # (line, daemon, target_kind, target)
        self.local_joins = set()
        self.cc105_sites = []           # (attr, line, held tuple)
        self.reentry = []               # (lock_id, line)


class _ModuleInfo:
    def __init__(self, path, display):
        self.path = path
        self.display = display
        stem = os.path.splitext(os.path.basename(path))[0]
        if stem == "__init__":
            stem = os.path.basename(os.path.dirname(path)) or stem
        self.stem = stem
        self.tree = None
        self.parse_error = None
        self.classes = {}
        self.functions = {}
        self.module_locks = {}          # name -> kind
        self.lock_order = []            # list of tuples of lock ids
        self.unlocked_callbacks = []    # ["Class.attr", ...]
        self.import_names = set()       # names bound by import statements
        self.waivers = {}               # line -> [rule, reason, used_flag]
        try:
            with open(path, "r", encoding="utf-8", errors="replace") as f:
                src = f.read()
        except OSError as e:
            self.parse_error = str(e)
            return
        for i, text in enumerate(src.splitlines(), 1):
            m = _WAIVE_RE.search(text)
            if m:
                self.waivers[i] = [m.group(1),
                                   (m.group(2) or "").strip(), False]
        try:
            self.tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            self.parse_error = str(e)
            return
        self._scan()

    def _scan(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.import_names.add(
                        alias.asname or alias.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name != "*":
                        self.import_names.add(alias.asname or alias.name)
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if isinstance(node.value, ast.Call):
                    kind = _ctor_kind(node.value)
                    if kind in ("lock", "rlock", "condition"):
                        self.module_locks[name] = kind
                elif name in ("LOCK_ORDER", "UNLOCKED_CALLBACKS"):
                    try:
                        val = ast.literal_eval(node.value)
                    except (ValueError, SyntaxError):
                        continue
                    if name == "LOCK_ORDER":
                        self.lock_order = [tuple(t) for t in val]
                    else:
                        self.unlocked_callbacks = list(val)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = _FuncInfo(
                    node.name, node, self)
            elif isinstance(node, ast.ClassDef):
                self._scan_class(node)

    def _scan_class(self, node):
        ci = _ClassInfo(node.name, self)
        for base in node.bases:
            if _last_comp(base) == "Thread":
                ci.is_thread_subclass = True
        for item in node.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            ci.methods[item.name] = _FuncInfo(
                item.name, item, self, cls=ci)
            # attribute inventory: self.X = threading.<ctor>() anywhere
            for sub in ast.walk(item):
                if not isinstance(sub, ast.Assign) \
                        or not isinstance(sub.value, ast.Call):
                    continue
                kind = _ctor_kind(sub.value)
                if kind is None:
                    continue
                for tgt in sub.targets:
                    if not _is_self_attr(tgt):
                        continue
                    if kind in ("lock", "rlock", "condition"):
                        ci.locks[tgt.attr] = kind
                    elif kind == "event":
                        ci.events.add(tgt.attr)
                    elif kind == "thread":
                        ci.thread_attrs.add(tgt.attr)
            if ci.is_thread_subclass and item.name == "__init__":
                for sub in ast.walk(item):
                    if isinstance(sub, ast.Call) \
                            and _last_comp(sub.func) == "__init__":
                        for kw in sub.keywords:
                            if kw.arg == "daemon" \
                                    and isinstance(kw.value, ast.Constant) \
                                    and kw.value.value is True:
                                ci.daemon_subclass = True
                    if isinstance(sub, ast.Assign):
                        for tgt in sub.targets:
                            if _is_self_attr(tgt) and tgt.attr == "daemon" \
                                    and isinstance(sub.value, ast.Constant) \
                                    and sub.value.value is True:
                                ci.daemon_subclass = True
        if ci.is_thread_subclass and "run" in ci.methods:
            ci.thread_entries.add("run")
        self.classes[node.name] = ci


# ---------------------------------------------------------------------------
# pass B: per-function analysis
# ---------------------------------------------------------------------------

class _Index:
    """Package-wide resolution tables built from every _ModuleInfo."""

    def __init__(self, modules):
        self.modules = modules
        self.methods_by_name = {}     # name -> [_FuncInfo]
        self.lock_attr_owners = {}    # attr -> [class name]
        self.lock_kinds = {}          # lock_id -> kind
        self.thread_subclasses = {}   # class name -> _ClassInfo
        self.lock_order = []
        self.contracts = set()        # (class name, attr)
        for mod in modules:
            self.lock_order.extend(mod.lock_order)
            for cb in mod.unlocked_callbacks:
                if "." in cb:
                    cls, attr = cb.rsplit(".", 1)
                    self.contracts.add((cls, attr))
            for name, kind in mod.module_locks.items():
                self.lock_kinds["%s.%s" % (mod.stem, name)] = kind
            for ci in mod.classes.values():
                if ci.is_thread_subclass:
                    self.thread_subclasses[ci.name] = ci
                for attr, kind in ci.locks.items():
                    self.lock_kinds["%s.%s" % (ci.name, attr)] = kind
                    self.lock_attr_owners.setdefault(attr, []).append(
                        ci.name)
                for mname, fi in ci.methods.items():
                    self.methods_by_name.setdefault(mname, []).append(fi)

    def resolve(self, fi, kind, name):
        if kind == "self":
            if fi.cls is not None:
                return fi.cls.methods.get(name)
            return None
        if kind == "mod":
            if name in fi.nested:
                return fi.nested[name]
            if fi.parent is not None and name in fi.parent.nested:
                return fi.parent.nested[name]
            return fi.module.functions.get(name)
        if kind == "any":
            if name in _GENERIC_METHODS:
                return None
            cands = self.methods_by_name.get(name, ())
            if len(cands) == 1:
                return cands[0]
        return None


class _FuncScan:
    """One recursive walk of a function body, tracking the held-lock set
    and loop depth; fills the _FuncInfo summary fields."""

    def __init__(self, fi, idx):
        self.fi = fi
        self.idx = idx
        self.cls = fi.cls
        self.mod = fi.module
        self.alias_cb = {}       # local name -> contract callback attr
        self.thread_alias = {}   # local name -> thread attr
        self.local_threads = set()
        self._consumed = set()   # id(Call) already handled by Assign

    # -- lock reference resolution ------------------------------------------

    def lock_ref(self, node):
        if _is_self_attr(node) and self.cls is not None:
            if node.attr in self.cls.locks:
                return "%s.%s" % (self.cls.name, node.attr)
            return None
        if isinstance(node, ast.Name):
            if node.id in self.mod.module_locks:
                return "%s.%s" % (self.mod.stem, node.id)
            return None
        if isinstance(node, ast.Attribute):
            owners = self.idx.lock_attr_owners.get(node.attr, ())
            if len(owners) == 1:
                return "%s.%s" % (owners[0], node.attr)
        return None

    def lock_kind(self, lock_id):
        return self.idx.lock_kinds.get(lock_id, "lock")

    # -- entry ---------------------------------------------------------------

    def run(self):
        self.stmts(self.fi.node.body, (), 0)

    # -- statement walking ---------------------------------------------------

    def stmts(self, body, held, loop):
        held = list(held)
        for st in body:
            if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
                f = st.value.func
                if isinstance(f, ast.Attribute) \
                        and f.attr in ("acquire", "release"):
                    lid = self.lock_ref(f.value)
                    if lid is not None:
                        if f.attr == "acquire":
                            self.on_acquire(lid, st.lineno, tuple(held))
                            held.append(lid)
                        elif lid in held:
                            held.remove(lid)
                        continue
            self.stmt(st, tuple(held), loop)

    def stmt(self, st, held, loop):
        if isinstance(st, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in st.items:
                self.expr(item.context_expr, tuple(inner), loop)
                lid = self.lock_ref(item.context_expr)
                if lid is not None:
                    self.on_acquire(lid, item.context_expr.lineno,
                                    tuple(inner))
                    inner.append(lid)
            self.stmts(st.body, tuple(inner), loop)
        elif isinstance(st, ast.While):
            self.expr(st.test, held, loop + 1)
            self.stmts(st.body, held, loop + 1)
            self.stmts(st.orelse, held, loop)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self.expr(st.iter, held, loop)
            self.stmts(st.body, held, loop)
            self.stmts(st.orelse, held, loop)
        elif isinstance(st, ast.If):
            self.expr(st.test, held, loop)
            self.stmts(st.body, held, loop)
            self.stmts(st.orelse, held, loop)
        elif isinstance(st, ast.Try):
            self.stmts(st.body, held, loop)
            for h in st.handlers:
                self.stmts(h.body, held, loop)
            self.stmts(st.orelse, held, loop)
            self.stmts(st.finalbody, held, loop)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sub = _FuncInfo(st.name, st, self.mod, cls=self.cls,
                            parent=self.fi)
            self.fi.nested[st.name] = sub
            _FuncScan(sub, self.idx).run()
        elif isinstance(st, ast.ClassDef):
            pass
        elif isinstance(st, ast.Assign):
            self.on_assign(st, held, loop)
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            tgt = st.target
            self.note_write_target(tgt, held)
            if isinstance(st, ast.AugAssign) or st.value is not None:
                self.expr(st.value, held, loop)
            if isinstance(st, ast.AugAssign):
                self.expr(tgt, held, loop)
        elif isinstance(st, ast.Return):
            if st.value is not None:
                self.expr(st.value, held, loop)
        else:
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self.expr(child, held, loop)
                elif isinstance(child, ast.stmt):
                    self.stmt(child, held, loop)

    def on_assign(self, st, held, loop):
        val = st.value
        if isinstance(val, ast.Call) and _ctor_kind(val) == "thread":
            target = None
            tkind = None
            for tgt in st.targets:
                if _is_self_attr(tgt):
                    target, tkind = tgt.attr, "attr"
                elif isinstance(tgt, ast.Name):
                    target, tkind = tgt.id, "local"
                    self.local_threads.add(tgt.id)
            self.on_thread_ctor(val, held, target=target, tkind=tkind)
            self._consumed.add(id(val))
        elif isinstance(val, ast.Name) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name):
            if val.id in self.local_threads:
                self.local_threads.add(st.targets[0].id)
        elif _is_self_attr(val) and self.cls is not None:
            for tgt in st.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                if (self.cls.name, val.attr) in self.idx.contracts:
                    self.alias_cb[tgt.id] = val.attr
                if val.attr in self.cls.thread_attrs:
                    self.thread_alias[tgt.id] = val.attr
        for tgt in st.targets:
            self.note_write_target(tgt, held)
        self.expr(val, held, loop)

    def note_write_target(self, tgt, held):
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self.note_write_target(el, held)
            return
        node = tgt
        if isinstance(node, ast.Subscript):
            node = node.value
        if _is_self_attr(node) and self.cls is not None:
            self.record_attr(self.fi.attr_writes, node.attr, tgt.lineno,
                             held)

    def record_attr(self, sink, attr, line, held):
        own = any(l.startswith(self.cls.name + ".") for l in held)
        sink.append((attr, line, own, bool(held)))

    # -- expression walking --------------------------------------------------

    def expr(self, node, held, loop):
        if node is None or isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.Call):
            self.on_call(node, held, loop)
            self.expr(node.func, held, loop)
            for a in node.args:
                self.expr(a, held, loop)
            for kw in node.keywords:
                self.expr(kw.value, held, loop)
            return
        if _is_self_attr(node) and self.cls is not None \
                and isinstance(node.ctx, ast.Load):
            self.record_attr(self.fi.attr_reads, node.attr, node.lineno,
                             held)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.expr(child, held, loop)

    # -- events --------------------------------------------------------------

    def on_acquire(self, lid, line, held):
        kind = self.lock_kind(lid)
        if lid in held and kind == "lock":
            self.fi.reentry.append((lid, line))
        if lid not in self.fi.local_acquires:
            self.fi.local_acquires[lid] = line
        for h in held:
            if h != lid:
                self.fi.edges.append((h, lid, line))

    def on_call(self, call, held, loop):
        if id(call) in self._consumed:
            return
        func = call.func
        last = _last_comp(func)
        if last == "Thread" and isinstance(func, (ast.Attribute, ast.Name)):
            self.on_thread_ctor(call, held)
            return
        if isinstance(func, ast.Name) \
                and func.id in self.idx.thread_subclasses:
            self.on_thread_ctor(
                call, held, subclass=self.idx.thread_subclasses[func.id])
            return
        if isinstance(func, ast.Attribute) and func.attr == "wait":
            if self.on_wait(call, func, held, loop):
                return
        desc = self.blocking_desc(call, func, last)
        if desc is not None:
            self.fi.blocking.append((call.lineno, desc, held, False))
        # CC105: direct or aliased unlocked-contract callback call
        if held:
            if _is_self_attr(func) and self.cls is not None \
                    and (self.cls.name, func.attr) in self.idx.contracts:
                self.fi.cc105_sites.append((func.attr, call.lineno, held))
            elif isinstance(func, ast.Name) and func.id in self.alias_cb:
                self.fi.cc105_sites.append(
                    (self.alias_cb[func.id], call.lineno, held))
        # call hint for propagation
        if isinstance(func, ast.Name):
            self.fi.calls.append(("mod", func.id, call.lineno, held))
        elif _is_self_attr(func):
            self.fi.calls.append(("self", func.attr, call.lineno, held))
        elif isinstance(func, ast.Attribute):
            root = func.value
            while isinstance(root, ast.Attribute):
                root = root.value
            # a chain rooted at an imported name (os.makedirs, np.stack)
            # targets that module, never a same-named method elsewhere in
            # the package — suppress the unique-method-name hint
            if not (isinstance(root, ast.Name)
                    and root.id in self.mod.import_names):
                self.fi.calls.append(("any", func.attr, call.lineno, held))

    def on_wait(self, call, func, held, loop):
        """-> True when fully handled (condition/event wait)."""
        recv = func.value
        lid = self.lock_ref(recv)
        if lid is not None and self.lock_kind(lid) == "condition":
            self.fi.cond_waits.append((lid, call.lineno, loop > 0, held))
            others = tuple(h for h in held if h != lid)
            if others:
                self.fi.blocking.append(
                    (call.lineno, "Condition.wait on %s" % lid, others,
                     False))
            elif lid in held:
                # releases its own lock while parked: only relevant to a
                # caller that holds an OUTER lock (deep propagation)
                self.fi.blocking.append(
                    (call.lineno, "Condition.wait on %s" % lid, held,
                     True))
            return True
        if _is_self_attr(recv) and self.cls is not None \
                and recv.attr in self.cls.events:
            if held:
                self.fi.blocking.append(
                    (call.lineno, "Event.wait (self.%s)" % recv.attr,
                     held, False))
            return True
        return False

    def blocking_desc(self, call, func, last):
        dotted = _dotted(func)
        if dotted == "time.sleep":
            return "time.sleep"
        if dotted and dotted.split(".", 1)[0] == "subprocess":
            return dotted
        if dotted in ("os.replace", "os.rename"):
            return dotted
        if dotted == "open":
            return "open (file I/O)"
        if dotted in ("np.savez", "np.savez_compressed", "np.save",
                      "np.load", "json.dump", "json.load",
                      "shutil.copytree", "shutil.rmtree", "shutil.move"):
            return "%s (file I/O)" % dotted
        if last in _RPC_METHODS:
            return "RPC %s" % last
        if last in _EXECUTOR_BLOCKING:
            return "executor %s (compile/device step)" % last
        if last == "join" and isinstance(func, ast.Attribute):
            recv = func.value
            if _is_self_attr(recv) and self.cls is not None \
                    and recv.attr in self.cls.thread_attrs:
                self.cls.joined_attrs.add(recv.attr)
                return "Thread.join (self.%s)" % recv.attr
            if isinstance(recv, ast.Name):
                if recv.id in self.thread_alias:
                    self.cls.joined_attrs.add(self.thread_alias[recv.id])
                    return "Thread.join (%s)" % recv.id
                if recv.id in self.local_threads:
                    self.fi.local_joins.add(recv.id)
                    return "Thread.join (%s)" % recv.id
        return None

    def on_thread_ctor(self, call, held, target=None, tkind=None,
                       subclass=None):
        daemon = None
        if subclass is not None:
            daemon = True if subclass.daemon_subclass else None
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
            elif kw.arg == "target":
                self.register_target(kw.value)
        self.fi.thread_ctors.append((call.lineno, daemon, tkind, target))
        self._consumed.add(id(call))

    def register_target(self, node):
        if _is_self_attr(node) and self.cls is not None:
            self.cls.thread_entries.add(node.attr)
        elif isinstance(node, ast.Name):
            if node.id in self.fi.nested:
                if self.cls is not None:
                    self.cls.thread_entries.add(
                        self.fi.nested[node.id].qualname)
                self.fi.nested[node.id].is_entry = True
            elif node.id in self.mod.functions:
                self.mod.functions[node.id].is_entry = True


# ---------------------------------------------------------------------------
# deep propagation + rule evaluation
# ---------------------------------------------------------------------------

def _all_functions(modules):
    for mod in modules:
        stack = list(mod.functions.values())
        for ci in mod.classes.values():
            stack.extend(ci.methods.values())
        while stack:
            fi = stack.pop()
            yield fi
            stack.extend(fi.nested.values())


class _Analyzer:
    def __init__(self, modules, label):
        self.modules = modules
        self.idx = _Index(modules)
        self.report = ThreadLintReport(label=label)
        self._deep_acq = {}
        self._deep_blk = {}
        self._seen = set()

    # -- plumbing ------------------------------------------------------------

    def emit(self, severity, rule, message, mod, line, func=None,
             suggestion=None):
        key = (rule, mod.path, line, message)
        if key in self._seen:
            return
        self._seen.add(key)
        self.report.add(severity, rule, message, mod.display, line,
                        func=func, suggestion=suggestion)

    def run(self):
        for mod in self.modules:
            if mod.parse_error is not None:
                self.report.add(INFO, "CC100",
                                "file skipped (parse error: %s)"
                                % mod.parse_error, mod.display, 1)
        for fi in _all_functions(self.modules):
            _FuncScan(fi, self.idx).run()
        self.check_cc101()
        self.check_cc102()
        self.check_cc103()
        self.check_cc104()
        self.check_cc105()
        self.check_cc106()
        self.apply_waivers()
        return self.report

    # -- deep summaries ------------------------------------------------------

    def deep_acquires(self, fi, stack=()):
        if fi in self._deep_acq:
            return self._deep_acq[fi]
        if fi in stack:
            return {}
        out = {lid: (fi, line) for lid, line in fi.local_acquires.items()}
        for kind, name, line, _held in fi.calls:
            g = self.idx.resolve(fi, kind, name)
            if g is None:
                continue
            for lid, site in self.deep_acquires(g, stack + (fi,)).items():
                out.setdefault(lid, site)
        self._deep_acq[fi] = out
        return out

    def deep_blocking(self, fi, stack=()):
        if fi in self._deep_blk:
            return self._deep_blk[fi]
        if fi in stack:
            return []
        out = [(fi, line, desc) for line, desc, _held, _d in fi.blocking]
        for kind, name, _line, _held in fi.calls:
            g = self.idx.resolve(fi, kind, name)
            if g is None:
                continue
            out.extend(self.deep_blocking(g, stack + (fi,)))
        self._deep_blk[fi] = out
        return out

    # -- CC101 ---------------------------------------------------------------

    def check_cc101(self):
        edges = {}   # (a, b) -> (fi, line)
        for fi in _all_functions(self.modules):
            for a, b, line in fi.edges:
                edges.setdefault((a, b), (fi, line))
            for lid, line in fi.reentry:
                self.emit(ERROR, "CC101",
                          "non-reentrant lock %s re-acquired while "
                          "already held (self-deadlock)" % lid,
                          fi.module, line, func=fi.qualname,
                          suggestion="use an RLock or restructure so the "
                                     "outer holder passes control down")
            for kind, name, line, held in fi.calls:
                if not held:
                    continue
                g = self.idx.resolve(fi, kind, name)
                if g is None:
                    continue
                for lid, _site in self.deep_acquires(g).items():
                    if lid in held \
                            and self.idx.lock_kinds.get(lid) == "lock":
                        self.emit(
                            ERROR, "CC101",
                            "non-reentrant lock %s re-acquired via call "
                            "to %s while already held" % (lid,
                                                          g.qualname),
                            fi.module, line, func=fi.qualname)
                        continue
                    for h in held:
                        if h != lid:
                            edges.setdefault((h, lid), (fi, line))
        # declared-order violations
        for (a, b), (fi, line) in sorted(edges.items()):
            for order in self.idx.lock_order:
                if a in order and b in order \
                        and order.index(a) > order.index(b):
                    self.emit(
                        ERROR, "CC101",
                        "acquisition %s -> %s inverts declared LOCK_ORDER "
                        "%s" % (a, b, " -> ".join(order)),
                        fi.module, line, func=fi.qualname,
                        suggestion="release %s before taking %s, or fix "
                                   "the registry if the contract changed"
                                   % (a, b))
        # cycles in the observed acquisition graph
        graph = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        for cyc in _find_cycles(graph):
            pairs = list(zip(cyc, cyc[1:] + cyc[:1]))
            fi, line = edges[pairs[0]]
            sites = ", ".join(
                "%s->%s@%s:%d" % (a, b, edges[(a, b)][0].module.display,
                                  edges[(a, b)][1])
                for a, b in pairs if (a, b) in edges)
            self.emit(ERROR, "CC101",
                      "lock-order cycle %s (%s)"
                      % (" -> ".join(cyc + [cyc[0]]), sites),
                      fi.module, line, func=fi.qualname,
                      suggestion="declare one order in LOCK_ORDER and "
                                 "restructure the inverted acquisition")
        # registry entries that name unknown locks rot silently — surface
        for order in self.idx.lock_order:
            for lid in order:
                if lid not in self.idx.lock_kinds:
                    mod = next((m for m in self.modules
                                if order in [tuple(t) for t
                                             in m.lock_order]),
                               self.modules[0])
                    self.emit(INFO, "CC101",
                              "LOCK_ORDER names unknown lock %s "
                              "(stale registry entry?)" % lid, mod, 1)

    # -- CC102 ---------------------------------------------------------------

    def check_cc102(self):
        for fi in _all_functions(self.modules):
            for line, desc, held, deep_only in fi.blocking:
                if held and not deep_only:
                    self.emit(
                        WARNING, "CC102",
                        "blocking %s while holding %s"
                        % (desc, ", ".join(sorted(held))),
                        fi.module, line, func=fi.qualname,
                        suggestion="move the blocking call outside the "
                                   "lock (snapshot state under the lock, "
                                   "act on it after release)")
            for kind, name, line, held in fi.calls:
                if not held:
                    continue
                g = self.idx.resolve(fi, kind, name)
                if g is None:
                    continue
                for bfi, bline, desc in self.deep_blocking(g):
                    self.emit(
                        WARNING, "CC102",
                        "blocking %s reachable while %s holds %s "
                        "(called via %s at %s:%d)"
                        % (desc, fi.qualname, ", ".join(sorted(held)),
                           g.qualname, fi.module.display, line),
                        bfi.module, bline, func=bfi.qualname)

    # -- CC103 ---------------------------------------------------------------

    def check_cc103(self):
        for mod in self.modules:
            for ci in mod.classes.values():
                if not ci.thread_entries:
                    continue
                funcs = self._class_functions(ci)
                fvals = set(funcs.values())
                locked = self._locked_context(ci, funcs, fvals)
                guarded = {}
                for fi in fvals:
                    for attr, line, own, _any in fi.attr_writes:
                        if (own or fi in locked) and attr not in guarded:
                            guarded[attr] = (fi, line)
                if not guarded:
                    continue
                reachable = self._reachable(ci, funcs)
                skip = (set(ci.locks) | ci.events | ci.thread_attrs)
                for fi in reachable:
                    if fi.name == "__init__" or fi in locked:
                        continue
                    for attr, line, _own, any_held in (fi.attr_writes
                                                       + fi.attr_reads):
                        if attr in guarded and attr not in skip \
                                and not any_held:
                            gfi, gline = guarded[attr]
                            self.emit(
                                WARNING, "CC103",
                                "self.%s is written under %s's lock "
                                "(%s:%d) but accessed lock-free here on "
                                "a thread path"
                                % (attr, ci.name, gfi.module.display,
                                   gline),
                                fi.module, line, func=fi.qualname,
                                suggestion="take the lock here too, or "
                                           "stop guarding the attribute "
                                           "anywhere if unsynchronized "
                                           "access is the contract")

    def _class_functions(self, ci):
        out = {}
        stack = list(ci.methods.values())
        while stack:
            fi = stack.pop()
            out[fi.qualname] = fi
            stack.extend(fi.nested.values())
        return out

    def _entry_funcs(self, ci, funcs):
        entries = []
        for ent in ci.thread_entries:
            if ent in funcs:
                entries.append(funcs[ent])
            elif ci.name + "." + ent in funcs:
                entries.append(funcs[ci.name + "." + ent])
        return entries

    def _locked_context(self, ci, funcs, fvals):
        """Fixpoint of methods whose every intra-class call site holds the
        class's own lock, either lexically or because the caller is itself
        locked context (the ``_*_locked`` helper convention).  Accesses in
        such methods are guarded by construction, not escapes."""
        entries = set(self._entry_funcs(ci, funcs))
        own_prefix = ci.name + "."
        sites = {}
        for fi in fvals:
            for kind, name, _line, held in fi.calls:
                g = self.idx.resolve(fi, kind, name)
                if g is not None and g in fvals and g is not fi:
                    own = any(h.startswith(own_prefix) for h in held)
                    sites.setdefault(g, []).append((fi, own))
        locked = set()
        changed = True
        while changed:
            changed = False
            for fi in fvals:
                if fi in locked or fi in entries:
                    continue
                ss = sites.get(fi)
                if not ss:
                    continue
                if all(own or caller in locked for caller, own in ss):
                    locked.add(fi)
                    changed = True
        return locked

    def _reachable(self, ci, funcs):
        entries = self._entry_funcs(ci, funcs)
        seen = set()
        stack = list(entries)
        while stack:
            fi = stack.pop()
            if fi in seen:
                continue
            seen.add(fi)
            for kind, name, _line, _held in fi.calls:
                g = self.idx.resolve(fi, kind, name)
                if g is not None and g.cls is ci and g in funcs.values():
                    stack.append(g)
            stack.extend(fi.nested.values())
        return seen

    # -- CC104 ---------------------------------------------------------------

    def check_cc104(self):
        for fi in _all_functions(self.modules):
            for lid, line, in_while, _held in fi.cond_waits:
                if not in_while:
                    self.emit(
                        ERROR, "CC104",
                        "%s.wait() without an enclosing while loop — a "
                        "spurious wakeup or stolen notify proceeds on a "
                        "false predicate" % lid,
                        fi.module, line, func=fi.qualname,
                        suggestion="wrap the wait in "
                                   "`while not <predicate>:`")

    # -- CC105 ---------------------------------------------------------------

    def check_cc105(self):
        for fi in _all_functions(self.modules):
            for attr, line, held in fi.cc105_sites:
                self.emit(
                    ERROR, "CC105",
                    "callback %s.%s is declared fired-unlocked "
                    "(UNLOCKED_CALLBACKS) but invoked holding %s"
                    % (fi.cls.name, attr, ", ".join(sorted(held))),
                    fi.module, line, func=fi.qualname,
                    suggestion="read the callback under the lock, invoke "
                               "it after release (the on_evict pattern)")

    # -- CC106 ---------------------------------------------------------------

    def check_cc106(self):
        for fi in _all_functions(self.modules):
            for line, daemon, tkind, target in fi.thread_ctors:
                if daemon is True:
                    continue
                ok = False
                if tkind == "attr" and fi.cls is not None \
                        and target in fi.cls.joined_attrs:
                    ok = True
                elif tkind == "local" and target in fi.local_joins:
                    ok = True
                if not ok:
                    self.emit(
                        WARNING, "CC106",
                        "Thread started without daemon=True or a tracked "
                        "join() path — leaks past interpreter shutdown "
                        "and across tests",
                        fi.module, line, func=fi.qualname,
                        suggestion="pass daemon=True, or keep the handle "
                                   "and join() it in a stop()/close() "
                                   "path")

    # -- waivers -------------------------------------------------------------

    def apply_waivers(self):
        by_display = {m.display: m for m in self.modules}
        for d in self.report.diagnostics:
            mod = by_display.get(d.path)
            if mod is None:
                continue
            for ln in (d.line, d.line - 1):
                w = mod.waivers.get(ln)
                if w is not None and w[0] == d.rule:
                    d.waived = True
                    d.waive_reason = w[1] or None
                    w[2] = True
                    break
        for mod in self.modules:
            for line, (rule, reason, used) in sorted(mod.waivers.items()):
                if not used:
                    self.report.unused_waivers.append(
                        (mod.display, line, rule, reason))


def _find_cycles(graph):
    """Minimal cycle enumeration: one representative cycle per SCC with
    more than one node (self-loops are the reentrancy check's job)."""
    index = {}
    low = {}
    on_stack = set()
    stack = []
    sccs = []
    counter = [0]

    def strongconnect(v):
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(scc)

    nodes = set(graph)
    for tos in graph.values():
        nodes.update(tos)
    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)

    out = []
    for scc in sccs:
        members = set(scc)
        # walk one cycle through the SCC deterministically
        start = min(members)
        path = [start]
        seen = {start}
        cur = start
        while True:
            nxt = min((w for w in graph.get(cur, ())
                       if w in members), default=None)
            if nxt is None:
                break
            if nxt == start:
                out.append(path)
                break
            if nxt in seen:
                out.append(path[path.index(nxt):])
                break
            path.append(nxt)
            seen.add(nxt)
            cur = nxt
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _collect_files(paths):
    files = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in ("__pycache__", ".git"))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        files.append(os.path.join(dirpath, fn))
        elif p.endswith(".py"):
            files.append(p)
    return files


def analyze_paths(paths, rules=None, label=None):
    """Run the CC1xx analysis over files/directories.  ``rules`` filters
    the report to a subset of rule ids.  -> ThreadLintReport."""
    if isinstance(paths, str):
        paths = [paths]
    files = _collect_files(paths)
    modules = [_ModuleInfo(f, os.path.relpath(f)) for f in files]
    report = _Analyzer(
        modules, label or ", ".join(paths)).run()
    if rules:
        keep = set(rules)
        report.diagnostics = [d for d in report.diagnostics
                              if d.rule in keep]
    return report


def expected_findings(path):
    """Scan a fixture module for ``# threadlint-expect: CCxxx`` markers;
    -> [(rule, line)].  Fixture tests and --seed-defect both use this."""
    out = []
    with open(path, "r", encoding="utf-8") as f:
        for i, text in enumerate(f, 1):
            m = _EXPECT_RE.search(text)
            if m:
                out.append((m.group(1), i))
    return out


def report_telemetry(report):
    """Count findings/waivers into telemetry (mirrors the
    ``static_check_warnings`` plumbing in core.analysis._dispatch)."""
    from . import telemetry
    if not telemetry.enabled():
        return
    for d in report.diagnostics:
        if d.severity == INFO:
            continue
        if d.waived:
            telemetry.inc("static_check_waivers_total", 1, rule=d.rule)
        else:
            telemetry.inc("static_check_concurrency_total", 1, rule=d.rule)
