"""Executor: runs Programs by compiling whole blocks to XLA.

TPU-native analog of ``paddle/fluid/framework/executor.cc:94`` +
``python/paddle/fluid/executor.py:423``.  Instead of interpreting ops one by
one, `run()` builds (and caches) a single jitted function per
(program-version, feed-signature, fetch-list) key: parameters stream in from
the Scope, get donated when the block overwrites them (optimizer update), and
the updated values are stored back.  Data-parallel / sharded execution reuses
the same path with a `jax.sharding.Mesh` (see paddle_tpu.compiler).
"""

import logging
import time

import numpy as np

import jax
import jax.numpy as jnp

from ..framework import (
    CPUPlace,
    Program,
    Variable,
    default_main_program,
    dtype_to_np,
)
from .lowering import BlockPlan, build_block_fn
from .scope import Scope
from . import telemetry as _telemetry
from . import tracing as _tracing

__all__ = ["Executor", "global_scope", "scope_guard", "CarriedStepFn",
           "aot_compile_cached"]

import contextlib
import threading

_RNG_COUNTER_LOCK = threading.Lock()

_global_scope = Scope()
# Per-thread scope override (same design as framework's default-program TLS):
# role threads (pserver/worker standing in for separate processes) each
# scope_guard their own Scope without racing on the module global; threads
# that never call scope_guard see the main thread's current scope.
_scope_tls = threading.local()


def _is_main_thread():
    return threading.current_thread() is threading.main_thread()


def global_scope():
    if not _is_main_thread() and getattr(_scope_tls, "scope", None) is not None:
        return _scope_tls.scope
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope):
    global _global_scope
    if _is_main_thread():
        old = _global_scope
        _global_scope = scope
        try:
            yield
        finally:
            _global_scope = old
    else:
        old = getattr(_scope_tls, "scope", None)
        _scope_tls.scope = scope
        try:
            yield
        finally:
            _scope_tls.scope = old


def _fetch_name(f):
    if isinstance(f, Variable):
        return f.name
    if isinstance(f, str):
        return f
    raise TypeError("bad fetch target %r" % (f,))


def as_numpy(t):
    if isinstance(t, jax.Array) and not t.is_fully_addressable:
        # multi-process fetch: materialize this process's shards only (the
        # reference's nccl2-mode trainers likewise see their local loss).
        # Dedupe by global index (replicated copies on several local
        # devices collapse to one) and order batch shards by their dim-0
        # offset; slice objects themselves are unorderable.
        uniq = {}
        for s in t.addressable_shards:
            key = tuple(sl.start or 0 for sl in s.index)
            uniq.setdefault(key, s)
        if not uniq:
            raise RuntimeError(
                "fetch spans no devices addressable by this process")
        arrs = [np.asarray(s.data) for _, s in sorted(uniq.items())]
        if len(arrs) == 1 or arrs[0].ndim == 0:
            return arrs[0]
        return np.concatenate(arrs, axis=0)
    return np.asarray(t)



def _with_seed_counter(fn):
    """Adapt fn(feeds, ro, rw, carry, key) to take a [seed, counter] uint32
    pair, deriving the key inside the trace (no eager key ops per step)."""

    def wrapped(feeds, params_ro, params_rw, params_carry, sc):
        key = jax.random.fold_in(jax.random.key(sc[0]), sc[1])
        return fn(feeds, params_ro, params_rw, params_carry, key)

    return wrapped


class _CompiledPlan:
    """One cache entry.  ``jfn`` is what run() calls: normally an
    AOT-``Compiled`` executable (eager compile on the miss path, possibly
    deserialized from the tier-B disk cache), or the lazy ``jax.jit``
    wrapper when the eager path had to fall back.  ``jit_fn`` keeps the
    jit wrapper either way for tools that need ``.lower()`` (hbm audit)."""

    __slots__ = ("plan", "jfn", "mesh", "data_axis", "jit_fn")

    def __init__(self, plan, jfn, mesh=None, data_axis=None, jit_fn=None):
        self.plan = plan
        self.jfn = jfn
        self.mesh = mesh
        self.data_axis = data_axis
        self.jit_fn = jit_fn if jit_fn is not None else jfn


class _BuildResult:
    """Stage-1 compile product: the BlockPlan plus the raw python callable
    and jit parameters — everything needed to gather/shard inputs and then
    trace, without having traced anything yet."""

    __slots__ = ("plan", "fn", "donate", "mesh", "data_axis",
                 "out_shardings")

    def __init__(self, plan, fn, donate, mesh=None, data_axis=None,
                 out_shardings=None):
        self.plan = plan
        self.fn = fn
        self.donate = donate
        self.mesh = mesh
        self.data_axis = data_axis
        self.out_shardings = out_shardings


def aot_compile_cached(jfn, args, disk_key, dev=None, meta=None):
    """Produce an AOT ``Compiled`` for ``jfn(*args)`` with tier-B disk
    persistence: disk restore -> eager ``lower().compile()`` (serialized
    back, round-trip-trialed) -> ``(None, cstats)`` when the eager path
    explodes (the caller falls back to the lazy jit wrapper).

    Shared by the Program path (``Executor._finalize_compile``) and the
    decode-serving step path (``CarriedStepFn``) — one implementation of
    the restore/compile/serialize discipline, including the tier-A
    poisoned-executable retry."""
    from . import compile_cache as _cc

    def mkctx():
        # jax.default_device is a single-use context manager
        return (jax.default_device(dev) if dev is not None
                else contextlib.nullcontext())

    tel = _telemetry.enabled()
    cstats = {"source": "fallback", "compile_ms": 0.0}
    compiled = None
    t0 = time.perf_counter()
    if disk_key is not None:
        rspan = _tracing.start_span("executor.cache_restore",
                                    key=disk_key[:12])
        got = _cc.load(disk_key)
        if got is not None:
            try:
                from jax.experimental import serialize_executable as _se

                with mkctx():
                    compiled = _se.deserialize_and_load(
                        got["payload"], got["in_tree"], got["out_tree"])
                cstats["source"] = "disk"
                if tel:
                    _telemetry.observe(
                        "compile_cache_load_ms",
                        (time.perf_counter() - t0) * 1e3)
            except Exception as e:
                compiled = None
                logging.warning(
                    "compile_cache: deserialize of %s failed (%s); "
                    "recompiling", disk_key[:12], e)
                _telemetry.inc("compile_cache_errors_total",
                               kind="deserialize")
                # crc-valid but unloadable (e.g. XLA build drift):
                # drop it so the store below rewrites the entry
                _cc.invalidate(disk_key)
        rspan.annotate(hit=compiled is not None).end()
    if compiled is None:
        cspan = _tracing.start_span("executor.compile")
        try:
            with mkctx():
                t_tr = time.perf_counter()
                lowered = jfn.lower(*args)
                t_lo = time.perf_counter()
                compiled = lowered.compile()
            cstats["source"] = "compiled"
            if tel:
                _telemetry.inc("executor_xla_compile_total")
                _telemetry.observe("executor_trace_lower_ms",
                                   (t_lo - t_tr) * 1e3)
                _telemetry.observe(
                    "executor_xla_compile_ms",
                    (time.perf_counter() - t_lo) * 1e3)
            if disk_key is not None:
                try:
                    from jax.experimental import \
                        serialize_executable as _se

                    def roundtrips(parts):
                        # an executable restored from jax's persistent
                        # XLA cache (tier A) serializes WITHOUT its JIT
                        # object code on XLA:CPU — the payload
                        # deserializes to "Symbols not found".  Trial-
                        # load before storing so tier B only ever holds
                        # self-contained artifacts.
                        try:
                            with mkctx():
                                _se.deserialize_and_load(*parts)
                            return True
                        except Exception:
                            return False

                    parts = _se.serialize(compiled)
                    if not roundtrips(parts):
                        _telemetry.inc(
                            "compile_cache_roundtrip_retry_total")
                        # jax memoizes the is_cache_used verdict the
                        # first time any compile runs, so flipping the
                        # flag alone is a no-op — reset_cache() forces
                        # the re-check (and again after, so tier A
                        # resumes for subsequent compiles)
                        from jax._src import \
                            compilation_cache as _jcc
                        cfg = jax.config
                        old = cfg.jax_enable_compilation_cache
                        try:
                            cfg.update("jax_enable_compilation_cache",
                                       False)
                            _jcc.reset_cache()
                            # in-memory weakref memo (pxla.
                            # _cached_compilation) would hand back the
                            # same poisoned executable for the
                            # identical HLO — drop it too
                            jax.clear_caches()
                            with mkctx():
                                compiled = jfn.lower(*args).compile()
                        finally:
                            cfg.update("jax_enable_compilation_cache",
                                       old)
                            _jcc.reset_cache()
                        parts = _se.serialize(compiled)
                    if roundtrips(parts):
                        _cc.store(disk_key, *parts, meta=meta or {})
                    else:
                        logging.warning(
                            "compile_cache: %s does not serialize "
                            "round-trippably; not stored",
                            disk_key[:12])
                        _telemetry.inc("compile_cache_errors_total",
                                       kind="serialize")
                except Exception as e:
                    logging.warning(
                        "compile_cache: serialize failed: %s", e)
                    _telemetry.inc("compile_cache_errors_total",
                                   kind="serialize")
        except Exception as e:
            # the lazy path compiles inside the first call — identical
            # semantics, just conflated timing (pre-PR behavior)
            logging.warning(
                "executor: eager AOT compile failed (%s); falling back "
                "to lazy jit", e)
            _telemetry.inc("executor_aot_fallback_total")
            compiled = None
        cspan.annotate(source=cstats["source"]).end()
    cstats["compile_ms"] = (time.perf_counter() - t0) * 1e3
    return compiled, cstats


class CarriedStepFn:
    """AOT-compiled step function with a persistent donated carry — the
    decode-serving analog of the Program path's bf16 param-carry: the
    carry (the paged KV cache) lives on device across steps, every call
    donates it back in, and the compiled executable is keyed per argument
    signature with tier-B disk persistence (``aot_compile_cached``).

    ``key_parts`` is a JSON-able description of everything that affects
    the lowering besides the argument signature (model fingerprint, cache
    geometry, trace flags) — it feeds ``compile_cache.raw_artifact_key``.
    ``warmup()`` compiles eagerly for one signature (the serving
    prewarm); a ``__call__`` on a signature never warmed compiles on the
    spot and counts ``executor_cache_miss_total``, so "zero runtime
    compiles under decode load" stays provable from the same counter the
    Program path uses."""

    def __init__(self, fn, donate_argnums=(0,), key_parts=None, name=None):
        self._jfn = jax.jit(fn, donate_argnums=donate_argnums)
        self._key_parts = key_parts
        # labels the hit/miss counters (fn=<name>) so a serving stack
        # running several step kinds per model — decode, draft rollout,
        # speculative verify — can prove flat misses per kind;
        # counter_total() still sums across the labels, so the
        # zero-runtime-compile asserts stay one prefix sum
        self._name = name
        self._compiled = {}

    @staticmethod
    def _sig(args):
        leaves, tree = jax.tree_util.tree_flatten(args)
        return (str(tree),
                tuple((tuple(x.shape), str(x.dtype))
                      if hasattr(x, "shape") else (None, str(type(x)))
                      for x in leaves))

    def _disk_key(self, sig):
        from . import compile_cache as _cc

        if not _cc.enabled() or self._key_parts is None:
            return None
        try:
            _cc.enable_xla_cache()
            return _cc.raw_artifact_key(
                "carried_step", {"parts": self._key_parts,
                                 "sig": [list(map(str, s)) for s in sig[1]],
                                 "tree": sig[0]})
        except Exception as e:
            logging.warning("carried_step: key derivation failed: %s", e)
            return None

    def warmup(self, *args):
        """Eager-compile for this signature; {"source", "compile_ms",
        "key"}.  Memory hits are free (idempotent prewarm)."""
        sig = self._sig(args)
        if sig in self._compiled:
            return {"source": "memory", "compile_ms": 0.0, "key": None}
        disk_key = self._disk_key(sig)
        compiled, cstats = aot_compile_cached(
            self._jfn, args, disk_key, meta={"kind": "carried_step"})
        self._compiled[sig] = compiled if compiled is not None \
            else self._jfn
        if _telemetry.enabled():
            labels = {"fn": self._name} if self._name else {}
            _telemetry.inc("executor_cache_miss_total", **labels)
        return {"source": cstats["source"],
                "compile_ms": cstats["compile_ms"], "key": disk_key}

    def __call__(self, *args):
        sig = self._sig(args)
        fn = self._compiled.get(sig)
        if fn is None:
            self.warmup(*args)
            fn = self._compiled[sig]
        elif _telemetry.enabled():
            labels = {"fn": self._name} if self._name else {}
            _telemetry.inc("executor_cache_hit_total", **labels)
            _telemetry.inc("executor_steps_total")
        return fn(*args)


class Executor:
    """Per-place executor with a program cache."""

    def __init__(self, place=None):
        self.place = place if place is not None else CPUPlace()
        self._cache = {}
        self._fuse_attempted = set()

    def reset_device_state(self):
        """Drop every compiled executable and fusion memo.  The elastic
        re-quorum layer (distributed/elastic.py) calls this after
        re-initializing jax.distributed: cached jfns close over the dead
        world's Mesh/devices and must never run again — the next run()
        recompiles against the new backend."""
        self._cache.clear()
        self._fuse_attempted = set()

    def snapshot_state(self, program, predicate=None):
        """Host-copy snapshot of the program's persistable scope state:
        one D2H device_get per tensor, returning {name: np.ndarray} with
        arrays the caller owns (copy=True — later steps can mutate scope
        tensors without corrupting an in-flight background checkpoint
        write).  This is the only step-path cost of an async
        CheckpointManager.save; serialization/crc/rename happen off-thread
        against this dict."""
        if predicate is None:
            predicate = lambda v: v.persistable and not v.is_data  # noqa: E731
        scope = global_scope()
        t0 = time.perf_counter()
        with _tracing.span("executor.snapshot"):
            out = {}
            for var in program.list_vars():
                if not predicate(var):
                    continue
                sv = scope.find_var(var.name)
                if sv is None or not sv.get_tensor()._is_initialized():
                    continue
                out[var.name] = np.array(sv.get_tensor().numpy(), copy=True)
        if _telemetry.enabled():
            _telemetry.observe("executor_snapshot_ms",
                               (time.perf_counter() - t0) * 1e3)
        return out

    def close(self):
        """Release cached executables and notify pservers this trainer is
        done (reference Executor::Close -> SendComplete, executor.cc:110)."""
        for comm in getattr(self, "_ps_comms", []):
            comm.complete()
        self._ps_comms = []
        self._cache.clear()
        # end-of-run telemetry snapshot (metrics.json/.prom under
        # FLAGS_telemetry_dir; atexit covers executors never closed)
        _telemetry.maybe_dump()

    # -- main entry ----------------------------------------------------------
    def run(
        self,
        program=None,
        feed=None,
        fetch_list=None,
        feed_var_name="feed",
        fetch_var_name="fetch",
        scope=None,
        return_numpy=True,
        use_program_cache=True,
    ):
        from ..compiler import CompiledProgram

        scope = scope if scope is not None else global_scope()
        fetch_list = fetch_list or []
        fetch_names = [_fetch_name(f) for f in fetch_list]

        # unwrap CompiledProgram FIRST so PS metadata on the inner program
        # is seen (a wrapped PS trainer must still send/recv)
        mesh = None
        data_axis = None
        if isinstance(program, CompiledProgram):
            compiled = program
            program = compiled._program
            mesh = compiled._mesh()
            data_axis = compiled._data_axis

        # parameter-server program: block in the server loop
        # (listen_and_serv_op.cc:110 RunSyncLoop analog)
        if program is not None and getattr(program, "_ps_server", None):
            from ..distributed.ps import run_pserver

            return run_pserver(self, program, scope)

        # PS trainer program: ensure comms + initial param pull, and fetch
        # this step's grads for the send/recv exchange after the run
        ps_meta = getattr(program, "_ps_trainer", None) if program else None
        ps_grad_names = []
        if ps_meta is not None:
            if getattr(scope, "_ps_comm", None) is None:
                from ..distributed.ps import TrainerPSComm

                scope._ps_comm = TrainerPSComm(ps_meta)
                scope._ps_comm.pull_initial_params(scope)
                if not hasattr(self, "_ps_comms"):
                    self._ps_comms = []
                self._ps_comms.append(scope._ps_comm)
            if not ps_meta.get("geo"):
                # geo-SGD trains locally (no grad sends) — only the
                # grad-shipping modes need the per-step grad fetch
                ps_grad_names = [g for g in ps_meta["param_grad"].values()
                                 if g not in fetch_names]
                fetch_names = fetch_names + ps_grad_names

        if program is None:
            program = default_main_program()

        if not feed:
            # program-driven input: a started non-iterable DataLoader
            # attached to this program supplies the batch (the reference's
            # py_reader `read` op path; raises core.EOFException at end)
            for loader in program._attached_loaders:
                if loader._started:
                    feed = loader._next_feed()
                    break
        feed = feed or {}

        feed_arrays = {}
        block = program.global_block()
        for name, value in feed.items():
            if isinstance(value, jax.Array):
                # device-resident feed: never pull back to host for dtype
                # coercion (x64-disabled JAX can't hold int64 anyway)
                feed_arrays[name] = value
                continue
            arr = np.asarray(value)
            v = block._find_var_recursive(name)
            if v is not None and v.dtype is not None and arr.dtype != dtype_to_np(v.dtype):
                arr = np.asarray(arr, dtype=dtype_to_np(v.dtype))
            feed_arrays[name] = arr

        # fuse BEFORE the cache key: the pass bumps the program version,
        # so running it inside _compile would orphan the cache entry and
        # force a full recompile on the next step
        self._maybe_fuse_optimizers(program, program.global_block(),
                                    list(feed_arrays), fetch_names)
        # trace-affecting flags must key the cache: a cached executable
        # baked the flag value it was traced under, and flipping the flag
        # without a cache miss would silently keep the old lowering
        from .. import flags as _flags

        trace_flags = tuple(sorted(_flags.get_flags(
            ["FLAGS_use_pallas_layer_norm", "FLAGS_check_nan_inf",
             "FLAGS_bn_stat_subsample",
             "FLAGS_fused_small_attention",
             "FLAGS_layout_match_params",
             "FLAGS_use_pallas_conv_block",
             "FLAGS_use_pallas_fused_opt",
             "FLAGS_use_pallas_embedding_bag",
             "FLAGS_deterministic_reduction"]).items()))
        # mesh keyed by content, not id(): a GC'd Mesh's successor can alias
        # the address exactly like the Program case above
        mesh_key = None
        if mesh is not None:
            mesh_key = (tuple(mesh.shape.items()),
                        tuple(d.id for d in mesh.devices.flat))
        key = (
            program._uid,
            program.version,
            tuple(sorted((n, a.shape, str(a.dtype)) for n, a in feed_arrays.items())),
            tuple(fetch_names),
            mesh_key,
            trace_flags,
        )
        tel = _telemetry.enabled()
        entry = self._cache.get(key) if use_program_cache else None
        cache_hit = entry is not None
        build = None
        build_s = 0.0
        if entry is None:
            # static verifier runs only on the compile path (cache misses),
            # memoized per program signature inside check_before_compile —
            # steady-state steps never pay for it, and FLAGS_static_check=
            # off is a single flag read
            from .analysis import check_before_compile
            from . import compile_cache as _cc

            _cc.enable_xla_cache()
            check_before_compile(program, list(feed_arrays), fetch_names,
                                 scope=scope,
                                 feed_shapes={n: tuple(a.shape)
                                              for n, a in
                                              feed_arrays.items()})
            t_build = time.perf_counter()
            build = self._build(program, list(feed_arrays), fetch_names,
                                mesh, data_axis)
            build_s = time.perf_counter() - t_build
            plan = build.plan
            if build.mesh is not None and mesh is None:
                mesh = build.mesh
                data_axis = build.data_axis
        else:
            plan = entry.plan
            if entry.mesh is not None and mesh is None:
                mesh = entry.mesh
                data_axis = entry.data_axis

        # gather params from scope
        params_ro, params_rw = {}, {}
        for n in plan.ro_names:
            params_ro[n] = self._scope_value(scope, n, block)
        for n in plan.rw_names:
            params_rw[n] = self._scope_value(scope, n, block)
        params_carry, carry_hits, carry_converts = self._gather_carry(
            scope, plan, block)
        # host->device transfer volume: numpy feeds cross the PCIe/tunnel
        # boundary; device-resident jax.Arrays are already there
        feed_bytes = 0
        if tel:
            feed_bytes = sum(int(a.nbytes) for a in feed_arrays.values()
                             if not isinstance(a, jax.Array))

        # deterministic functional PRNG: (program seed, per-scope step
        # counter).  Locked: pipeline section workers run concurrently
        # against one scope and must never draw the same key.
        seed = program.random_seed or 0
        with _RNG_COUNTER_LOCK:
            counter = scope._rng_counter
            scope._rng_counter = counter + 1
        # key derivation happens inside the compiled fn (kept out of the
        # eager path: per-op dispatch through the device tunnel is slow)
        rng = np.asarray([seed & 0xFFFFFFFF, counter & 0xFFFFFFFF],
                         dtype=np.uint32)

        if mesh is not None:
            feed_arrays = self._shard_feeds(feed_arrays, mesh, data_axis)
            params_ro = self._shard_params(params_ro, mesh, block)
            params_rw = self._shard_params(params_rw, mesh, block)

        dev = self._jax_device(mesh)
        cstats = None
        if entry is None:
            # eager AOT compile (or tier-B cache restore) with the real
            # first-step inputs — shapes, dtypes AND shardings are exactly
            # what every subsequent call passes, and compile_ms stops being
            # conflated with the first step's wall time
            disk_key = self._disk_key(program, plan, feed_arrays,
                                      fetch_names, trace_flags, mesh, dev)
            entry, cstats = self._finalize_compile(
                build, feed_arrays, params_ro, params_rw, params_carry,
                rng, disk_key, dev)
            if use_program_cache:
                self._cache[key] = entry
        ctx = jax.default_device(dev) if dev is not None else contextlib.nullcontext()
        from ..profiler import RecordEvent

        from ..flags import flag as _trace_flag

        if _trace_flag("hbm_audit"):
            from .memory_audit import maybe_audit

            report = maybe_audit(entry, feed_arrays, params_ro, params_rw,
                                 params_carry, rng)
            if report is not None:
                # fold the HBM report into the telemetry dump so one
                # metrics.json answers both "how slow" and "how big"
                _telemetry.set_info("memory_audit", report)

        t_step = time.perf_counter() if tel else 0.0
        try:
            # nests under whatever span is active on this thread — the
            # serving dispatcher's serving.execute, or a training loop's
            # root — so cross-process traces reach down to the step
            with _tracing.span("executor.step", step=int(counter),
                               cache_hit=cache_hit):
                with ctx, RecordEvent("Executor::Run"):
                    fetches, updated, updated_carry = entry.jfn(
                        feed_arrays, params_ro, params_rw, params_carry,
                        rng)
        except Exception:
            if params_carry:
                # the carry inputs were donated: a failed call may have
                # consumed them, so drop the cache (next run reconverts
                # from the still-live f32 masters)
                cache = scope.__dict__.get("_layout_carry_cache") or {}
                for n in params_carry:
                    cache.pop(n, None)
            if tel:
                _telemetry.inc("executor_step_errors_total")
                _telemetry.event("step_error", step=int(counter))
            raise

        if tel:
            step_ms = (time.perf_counter() - t_step) * 1e3
            fetch_bytes = sum(int(getattr(f, "nbytes", 0)) for f in fetches)
            no_donate = getattr(program, "_no_donate", False)
            if cache_hit:
                compile_ms = None
            elif cstats is not None and cstats["source"] != "fallback":
                # eager AOT path: plan build + trace/lower + XLA compile
                # (or tier-B deserialize) — measured apart from the step
                compile_ms = build_s * 1e3 + cstats["compile_ms"]
            else:
                # lazy fallback: jit compiles inside the first call, so the
                # pre-PR conflation is the honest number
                compile_ms = build_s * 1e3 + step_ms
            _telemetry.record_step(
                step_ms, cache_hit,
                compile_ms=compile_ms,
                donated=0 if no_donate else
                len(params_rw) + len(params_carry),
                feed_bytes=feed_bytes, fetch_bytes=fetch_bytes,
                carry_hits=carry_hits, carry_converts=carry_converts)
            cmeta = getattr(program, "_collective_meta", None)
            if cmeta and cmeta.get("wire_bytes_per_step"):
                # analytic bytes-on-ICI for the step's gradient exchange
                # (stamped by the collective transpiler; see
                # transpiler/collective.py _wire_bytes)
                wire = float(cmeta["wire_bytes_per_step"])
                _telemetry.inc("collective_wire_bytes_total", wire)
                _telemetry.set_gauge("collective_wire_bytes_per_step", wire)
        from ..profiler import mark_instant

        mark_instant("step", args={"step": int(counter)})
        _tracing.instant("step", step=int(counter))

        for n, val in updated.items():
            scope.var(n).set(val)
        if updated_carry:
            # refresh the carry cache: pair each bf16 copy with the scope
            # object it mirrors so staleness is caught by identity (an
            # external scope.set — checkpoint restore — forces reconvert)
            cache = scope.__dict__.setdefault("_layout_carry_cache", {})
            for n, bf in updated_carry.items():
                if n in updated:
                    cache[n] = (scope.var(n).get_tensor().get(), bf)
                elif n in cache:
                    cache[n] = (cache[n][0], bf)
                else:
                    cache[n] = (None, bf)

        from ..flags import flag as _flag

        if _flag("check_nan_inf"):
            # reference FLAGS_check_nan_inf (operator.cc:947): scan outputs;
            # block compilation means we check fetches + updated state vars
            for name, val in list(zip(fetch_names, fetches)) + list(
                    updated.items()):
                arr = np.asarray(val)
                if np.issubdtype(arr.dtype, np.floating) and not np.isfinite(
                        arr).all():
                    raise RuntimeError(
                        "Operator output contains NaN/Inf: variable %r "
                        "(FLAGS_check_nan_inf)" % name)

        if ps_meta is not None:
            # send grads -> barrier -> pull params (the transpiler-
            # rewritten send/recv op sequence, executed by the runtime so
            # the compiled step stays pure).  Taken from the FULL fetch
            # list: a grad the user fetches themselves is still a grad.
            all_grads = set(ps_meta["param_grad"].values())
            grad_vals = {
                name: np.asarray(v)
                for name, v in zip(fetch_names, fetches)
                if name in all_grads
            }
            scope._ps_comm.step(scope, grad_vals)
            n_user = len(fetches) - len(ps_grad_names)
            fetches = fetches[:n_user]

        if return_numpy:
            return [as_numpy(f) for f in fetches]
        return list(fetches)

    # -- internals -----------------------------------------------------------
    def _jax_device(self, mesh):
        if mesh is not None:
            return None
        try:
            return self.place.jax_device()
        except Exception:
            return None

    def _scope_value(self, scope, name, block):
        var = scope.find_var(name)
        if var is None or not var.get_tensor()._is_initialized():
            raise RuntimeError(
                "variable %r is not initialized in scope — run the startup "
                "program first (fluid.Executor.run(fluid.default_startup_program()))"
                % name
            )
        val = var.get_tensor().get()
        v = block._find_var_recursive(name)
        if (
            v is not None
            and v.dtype is not None
            and not isinstance(val, jax.Array)
        ):
            val = np.asarray(val, dtype=dtype_to_np(v.dtype))
        return val

    def _gather_carry(self, scope, plan, block):
        """bf16 layout-matched copies for plan.carry_names, cached per scope
        and validated against the f32 master by OBJECT IDENTITY: as long as
        the scope still holds the exact array the copy was derived from
        (i.e. only the compiled step has updated it), the cached bf16 array
        is current; any external scope.set (checkpoint restore, manual
        assignment) breaks identity and forces a fresh convert.

        Returns (carry dict, cache hits, fresh converts) — the counts feed
        the telemetry step record."""
        carry_names = getattr(plan, "carry_names", None)
        if not carry_names:
            return {}, 0, 0
        cache = scope.__dict__.setdefault("_layout_carry_cache", {})
        out = {}
        hits = converts = 0
        for n in carry_names:
            master = self._scope_value(scope, n, block)
            ent = cache.get(n)
            if ent is not None and ent[0] is master:
                out[n] = ent[1]
                hits += 1
                continue
            bf = jnp.asarray(master).astype(jnp.bfloat16)
            cache[n] = (master, bf)
            out[n] = bf
            converts += 1
        return out, hits, converts

    def _build(self, program, feed_names, fetch_names, mesh, data_axis,
               devices=None):
        """Stage 1 of a compile: BlockPlan + raw callable + jit params.
        No tracing happens here — run()/warmup() gather and shard the real
        inputs first, then _finalize_compile traces with them.  ``devices``
        overrides the SPMD mesh's device list (elastic standby pre-compiles
        a smaller world over a device prefix of the current backend)."""
        from .lowering import build_spmd_block_fn, has_collective_ops

        from .. import flags as _flags

        block = program.global_block()
        no_donate = getattr(program, '_no_donate', False)
        spmd = mesh is None and has_collective_ops(block)
        # layout-matched param carry: single-process, single-device-program,
        # donated programs only — carry buffers alias across steps via
        # donation, and the SPMD/mesh paths spec params per-name
        allow_carry = (
            bool(_flags.flag("layout_match_params"))
            and mesh is None and not spmd and not no_donate
            and jax.process_count() == 1
        )
        plan = BlockPlan(block, feed_names, fetch_names,
                         allow_carry=allow_carry)
        # pipeline sections share param buffers across concurrently
        # running executors — donation would let one section delete an
        # array another still reads (real on TPU; CPU ignores donation).
        # The bf16 carry dict (arg 3) is donated alongside params_rw so a
        # read-only carry aliases its output and survives step to step.
        donate = () if no_donate else (2, 3)
        if spmd:
            # fleet/transpiler collective path: program-level c_* ops ->
            # manual SPMD over all local devices (reference: one process
            # per GPU + NCCL ring; here: shard_map over the mesh axis).
            # Runs even on 1 device (psum over a size-1 axis is identity)
            # so the transpiler's 1/nranks loss-grad scale stays paired
            # with a real — if degenerate — allreduce.
            from jax.sharding import Mesh

            devs = list(devices) if devices is not None else jax.devices()
            mesh = Mesh(np.array(devs), ("data",))
            sfn = build_spmd_block_fn(plan, mesh, axis="data")

            def fn5(feeds, params_ro, params_rw, params_carry, key,
                    _sfn=sfn):
                fetches, updated = _sfn(feeds, params_ro, params_rw, key)
                return fetches, updated, {}

            return _BuildResult(plan, _with_seed_counter(fn5), donate,
                                mesh, "data")
        fn = _with_seed_counter(build_block_fn(plan, mesh=mesh))
        if mesh is None:
            return _BuildResult(plan, fn, donate)
        from jax.sharding import NamedSharding, PartitionSpec as P

        replicated = NamedSharding(mesh, P())
        out_shardings = ([replicated] * len(fetch_names),
                         {n: self._param_sharding(mesh, block, n)
                          for n in plan.persist_written},
                         {})
        return _BuildResult(plan, fn, donate, out_shardings=out_shardings)

    def _disk_key(self, program, plan, feed_arrays, fetch_names, trace_flags,
                  mesh, dev):
        """Tier-B content key for this executable, or None when the disk
        cache is off (or the key can't be derived — never fatal)."""
        from . import compile_cache as _cc

        if not _cc.enabled():
            return None
        try:
            feed_sig = sorted((n, tuple(a.shape), str(a.dtype))
                              for n, a in feed_arrays.items())
            mesh_sig = None
            if mesh is not None:
                # axis names/sizes only: device ids are reassigned when the
                # backend re-initializes (elastic), and must not split keys
                mesh_sig = [[str(k), int(v)] for k, v in mesh.shape.items()]
            extra = {
                "donate": not getattr(program, "_no_donate", False),
                "dev": str(dev) if dev is not None else None,
                "carry": sorted(getattr(plan, "carry_names", None) or ()),
            }
            return _cc.artifact_key(program, feed_sig, fetch_names,
                                    trace_flags, mesh_sig=mesh_sig,
                                    extra=extra)
        except Exception as e:
            logging.warning("compile_cache: key derivation failed: %s", e)
            return None

    def _finalize_compile(self, build, feeds, params_ro, params_rw,
                          params_carry, rng, disk_key, dev):
        """Stage 2: produce the executable for already-gathered inputs.
        Order: tier-B disk restore -> eager jit(...).lower(...).compile()
        (serialized back to disk) -> lazy jit fallback if either explodes.
        Returns (entry, {"source", "compile_ms"})."""
        if build.out_shardings is not None:
            jfn = jax.jit(build.fn, donate_argnums=build.donate,
                          out_shardings=build.out_shardings)
        else:
            jfn = jax.jit(build.fn, donate_argnums=build.donate)
        compiled, cstats = aot_compile_cached(
            jfn, (feeds, params_ro, params_rw, params_carry, rng),
            disk_key, dev,
            meta={"fetch": list(build.plan.fetch_names),
                  "n_feeds": len(feeds)})
        entry = _CompiledPlan(
            build.plan, compiled if compiled is not None else jfn,
            build.mesh, build.data_axis, jit_fn=jfn)
        return entry, cstats

    def warmup(self, program=None, feed_specs=None, fetch_list=None,
               scope=None, devices=None):
        """Pre-compile `program` for the given feed signature WITHOUT
        running a step: populates the in-memory executable cache and, when
        FLAGS_compile_cache_dir is set, the on-disk tier-B cache (elastic
        standby / serving-bucket prewarm path).

        ``feed_specs`` maps feed name -> concrete array OR (shape, dtype).
        Parameters must already be initialized in ``scope`` (run the
        startup program first).  ``devices`` overrides the SPMD mesh's
        device list (used by elastic standby to compile a smaller world);
        entries built with an override are only written to disk, never
        into the in-memory cache (their mesh is not this world's).

        Returns {"source": "memory"|"disk"|"compiled"|"fallback",
        "compile_ms": float, "key": tier-B key or None}."""
        from ..compiler import CompiledProgram

        scope = scope if scope is not None else global_scope()
        fetch_list = fetch_list or []
        fetch_names = [_fetch_name(f) for f in fetch_list]
        mesh = None
        data_axis = None
        if isinstance(program, CompiledProgram):
            compiled_prog = program
            program = compiled_prog._program
            mesh = compiled_prog._mesh()
            data_axis = compiled_prog._data_axis
        if program is None:
            program = default_main_program()
        block = program.global_block()
        feed_arrays = {}
        for name, spec in (feed_specs or {}).items():
            if isinstance(spec, (tuple, list)) and len(spec) == 2 and \
                    isinstance(spec[0], (tuple, list)):
                shape, dt = spec
                if dt is None:
                    v = block._find_var_recursive(name)
                    dt = dtype_to_np(v.dtype) if v is not None else np.float32
                feed_arrays[name] = np.zeros(tuple(shape), dtype=np.dtype(dt))
            elif isinstance(spec, jax.Array):
                feed_arrays[name] = spec
            else:
                arr = np.asarray(spec)
                v = block._find_var_recursive(name)
                if v is not None and v.dtype is not None and \
                        arr.dtype != dtype_to_np(v.dtype):
                    arr = np.asarray(arr, dtype=dtype_to_np(v.dtype))
                feed_arrays[name] = arr

        self._maybe_fuse_optimizers(program, block, list(feed_arrays),
                                    fetch_names)
        from .. import flags as _flags

        trace_flags = tuple(sorted(_flags.get_flags(
            ["FLAGS_use_pallas_layer_norm", "FLAGS_check_nan_inf",
             "FLAGS_bn_stat_subsample",
             "FLAGS_fused_small_attention",
             "FLAGS_layout_match_params",
             "FLAGS_use_pallas_conv_block",
             "FLAGS_use_pallas_fused_opt",
             "FLAGS_use_pallas_embedding_bag",
             "FLAGS_deterministic_reduction"]).items()))
        mesh_key = None
        if mesh is not None:
            mesh_key = (tuple(mesh.shape.items()),
                        tuple(d.id for d in mesh.devices.flat))
        key = (
            program._uid,
            program.version,
            tuple(sorted((n, a.shape, str(a.dtype))
                         for n, a in feed_arrays.items())),
            tuple(fetch_names),
            mesh_key,
            trace_flags,
        )
        if devices is None and key in self._cache:
            return {"source": "memory", "compile_ms": 0.0, "key": None}
        from .analysis import check_before_compile
        from . import compile_cache as _cc

        _cc.enable_xla_cache()
        check_before_compile(program, list(feed_arrays), fetch_names,
                             scope=scope,
                             feed_shapes={n: tuple(a.shape)
                                          for n, a in feed_arrays.items()})
        t0 = time.perf_counter()
        # the warmup span stacks over the whole build+compile so the
        # cache_restore/compile child spans nest under it
        with _tracing.span("executor.warmup") as wspan:
            build = self._build(program, list(feed_arrays), fetch_names,
                                mesh, data_axis, devices=devices)
            plan = build.plan
            if build.mesh is not None and mesh is None:
                mesh = build.mesh
                data_axis = build.data_axis
            params_ro, params_rw = {}, {}
            for n in plan.ro_names:
                params_ro[n] = self._scope_value(scope, n, block)
            for n in plan.rw_names:
                params_rw[n] = self._scope_value(scope, n, block)
            params_carry, _h, _c = self._gather_carry(scope, plan, block)
            rng = np.asarray([(program.random_seed or 0) & 0xFFFFFFFF, 0],
                             dtype=np.uint32)
            if mesh is not None:
                feed_arrays = self._shard_feeds(feed_arrays, mesh,
                                                data_axis)
                params_ro = self._shard_params(params_ro, mesh, block)
                params_rw = self._shard_params(params_rw, mesh, block)
            dev = self._jax_device(mesh)
            disk_key = self._disk_key(program, plan, feed_arrays,
                                      fetch_names, trace_flags, mesh, dev)
            entry, cstats = self._finalize_compile(
                build, feed_arrays, params_ro, params_rw, params_carry,
                rng, disk_key, dev)
            wspan.annotate(source=cstats["source"])
        if devices is None:
            self._cache[key] = entry
        ms = (time.perf_counter() - t0) * 1e3
        _telemetry.inc("executor_warmup_total")
        _telemetry.event("warmup", source=cstats["source"],
                         compile_ms=round(ms, 3))
        return {"source": cstats["source"], "compile_ms": ms,
                "key": disk_key}

    def _maybe_fuse_optimizers(self, program, block, feed_names,
                               fetch_names):
        """Horizontal optimizer fusion before lowering (reference
        BuildStrategy fuse_all_optimizer_ops): hundreds of tiny
        per-parameter update fusions each pay a fixed launch cost — ~46 ms
        of a 211 ms ResNet-50 step in the round-3 profile.  Attempted once
        per (program, version): with the rank-capped default most groups
        stay unfused, so without memoization every step would pay a full
        pass scan that is guaranteed to change nothing."""
        key = (program._uid, program.version)
        if key in self._fuse_attempted:
            return
        self._fuse_attempted.add(key)
        from .. import flags as _flags

        f = _flags.get_flags(["FLAGS_fuse_optimizer_ops",
                              "FLAGS_deterministic_reduction"])
        if not f["FLAGS_fuse_optimizer_ops"]:
            return
        if f["FLAGS_deterministic_reduction"]:
            # the fused flat-buffer update lets XLA regroup FMAs with the
            # surrounding HLO, so the same update computes different last
            # ulps in different programs — incompatible with the bitwise
            # cross-program parity deterministic mode promises
            return
        n_opt = sum(op.type in ("sgd", "momentum", "adam")
                    for op in block.ops)
        if n_opt < 4:
            return
        from .. import ir as _ir

        _ir.apply_pass("fuse_optimizer_ops_pass", program, None,
                       protected=set(feed_names) | set(fetch_names))
        # the pass bumps the version when it fuses; mark the new version
        # attempted too so the next run doesn't rescan
        self._fuse_attempted.add((program._uid, program.version))

    def _param_sharding(self, mesh, block, name):
        from jax.sharding import NamedSharding, PartitionSpec as P

        v = block._find_var_recursive(name)
        if v is not None and v.sharding:
            # drop axis names the mesh doesn't have (e.g. a table annotated
            # ("model", None) running on a data-only mesh stays replicated)
            spec = tuple(a if (a is None or a in mesh.axis_names) else None
                         for a in v.sharding)
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    def _shard_params(self, params, mesh, block):
        multi = jax.process_count() > 1
        out = {}
        for n, v in params.items():
            sh = self._param_sharding(mesh, block, n)
            if multi:
                if isinstance(v, jax.Array) and v.sharding.device_set == \
                        sh.device_set:
                    out[n] = jax.device_put(v, sh)
                    continue
                # multi-process (nccl2-mode analog): every process holds
                # the full (identically-seeded) value — locally-committed
                # arrays (e.g. from a single-device startup run) included;
                # assemble the global array from process-local data
                out[n] = jax.make_array_from_process_local_data(
                    sh, np.asarray(v))
            else:
                out[n] = jax.device_put(v, sh)
        return out

    def _shard_feeds(self, feed_arrays, mesh, data_axis):
        from jax.sharding import NamedSharding, PartitionSpec as P

        multi = jax.process_count() > 1
        out = {}
        for n, a in feed_arrays.items():
            batch_ok = (a.ndim >= 1 and data_axis
                        and a.shape[0] % mesh.shape[data_axis] == 0)
            if multi:
                if isinstance(a, jax.Array) and not a.is_fully_addressable:
                    out[n] = a  # already a correctly-assembled global array
                    continue
                # reference nccl2-mode protocol: each trainer process feeds
                # its LOCAL batch shard (numpy or a locally-committed jax
                # array, e.g. from the double-buffered DataLoader); the
                # global batch is the concatenation over processes
                local = np.asarray(a)
                local_dev = max(
                    len([d for d in mesh.devices.flat
                         if d.process_index == jax.process_index()]), 1)
                if local.ndim >= 1 and data_axis \
                        and local.shape[0] % local_dev == 0:
                    spec = P(data_axis, *([None] * (local.ndim - 1)))
                elif local.ndim >= 1 and data_axis and local.shape[0] > 1:
                    # Reference contract (feed_and_split_tensor_into_local_
                    # scopes): every multi-device feed is a batch split
                    # across devices, and an indivisible batch is an error.
                    # Replicating here instead would silently diverge
                    # per-device values when trainers feed distinct shards.
                    # Genuinely replicated constants should be shape
                    # [1, ...] or pre-committed replicated jax.Arrays (the
                    # is_fully_addressable path above).
                    raise ValueError(
                        "multi-process feed '%s': local leading dim %d is "
                        "not divisible by the %d local device(s); pad the "
                        "batch, or feed replicated constants with leading "
                        "dim 1 / as pre-committed jax.Arrays"
                        % (n, local.shape[0], local_dev))
                else:
                    # leading dim 1 (or scalar): broadcast-like feed (lr,
                    # beta_pow) — identical across processes, replicate
                    spec = P()
                out[n] = jax.make_array_from_process_local_data(
                    NamedSharding(mesh, spec), local)
                continue
            spec = (P(data_axis, *([None] * (a.ndim - 1)))
                    if batch_ok else P())
            out[n] = jax.device_put(a, NamedSharding(mesh, spec))
        return out

    # -- dataset/trainer entry points (C++ trainer path analog) --------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           checkpoint_manager=None):
        from ..trainer import train_from_dataset

        return train_from_dataset(self, program, dataset, scope, thread,
                                  fetch_list, fetch_info, print_period,
                                  checkpoint_manager=checkpoint_manager)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        from ..trainer import infer_from_dataset

        return infer_from_dataset(self, program, dataset, scope, thread,
                                  fetch_list, fetch_info, print_period)
