"""Op registry: each op type has a lowering to JAX/XLA, shape inference, and a
grad-op maker.

TPU-native analog of the reference's ``REGISTER_OPERATOR`` /
``OpInfoMap`` (paddle/fluid/framework/op_registry.h:68,199): instead of
per-device kernel functors, an op registers a **lowering** — a pure function
built from jax.numpy / lax that the executor traces into one XLA computation
per block.  Gradients come either from a hand-written grad op (parity with the
reference's grad-op-desc makers, grad_op_desc_maker.h) or from a default
maker that differentiates the forward lowering with ``jax.vjp`` inside the
same trace (XLA CSE merges the recomputed forward).
"""

import functools

import numpy as np

__all__ = [
    "OpDef",
    "register_op",
    "get_op_def",
    "has_op_def",
    "all_op_types",
    "GradOpDesc",
]

_OP_REGISTRY = {}


class GradOpDesc:
    """Description of one grad op to append (analog of OpDesc from a C++
    grad-op maker)."""

    def __init__(self, type, inputs, outputs, attrs=None):
        self.type = type
        self.inputs = inputs
        self.outputs = outputs
        self.attrs = dict(attrs or {})


class OpDef:
    """Registered metadata + behavior for one op type."""

    def __init__(
        self,
        type,
        inputs=(),
        outputs=(),
        attrs=None,
        lower=None,
        infer_shape=None,
        grad_maker="auto",
        no_grad_inputs=(),
        optional_inputs=(),
        duplicable_inputs=(),
        duplicable_outputs=(),
        stateful=False,
        n_rng=0,
    ):
        self.type = type
        self.input_slots = tuple(inputs)
        self.output_slots = tuple(outputs)
        self.default_attrs = dict(attrs or {})
        self.lower = lower
        self.infer_shape = infer_shape
        # grad_maker: "auto" (vjp-based default), None (no gradient), or a
        # callable op -> list[GradOpDesc]
        self.grad_maker = grad_maker
        self.no_grad_inputs = frozenset(no_grad_inputs)
        self.optional_inputs = frozenset(optional_inputs)
        self.duplicable_inputs = frozenset(duplicable_inputs)
        self.duplicable_outputs = frozenset(duplicable_outputs)
        # def-level consistency: every slot-qualifier set must name real
        # slots — a typo here (or an output slot listed as an optional
        # input) is silent metadata rot the per-instance validate() can
        # never catch, because instances only carry slots they use
        ins, outs = set(self.input_slots), set(self.output_slots)
        for label, members, universe in (
            ("no_grad_inputs", self.no_grad_inputs, ins),
            ("optional_inputs", self.optional_inputs, ins),
            ("duplicable_inputs", self.duplicable_inputs, ins),
            ("duplicable_outputs", self.duplicable_outputs, outs),
        ):
            unknown = members - universe
            if unknown:
                raise ValueError(
                    "op %r: %s %s are not declared %s slots (%s)"
                    % (type, label, sorted(unknown),
                       "input" if universe is ins else "output",
                       sorted(universe)))
        self.stateful = stateful
        self.n_rng = n_rng  # number of PRNG keys the lowering consumes
        # optional per-op predicate attrs -> bool: does THIS instance
        # actually consume rng?  (flash_attention only draws when its
        # dropout is active; the recompute planner uses this to keep the
        # dropout-free instances replayable)
        self.rng_when = None

    # -- validation ----------------------------------------------------------
    def validate(self, op):
        for slot in op.inputs:
            if slot not in self.input_slots:
                raise ValueError(
                    "op %s has no input slot %r (has %s)"
                    % (self.type, slot, self.input_slots)
                )
        for slot in op.outputs:
            if slot not in self.output_slots:
                raise ValueError(
                    "op %s has no output slot %r (has %s)"
                    % (self.type, slot, self.output_slots)
                )
        for k, v in self.default_attrs.items():
            op.attrs.setdefault(k, v)

    # -- shape inference -----------------------------------------------------
    def run_infer_shape(self, op, block):
        try:
            if self.infer_shape is not None:
                self.infer_shape(op, block)
            elif self.lower is not None:
                _default_infer_shape(self, op, block)
        except NotImplementedError:
            pass

    # -- gradient ------------------------------------------------------------
    def make_grad_ops(self, op, no_grad_set):
        """Return list[GradOpDesc] for this forward op.

        The default ("auto") maker emits one `<type>_grad` op taking the
        forward inputs, forward outputs, and output grads, producing input
        grads; its lowering replays the forward via jax.vjp.
        """
        if self.grad_maker is None:
            return []
        if callable(self.grad_maker):
            return self.grad_maker(op, no_grad_set)
        # auto
        from ..framework import _grad_var_name

        inputs = {}
        for slot in self.input_slots:
            if op.input(slot):
                inputs[slot] = list(op.input(slot))
        for slot in self.output_slots:
            if op.output(slot):
                inputs["Out@" + slot] = list(op.output(slot))
                # "" holes (outputs the maker declined, e.g. grads of
                # non-float inputs on a grad op) must stay holes, not
                # become the bogus name "@GRAD"
                inputs["GRAD@" + slot] = [
                    _grad_var_name(n) if n else "" for n in op.output(slot)
                ]
        outputs = {}
        block = op.block
        for slot in self.input_slots:
            if slot in self.no_grad_inputs:
                continue
            names = []
            for n in op.input(slot):
                v = block._find_var_recursive(n) if block is not None else None
                is_float = v is None or v.dtype is None or v.dtype.startswith(
                    ("float", "bfloat")
                )
                if n in no_grad_set or not is_float:
                    names.append("")  # hole: no gradient wanted
                else:
                    names.append(_grad_var_name(n))
            if any(names):
                outputs["X@" + slot] = names
        if not outputs:
            return []
        return [
            GradOpDesc(
                self.type + "_grad",
                inputs,
                outputs,
                dict(op.attrs),
            )
        ]


# ---------------------------------------------------------------------------
# Synthesized grad ops: `<type>_grad` differentiates the registered forward
# lowering with jax.vjp inside the same block trace.  The forward replay is
# CSE'd with the real forward by XLA (and is exactly what remat wants).
# ---------------------------------------------------------------------------


def _synthesize_grad_opdef(base):
    import jax
    import jax.numpy as jnp

    in_slots = list(base.input_slots)
    dup_in = set(base.duplicable_inputs)
    opt_in = set(base.optional_inputs)
    for s in base.output_slots:
        in_slots += ["Out@" + s, "GRAD@" + s]
        if s in base.duplicable_outputs:
            dup_in.update(("Out@" + s, "GRAD@" + s))
        opt_in.update(("Out@" + s, "GRAD@" + s))
    out_slots = ["X@" + s for s in base.input_slots]
    dup_out = set("X@" + s for s in base.input_slots if s in base.duplicable_inputs)

    n_in = len(base.input_slots)
    n_out = len(base.output_slots)

    def grad_lower(ctx, *args, **attrs):
        fwd_ins = list(args[:n_in])
        rest = args[n_in:]
        fwd_outs = [rest[2 * i] for i in range(n_out)]
        out_grads = [rest[2 * i + 1] for i in range(n_out)]

        op = ctx.op
        if op is not None and op.type != base.type + "_grad":
            # replayed inside a higher-order (grad-of-grad) lowering: ctx.op
            # is the outer op, whose output slots do not describe this replay
            # — differentiate wrt every float input and let XLA DCE the rest
            op = None
        requested = []
        for i, s in enumerate(base.input_slots):
            if op is not None:
                names = op.output("X@" + s)
                want = bool(names) and any(names)
            else:
                want = True
            x = fwd_ins[i]
            is_float = (
                x is not None
                and not isinstance(x, (list, tuple))
                and jnp.issubdtype(jnp.asarray(x).dtype
                                   if not hasattr(x, "dtype") else x.dtype,
                                   jnp.inexact)
            ) or (
                isinstance(x, (list, tuple)) and x
                and all(jnp.issubdtype(xi.dtype, jnp.inexact) for xi in x)
            )
            requested.append(want and is_float)
        diff_idx = [i for i, r in enumerate(requested) if r]
        if not diff_idx:
            return tuple(None for _ in out_slots)

        def fwd(*diff_vals):
            full = list(fwd_ins)
            for j, i in enumerate(diff_idx):
                full[i] = diff_vals[j]
            out = base.lower(ctx, *full, **attrs)
            return out if isinstance(out, tuple) else (out,)

        primals = [fwd_ins[i] for i in diff_idx]
        outs, vjp_fn = jax.vjp(fwd, *primals)
        cots = []
        for o, g in zip(outs, out_grads):
            if o is None:
                cots.append(None)
            elif g is None:
                cots.append(jax.tree_util.tree_map(jnp.zeros_like, o))
            elif isinstance(o, (list, tuple)):
                cots.append(
                    type(o)(
                        gi if gi is not None else jnp.zeros_like(oi)
                        for oi, gi in zip(o, g)
                    )
                )
            else:
                cots.append(g.astype(o.dtype) if g.dtype != o.dtype else g)
        grads = vjp_fn(tuple(cots))
        result = []
        gi = 0
        for i in range(n_in):
            if i in diff_idx:
                result.append(grads[gi])
                gi += 1
            else:
                result.append(None)
        return tuple(result)

    def grad_infer_shape(op, block):
        # each input grad has the shape/dtype of its forward input
        for s in base.input_slots:
            for fwd_name, gname in zip(op.input(s), op.output("X@" + s)):
                if not gname:
                    continue
                fv = block._find_var_recursive(fwd_name)
                gv = block._find_var_recursive(gname)
                if fv is not None and gv is not None:
                    gv.shape = fv.shape
                    if gv.dtype is None:
                        gv.dtype = fv.dtype

    return OpDef(
        base.type + "_grad",
        inputs=in_slots,
        outputs=out_slots,
        lower=grad_lower,
        infer_shape=grad_infer_shape,
        # grad ops are themselves differentiable (vjp of grad_lower), which
        # is what double-grad rides: <op>_grad_grad is synthesized on demand
        # the same way (reference registers conv2d_grad_grad et al. by hand,
        # conv_op.cc:652)
        grad_maker="auto",
        optional_inputs=opt_in,
        duplicable_inputs=dup_in,
        duplicable_outputs=dup_out,
    )


def register_op(
    type,
    inputs=(),
    outputs=(),
    attrs=None,
    infer_shape=None,
    grad_maker="auto",
    no_grad_inputs=(),
    optional_inputs=(),
    duplicable_inputs=(),
    duplicable_outputs=(),
    stateful=False,
    n_rng=0,
):
    """Decorator registering a lowering function as op `type`.

    The lowering signature is ``lower(ctx, *input_slot_values, **attrs)`` and
    must return a tuple matching ``outputs`` (or a single value for one
    output).  Slot values are lists when the slot is duplicable, otherwise a
    single jax array (or None for absent optional inputs).
    """

    def deco(fn):
        opdef = OpDef(
            type,
            inputs=inputs,
            outputs=outputs,
            attrs=attrs,
            lower=fn,
            infer_shape=infer_shape,
            grad_maker=grad_maker,
            no_grad_inputs=no_grad_inputs,
            optional_inputs=optional_inputs,
            duplicable_inputs=duplicable_inputs,
            duplicable_outputs=duplicable_outputs,
            stateful=stateful,
            n_rng=n_rng,
        )
        if type in _OP_REGISTRY:
            raise ValueError("op %r registered twice" % type)
        _OP_REGISTRY[type] = opdef
        fn.opdef = opdef
        return fn

    return deco


# -- executed-op recording ---------------------------------------------------
# Every op type actually LOWERED for execution (graph run_op + dygraph
# trace_op; shape-inference's abstract evaluation does not count).  The
# op-coverage audit (tests/test_op_coverage.py + conftest sessionfinish)
# reads this so coverage means "a test executed the lowering", not "the op
# name appears somewhere in test text" — a golden replaced by a comment
# containing the op name now fails the audit (round-3 verdict weak #3).
EXECUTED_OP_TYPES = set()


def record_executed(type):
    EXECUTED_OP_TYPES.add(type)


def get_op_def(type):
    _ensure_ops_loaded()
    if type not in _OP_REGISTRY:
        if type.endswith("_grad"):
            # recursive: "X_grad_grad" synthesizes "X_grad" (itself possibly
            # synthesized) on demand
            try:
                base = get_op_def(type[: -len("_grad")])
            except ValueError:
                base = None
            if base is not None and base.grad_maker == "auto":
                _OP_REGISTRY[type] = _synthesize_grad_opdef(base)
                return _OP_REGISTRY[type]
        raise ValueError("unknown op type %r" % type)
    return _OP_REGISTRY[type]


def has_op_def(type):
    _ensure_ops_loaded()
    return type in _OP_REGISTRY


def all_op_types():
    _ensure_ops_loaded()
    return sorted(_OP_REGISTRY)


_ops_loaded = False


def _ensure_ops_loaded():
    global _ops_loaded
    if not _ops_loaded:
        _ops_loaded = True
        from .. import ops  # noqa: F401  (registers everything)


# ---------------------------------------------------------------------------
# Default shape inference via jax.eval_shape with a symbolic batch dim.
# -1 dims in input shapes become one shared symbolic size `b`; output dims
# containing `b` map back to -1.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=1)
def _sym_batch():
    from jax import export

    return export.symbolic_shape("_pb")[0]


def _sym_struct(shape, dtype):
    import jax

    from ..framework import dtype_to_np

    b = _sym_batch()
    dims = tuple(b if d == -1 else d for d in (shape or ()))
    return jax.ShapeDtypeStruct(dims, dtype_to_np(dtype))


def _unsym(dims):
    out = []
    for d in dims:
        if isinstance(d, int):
            out.append(d)
        else:
            out.append(-1)  # symbolic expression involving the batch dim
    return tuple(out)


def _default_infer_shape(opdef, op, block):
    import jax

    from .lowering import LowerCtx

    in_structs = []
    for slot in opdef.input_slots:
        names = op.input(slot)
        if not names:
            in_structs.append([] if slot in opdef.duplicable_inputs else None)
            continue
        structs = []
        for n in names:
            v = block.var(n)
            if v.shape is None or v.dtype is None:
                raise NotImplementedError  # cannot infer
            structs.append(_sym_struct(v.shape, v.dtype))
        if slot in opdef.duplicable_inputs:
            in_structs.append(structs)
        else:
            in_structs.append(structs[0])

    ctx = LowerCtx.abstract(n_rng=opdef.n_rng)

    def fn(*args):
        return opdef.lower(ctx, *args, **_lower_attrs(op.attrs))

    try:
        out = jax.eval_shape(fn, *in_structs)
    except Exception:
        return  # leave declared shapes in place when symbolic eval fails
    if not isinstance(out, (tuple, list)):
        out = (out,)
    flat = []
    for o in out:
        if isinstance(o, (tuple, list)):
            flat.append(list(o))
        else:
            flat.append(o)
    for slot, o in zip(opdef.output_slots, flat):
        names = op.output(slot)
        if not names:
            continue
        items = o if isinstance(o, list) else [o]
        for n, st in zip(names, items):
            if st is None:
                continue
            v = block.var(n)
            v.shape = _unsym(st.shape)
            if v.dtype is None:
                from ..framework import convert_np_dtype_to_dtype_

                v.dtype = convert_np_dtype_to_dtype_(st.dtype)


def _lower_attrs(attrs):
    """Strip framework-internal attrs before passing to a lowering."""
    from ..framework import OP_ROLE_KEY, OP_ROLE_VAR_KEY

    skip = (OP_ROLE_KEY, OP_ROLE_VAR_KEY, "op_namescope", "op_callstack",
            "op_device", "with_quant_attr")
    return {k: v for k, v in attrs.items() if k not in skip}
