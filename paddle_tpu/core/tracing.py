"""Distributed tracing: cross-process request/step spans + flight recorder.

PR 3's telemetry registry (core/telemetry.py) answers *how often / how
slow in aggregate*; this layer answers *where one specific request or
step spent its time* across the client -> server -> engine -> executor
chain and across ranks.  Design:

- **spans**: trace_id (32 hex) / span_id (16 hex) / parent_id, wall-clock
  start (``time.time``) + monotonic duration (``perf_counter``), free-form
  ``attrs``, and ``links`` to other spans (a serving batch span links the
  N request spans it serves).  A thread-local span stack parents nested
  spans automatically; ``activate()`` pushes an existing span so work on
  another thread (the serving dispatcher running the executor) nests
  under it.
- **propagation**: W3C-style ``traceparent`` strings
  (``00-<trace>-<span>-01``) ride the serving codec meta and are stamped
  onto native-RPC SEND frame names (native/rpc.py), so one trace_id spans
  client, replicas, trainers, and pservers.  ``remote_parent()`` opens a
  child span under a context received off the wire.
- **sink**: one JSONL stream per process, ``trace-<pid>.jsonl`` under
  ``FLAGS_telemetry_dir``, size-bounded by ``FLAGS_telemetry_max_bytes``
  (same rotate-and-keep-one guard as telemetry's steps.jsonl).
  tools/trace_view.py merges the per-process files into a single
  Chrome/Perfetto trace.json with cross-process flow arrows.
- **zero-cost off**: ``FLAGS_tracing`` is off by default; every public
  call early-returns after a single flag read, handing back one shared
  inert ``_NULL_SPAN``.  No file, no thread state, no signal handlers.
- **flight recorder**: a bounded ring of the most recent span/instant
  records plus write-through ``note()`` breadcrumbs, dumped to
  ``<telemetry_dir>/flightrec-<pid>.json`` on fault-injection fire,
  unhandled exception, SIGTERM, and atexit — a killed fleet replica
  leaves a postmortem naming its in-flight batch.  Because SIGKILL is
  uncatchable, ``note()`` checkpoints the ring to disk immediately, so
  even a -9'd process leaves its last breadcrumbs behind.
"""

import atexit
import json
import os
import sys
import threading
import time

__all__ = [
    "enabled", "Span", "start_span", "span", "activate", "remote_parent",
    "record_span", "instant", "current_span", "current_context",
    "traceparent", "parse_traceparent", "set_process_name", "note",
    "flight_dump", "flush", "reset",
]

_FLIGHT_CAP = 512          # ring slots kept for the postmortem dump
_WIRE_SEP = "\x1f"         # RPC frame-name separator for the traceparent

_lock = threading.RLock()
_tls = threading.local()   # .stack = [Span, ...] per thread
_sink = [None, None]       # (path, _RotatingFile) — telemetry's sink idiom
_proc_name = [None]        # explicit process track name (serve.py sets it)
_proc_header_written = [False]
_flight = []               # bounded ring of record dicts
_handlers_installed = [False]
_rng_state = [None]        # (pid, counter) — fork-safe id generation


_flags_mod = [None]        # cached flags module (import once, read often)


def _flags():
    m = _flags_mod[0]
    if m is None:
        from .. import flags as m

        _flags_mod[0] = m
    return m


def enabled():
    """One flag read — the telemetry.enabled() guard pattern."""
    return bool(_flags().flag("tracing"))


def _telemetry_dir():
    return _flags().flag("telemetry_dir") or ""


def _new_id(nbytes):
    # os.urandom per id is measurably slow; draw from a per-process
    # counter folded with startup entropy (fork-safe: keyed by pid)
    pid = os.getpid()
    with _lock:
        st = _rng_state[0]
        if st is None or st[0] != pid:
            st = [pid, int.from_bytes(os.urandom(8), "little")]
            _rng_state[0] = st
        st[1] = (st[1] * 6364136223846793005 + 1442695040888963407) \
            % (1 << 64)
        v = st[1]
        if nbytes > 8:
            st[1] = (st[1] * 6364136223846793005 + 1442695040888963407) \
                % (1 << 64)
            v = (v << 64) | st[1]
    h = "%0*x" % (2 * nbytes, v)
    return h[-2 * nbytes:]


# -- W3C-style context --------------------------------------------------------

def parse_traceparent(tp):
    """``00-<32 hex trace>-<16 hex span>-<flags>`` -> (trace_id, span_id)
    or None on anything malformed (a bad header never breaks a request)."""
    if not isinstance(tp, str):
        return None
    parts = tp.split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        int(parts[1], 16), int(parts[2], 16)
    except ValueError:
        return None
    return parts[1], parts[2]


def _format_traceparent(trace_id, span_id):
    return "00-%s-%s-01" % (trace_id, span_id)


def current_span():
    """Innermost active span on this thread, or None."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def current_context():
    """(trace_id, span_id) of the innermost active span, or None."""
    s = current_span()
    return (s.trace_id, s.span_id) if s is not None else None


def traceparent():
    """Serialized context of the current span for the wire, or None."""
    s = current_span()
    return _format_traceparent(s.trace_id, s.span_id) if s else None


# -- spans --------------------------------------------------------------------

class Span:
    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t_wall",
                 "_t0", "dur_ms", "attrs", "links", "thread", "_ended")

    def __init__(self, name, trace_id=None, parent_id=None, **attrs):
        self.name = name
        self.trace_id = trace_id or _new_id(16)
        self.span_id = _new_id(8)
        self.parent_id = parent_id
        self.t_wall = time.time()
        self._t0 = time.perf_counter()
        self.dur_ms = None
        self.attrs = dict(attrs) if attrs else {}
        self.links = []
        self.thread = threading.current_thread().name
        self._ended = False

    def annotate(self, **attrs):
        self.attrs.update(attrs)
        return self

    def link(self, other):
        """Associate another span (same- or cross-trace) without
        parenting it — e.g. a batch span linking the requests it serves."""
        if isinstance(other, Span):
            self.links.append([other.trace_id, other.span_id])
        elif other:  # (trace_id, span_id) tuple
            self.links.append([other[0], other[1]])
        return self

    @property
    def context(self):
        return (self.trace_id, self.span_id)

    @property
    def traceparent(self):
        return _format_traceparent(self.trace_id, self.span_id)

    def end(self):
        if self._ended:
            return self
        self._ended = True
        self.dur_ms = (time.perf_counter() - self._t0) * 1e3
        _emit(self._record())
        return self

    def _record(self):
        rec = {"t": "span", "name": self.name, "tid": self.trace_id,
               "sid": self.span_id, "parent": self.parent_id,
               "ts": int(self.t_wall * 1e6),
               "dur": int((self.dur_ms or 0.0) * 1e3),
               "thr": self.thread}
        if self.attrs:
            rec["attrs"] = self.attrs
        if self.links:
            rec["links"] = self.links
        return rec


class _NullSpan:
    """Inert span handed out when FLAGS_tracing is off: every method is a
    cheap no-op so call sites never branch on the flag themselves."""

    __slots__ = ()
    trace_id = span_id = parent_id = None
    context = None
    traceparent = None
    dur_ms = None

    def annotate(self, **attrs):
        return self

    def link(self, other):
        return self

    def end(self):
        return self


_NULL_SPAN = _NullSpan()


def _push(s):
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(s)


def _pop(s):
    stack = getattr(_tls, "stack", None)
    if stack and stack[-1] is s:
        stack.pop()
    elif stack and s in stack:   # out-of-order end: drop it anyway
        stack.remove(s)


def start_span(name, parent=None, **attrs):
    """Open a span (NOT pushed on the thread stack — pair with .end(), or
    use the ``span()`` context manager for stack semantics).  ``parent``
    may be a Span, a (trace_id, span_id) tuple, or None (defaults to the
    current thread's innermost span; a root span otherwise)."""
    if not enabled():
        return _NULL_SPAN
    if parent is None:
        parent = current_span()
    if isinstance(parent, Span):
        return Span(name, trace_id=parent.trace_id,
                    parent_id=parent.span_id, **attrs)
    if isinstance(parent, _NullSpan):
        parent = None
    if parent:  # (trace_id, span_id)
        return Span(name, trace_id=parent[0], parent_id=parent[1], **attrs)
    return Span(name, **attrs)


class _SpanCtx:
    """Context manager that pushes the span on this thread's stack (so
    nested spans parent under it) and ends it on exit."""

    __slots__ = ("span",)

    def __init__(self, s):
        self.span = s

    def __enter__(self):
        if self.span is not _NULL_SPAN:
            _push(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb):
        if self.span is not _NULL_SPAN:
            if exc is not None:
                self.span.annotate(error=str(exc)[:200])
            _pop(self.span)
            self.span.end()
        return False


def span(name, parent=None, **attrs):
    """``with tracing.span("serving.execute", bucket=4) as s: ...`` —
    opens, stacks, and ends a span around the block."""
    return _SpanCtx(start_span(name, parent=parent, **attrs))


class _ActivateCtx:
    """Push an EXISTING span on this thread's stack without ending it on
    exit — used to parent executor spans under the serving batch span
    that lives on the dispatcher thread."""

    __slots__ = ("span",)

    def __init__(self, s):
        self.span = s

    def __enter__(self):
        if isinstance(self.span, Span):
            _push(self.span)
        return self.span

    def __exit__(self, exc_type, exc, tb):
        if isinstance(self.span, Span):
            _pop(self.span)
        return False


def activate(s):
    return _ActivateCtx(s)


def remote_parent(tp):
    """Context manager: open a span factory under a wire context.  Usage:
    ``with tracing.remote_parent(meta.get("traceparent")): ...`` — spans
    started inside parent under the remote caller's span.  A missing or
    malformed header degrades to local-root semantics."""
    ctx = parse_traceparent(tp) if tp else None
    if not enabled() or ctx is None:
        return _ActivateCtx(_NULL_SPAN)
    anchor = Span.__new__(Span)  # stack anchor only, never emitted
    anchor.name = "<remote>"
    anchor.trace_id, anchor.span_id = ctx
    anchor.parent_id = None
    anchor.t_wall = time.time()
    anchor._t0 = time.perf_counter()
    anchor.dur_ms = None
    anchor.attrs = {}
    anchor.links = []
    anchor.thread = threading.current_thread().name
    anchor._ended = True  # end() can never re-emit it
    return _ActivateCtx(anchor)


def record_span(name, wall_start_s, dur_ms, parent=None, trace_id=None,
                links=None, **attrs):
    """Emit a span RETROACTIVELY from measured timestamps (the elastic
    re-quorum phases are measured as perf_counter deltas first, then laid
    out as a span tree).  ``links`` associates other spans without
    parenting them — each entry a Span or (trace_id, span_id) tuple, e.g.
    the elastic restore phase linking the checkpoint.restore span that
    served it.  Returns the span (already ended)."""
    if not enabled():
        return _NULL_SPAN
    if isinstance(parent, Span):
        trace_id, parent_id = parent.trace_id, parent.span_id
    elif isinstance(parent, (tuple, list)) and len(parent) == 2:
        trace_id, parent_id = parent
    else:
        parent_id = None
    s = Span.__new__(Span)
    s.name = name
    s.trace_id = trace_id or _new_id(16)
    s.span_id = _new_id(8)
    s.parent_id = parent_id
    s.t_wall = float(wall_start_s)
    s._t0 = None
    s.dur_ms = float(dur_ms)
    s.attrs = dict(attrs) if attrs else {}
    s.links = []
    for other in (links or ()):
        if other is not None and not isinstance(other, _NullSpan):
            s.link(other)
    s.thread = threading.current_thread().name
    s._ended = True
    _emit(s._record())
    return s


def instant(name, **attrs):
    """Point-in-time marker on the current trace (folds the profiler's
    mark_instant semantics into the tracing stream)."""
    if not enabled():
        return
    rec = {"t": "inst", "name": name, "ts": int(time.time() * 1e6),
           "thr": threading.current_thread().name}
    ctx = current_context()
    if ctx is not None:
        rec["tid"], rec["sid"] = ctx
    if attrs:
        rec["attrs"] = attrs
    _emit(rec)


def set_process_name(name):
    """Name this process's track in the merged trace (e.g.
    ``serving-replica-0``); defaults to ``pid-<pid>``."""
    _proc_name[0] = str(name)
    _proc_header_written[0] = False  # re-announce under the new name


# -- sink ---------------------------------------------------------------------

def _proc_header():
    return {"t": "proc", "pid": os.getpid(),
            "name": _proc_name[0] or ("pid-%d" % os.getpid()),
            "ts": int(time.time() * 1e6)}


def _sink_fh(d):
    from .telemetry import _RotatingFile

    path = os.path.join(d, "trace-%d.jsonl" % os.getpid())
    if _sink[0] != path:
        if _sink[1] is not None:
            _sink[1].close()
        try:
            os.makedirs(d, exist_ok=True)
            _sink[0] = path
            _sink[1] = _RotatingFile(path)
            _proc_header_written[0] = False
        except OSError:
            _sink[0] = _sink[1] = None
    return _sink[1]


def _emit(rec):
    _install_handlers()
    with _lock:
        _flight.append(rec)
        if len(_flight) > _FLIGHT_CAP:
            del _flight[: len(_flight) - _FLIGHT_CAP]
        d = _telemetry_dir()
        if not d:
            return
        fh = _sink_fh(d)
        if fh is None:
            return
        if not _proc_header_written[0]:
            _proc_header_written[0] = True
            fh.write(json.dumps(_proc_header()) + "\n")
        fh.write(json.dumps(rec, default=str) + "\n")
        fh.flush()
    if _telemetry_enabled():
        from . import telemetry as _tm

        _tm.inc("tracing_records_total", kind=rec["t"])


def _telemetry_enabled():
    from . import telemetry as _tm

    return _tm.enabled()


def flush():
    """Flush the JSONL sink (tests; the stream is flushed per record
    already, this also covers a swapped telemetry_dir)."""
    with _lock:
        if _sink[1] is not None:
            _sink[1].flush()


def reset():
    """Tests: drop the sink, the flight ring, and per-thread stacks are
    left to unwind naturally (they are context-managed)."""
    with _lock:
        if _sink[1] is not None:
            _sink[1].close()
        _sink[0] = _sink[1] = None
        _proc_header_written[0] = False
        _flight[:] = []


# -- flight recorder ----------------------------------------------------------

def note(kind, **fields):
    """Write-through breadcrumb: lands in the flight ring AND immediately
    checkpoints the ring to flightrec-<pid>.json.  The serving engine
    notes each batch's req_ids here right before execute — SIGKILL is
    uncatchable, so the postmortem must already be on disk when it hits."""
    if not enabled():
        return
    rec = {"t": "note", "kind": kind, "ts": int(time.time() * 1e6),
           "thr": threading.current_thread().name}
    ctx = current_context()
    if ctx is not None:
        rec["tid"], rec["sid"] = ctx
    if fields:
        rec.update(fields)
    _emit(rec)
    flight_dump(reason="note:" + kind)


def flight_dump(reason="manual"):
    """Atomically write the flight ring to <telemetry_dir>/
    flightrec-<pid>.json.  Returns the path, or None (off / no dir)."""
    if not enabled():
        return None
    d = _telemetry_dir()
    if not d:
        return None
    path = os.path.join(d, "flightrec-%d.json" % os.getpid())
    with _lock:
        doc = {"proc": _proc_header(), "reason": reason,
               "dumped_at": int(time.time() * 1e6),
               "records": list(_flight)}
    tmp = path + ".tmp"
    try:
        os.makedirs(d, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
        os.replace(tmp, path)
    except OSError:
        return None
    if _telemetry_enabled():
        from . import telemetry as _tm

        _tm.inc("tracing_flightrec_dumps_total",
                reason=reason.split(":", 1)[0])
    return path


def _install_handlers():
    """Lazy, once: atexit + excepthook always; SIGTERM only from the main
    thread (signal.signal raises elsewhere) and chaining any prior
    handler so serve.py's graceful-shutdown handler still runs."""
    if _handlers_installed[0]:
        return
    with _lock:
        if _handlers_installed[0]:
            return
        _handlers_installed[0] = True
    atexit.register(lambda: flight_dump(reason="atexit"))

    prev_hook = sys.excepthook

    def hook(exc_type, exc, tb):
        try:
            flight_dump(reason="exception:%s" % exc_type.__name__)
        except Exception:
            pass
        prev_hook(exc_type, exc, tb)

    sys.excepthook = hook
    if threading.current_thread() is threading.main_thread():
        import signal

        try:
            prev = signal.getsignal(signal.SIGTERM)

            def on_term(signum, frame):
                try:
                    flight_dump(reason="sigterm")
                except Exception:
                    pass
                if callable(prev):
                    prev(signum, frame)
                elif prev == signal.SIG_DFL:
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                    os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, on_term)
        except (ValueError, OSError):
            pass


# -- RPC frame-name stamping (native/rpc.py) ----------------------------------

def stamp_wire_name(name):
    """Append the current trace context to an RPC SEND frame name
    (``<name>\\x1f<traceparent>``) — only when tracing is on AND a span is
    active, so heartbeats/control traffic outside any trace stay
    byte-identical on the wire.  The 1024-byte name buffer fits any
    protocol key plus the 55-char header."""
    if not enabled():
        return name
    tp = traceparent()
    if tp is None or len(name) + len(tp) + 1 > 1000:
        return name
    return name + _WIRE_SEP + tp


def strip_wire_name(name):
    """Inverse of stamp_wire_name on the poll side: returns
    (bare_name, traceparent_or_None)."""
    if _WIRE_SEP not in name:
        return name, None
    bare, _, tp = name.partition(_WIRE_SEP)
    return bare, (tp if parse_traceparent(tp) else None)


def wire_received(name, tp):
    """Record receipt of a stamped frame: an instant on the SENDER's
    context (tid/sid from the wire header, not this thread's stack), so
    the merged trace shows where each RPC landed."""
    if not enabled() or tp is None:
        return
    ctx = parse_traceparent(tp)
    if ctx is None:
        return
    rec = {"t": "inst", "name": "rpc.recv", "ts": int(time.time() * 1e6),
           "thr": threading.current_thread().name,
           "tid": ctx[0], "sid": ctx[1], "attrs": {"var": name}}
    _emit(rec)
