"""Whole-world static verifier: cross-rank collective-schedule deadlock
analysis + static liveness/peak-HBM estimation (pre-compile).

PR 4's verifier (``core/analysis.py``) checks ONE rank's program in
isolation.  Every distributed failure we have actually shipped since —
elastic re-quorum rewrites, ZeRO-1 reduce-scatter/all-gather chains,
pre-compiled standby worlds — fails *across* ranks: a collective emitted
in a different order, with a different shape/scale/bucket, or on only a
subset of ranks hangs the whole world at runtime with no diagnostic.
This module materializes every rank's transpiled program for a declared
world, extracts each rank's ordered collective trace, and runs a
lockstep matching simulation:

  DL101  cross-rank collective-sequence mismatch (the static deadlock):
         rank r's k-th collective on a ring differs in op type, or r
         runs fewer/more collectives on the ring than the reference
  DL102  matched collectives disagree on shape/dtype/reduction scale or
         quantization geometry (bucket/wire dtype/orig_shape) — not a
         hang but a silent cross-rank corruption
  DL103  collective emitted under control flow whose condition is
         rank-divergent (derived from per-rank data): the branch may
         take different arms on different ranks, so the collective is
         only *conditionally* matched — a latent hang
  DL104  ring/world membership does not cover the declared mesh:
         endpoints/nranks/c_comm_init disagree with the declared world,
         or main-program rings were never initialized in startup

On the same per-block liveness pass the matcher needs, a static memory
estimator attributes per-replica bytes (``Variable.sharding``-aware, so
ZeRO-1 shard slots count 1/nranks) and reports:

  MEM001  static per-replica peak-HBM estimate (informational):
          resident persistable state + feed arguments + the interval-
          liveness peak of transients — cross-checked against
          ``memory_audit``'s compiled ``memory_analysis`` on CPU tier
  MEM002  donation opportunity the executor is not exploiting
          (e.g. ``program._no_donate`` leaves overwritten persistable
          state undonated, doubling its footprint)
  MEM003  predicted peak exceeds ``FLAGS_hbm_budget_bytes`` — the
          on-chip OOM becomes a readable pre-compile diagnostic

Entry points mirror PR 4's three: ``verify_world()`` is called from
``transpiler/collective.py`` (post-transpile, error mode only — warn
mode leaves the cheap single-rank subset to the executor hook) and from
``distributed/elastic.py`` standby pre-verification (a standby world can
never be adopted with a latent deadlock); ``annotate_rank_checks()``
rides the executor's ``check_before_compile`` escalation; and
``tools/proglint.py --world N --mesh dpxtp [--zero1] [--mem-budget]``
runs it standalone over the bundled model zoo.
"""

import threading

from . import analysis
from .analysis import (ERROR, INFO, WARNING, VerifyReport, _COLLECTIVE_OPS,
                       _block_paths, _runtime_ops)

__all__ = [
    "CollectiveEvent",
    "extract_trace",
    "materialize_world",
    "verify_world",
    "check_world_transpiled",
    "annotate_rank_checks",
    "estimate_program_hbm",
]

# collectives whose OUTPUT is bitwise-uniform across ranks (every rank
# reduces/gathers the same global value) — they SCRUB divergence taint
_UNIFORM_OUT = frozenset((
    "c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
    "c_allreduce_prod", "c_allreduce_qsum", "c_broadcast", "c_allgather",
    "c_allgather_q", "allreduce", "broadcast",
))

# collectives whose output is a per-rank SHARD (each rank sees different
# values) — they INTRODUCE divergence even from uniform inputs
_DIVERGENT_OUT = frozenset((
    "c_reducescatter", "c_reducescatter_q", "c_shard_slice",
))

# attrs that must agree on a matched collective (DL102); orig_shape /
# bucket / dtype carry the EQuARX quantization geometry, scale the folded
# 1/nranks reduction average, nranks the shard-world
_MATCH_ATTRS = ("scale", "nranks", "bucket", "dtype", "orig_shape")

_DTYPE_BYTES = {
    "bool": 1, "int8": 1, "uint8": 1, "int16": 2, "float16": 2,
    "bfloat16": 2, "int32": 4, "float32": 4, "int64": 8, "float64": 8,
}

# reentrancy guard: verify_world materializes sibling ranks through
# Collective.transpile, which itself hooks back into check_world_transpiled
_tls = threading.local()


def _materializing():
    return bool(getattr(_tls, "active", False))


class _guard:
    def __enter__(self):
        self._prev = getattr(_tls, "active", False)
        _tls.active = True

    def __exit__(self, *exc):
        _tls.active = self._prev


# ---------------------------------------------------------------------------
# collective trace extraction (+ rank-divergence taint)
# ---------------------------------------------------------------------------


class CollectiveEvent:
    """One collective in one rank's execution order: what would be posted
    to the wire, where it sits in the program, and whether control flow
    above it is rank-divergent."""

    __slots__ = ("op_type", "ring", "block_idx", "op_idx", "block_path",
                 "var", "shape", "dtype", "attrs", "divergent", "via")

    def __init__(self, op_type, ring, block_idx, op_idx, block_path, var,
                 shape, dtype, attrs, divergent, via):
        self.op_type = op_type
        self.ring = ring
        self.block_idx = block_idx
        self.op_idx = op_idx
        self.block_path = block_path
        self.var = var
        self.shape = shape
        self.dtype = dtype
        self.attrs = attrs
        self.divergent = divergent
        self.via = via  # condition var that made the context divergent

    def describe(self):
        return "%s(%s%s) ring %s" % (
            self.op_type, self.var or "?",
            "" if self.shape is None else " " + "x".join(
                str(d) for d in self.shape),
            self.ring)


def _sub_block_idx(op):
    sub = op.attr("sub_block")
    if sub is None:
        return None
    return int(getattr(sub, "idx", sub))


def _cond_var(op):
    """The control-flow condition variable of a sub-block op, if any."""
    if op.type == "while":
        names = op.input("Condition")
    elif op.type == "conditional_block":
        names = op.input("Cond")
    else:
        names = ()
    return names[0] if names else None


def divergence_taint(program):
    """Names whose VALUE may differ across ranks: per-rank data feeds
    (``is_data``) and everything dataflow-derived from them, plus shard-
    producing collective outputs.  Uniform-output collectives scrub the
    taint (an allreduced loss is the same number everywhere, so a branch
    on it is rank-uniform).  Two passes reach the fixed point through
    loop-carried vars."""
    tainted = set()
    for blk in program.blocks:
        for name, v in blk.vars.items():
            if getattr(v, "is_data", False):
                tainted.add(name)

    def walk(blk):
        for op in blk.ops:
            if op.type in ("feed", "fetch"):
                continue
            sub = _sub_block_idx(op)
            if sub is not None and sub < len(program.blocks):
                walk(program.blocks[sub])
            if op.type in _UNIFORM_OUT:
                # the reduced/gathered value is identical on every rank:
                # taint does not pass through, and an in-place allreduce
                # (Out aliases X) leaves the name rank-uniform after it
                tainted.difference_update(
                    n for n in op.output_arg_names if n)
                continue
            if (op.type in _DIVERGENT_OUT
                    or any(n in tainted for n in op.input_arg_names if n)):
                tainted.update(n for n in op.output_arg_names if n)

    for _ in range(2):
        walk(program.global_block())
    return tainted


def extract_trace(program):
    """Every collective in one rank's program, in execution order
    (descending into while/cond/recurrent sub-blocks at the point their
    parent op runs), with ring/shape/dtype/quant attrs and the
    rank-divergent-control-flow bit DL103 keys on."""
    paths = _block_paths(program)
    tainted = divergence_taint(program)
    events = []

    def walk(blk, divergent, via):
        for op_idx, op in enumerate(blk.ops):
            if op.type in ("feed", "fetch"):
                continue
            sub = _sub_block_idx(op)
            if sub is not None and sub < len(program.blocks):
                cond = _cond_var(op)
                cond_div = cond is not None and cond in tainted
                walk(program.blocks[sub], divergent or cond_div,
                     via or (cond if cond_div else None))
                continue
            if op.type not in _COLLECTIVE_OPS:
                continue
            x = (op.input("X") or (None,))[0]
            v = blk._find_var_recursive(x) if x else None
            events.append(CollectiveEvent(
                op.type, op.attr("ring_id"), blk.idx, op_idx,
                paths.get(blk.idx) or None, x,
                tuple(int(d) for d in v.shape) if v is not None and v.shape
                else None,
                getattr(v, "dtype", None),
                {k: op.attr(k) for k in _MATCH_ATTRS
                 if op.attr(k) is not None},
                divergent, via))

    walk(program.global_block(), False, None)
    return events


# ---------------------------------------------------------------------------
# world materialization
# ---------------------------------------------------------------------------


def materialize_world(base_main, base_startup, nranks, nrings=1,
                      endpoints=None):
    """Clone the pristine programs and run the flag-selected gradient
    transpiler once per rank — the same rewrite each process would apply
    — returning ``[(main, startup), ...]`` indexed by rank.  Guarded so
    the transpiler's own post-transpile world hook does not recurse."""
    from ..transpiler.collective import select_grad_transpiler

    if endpoints is None:
        endpoints = ["world-check:%d" % (9000 + r) for r in range(nranks)]
    if len(endpoints) != nranks:
        raise ValueError("endpoints %d != nranks %d"
                         % (len(endpoints), nranks))
    out = []
    with _guard():
        for r in range(nranks):
            main = base_main.clone()
            startup = base_startup.clone()
            # clone() rebuilds only IR state; executor-facing side flags
            # like _no_donate must survive or MEM002 goes blind here
            if getattr(base_main, "_no_donate", False):
                main._no_donate = True
            t = select_grad_transpiler(nrings)
            t.transpile(startup_program=startup, main_program=main, rank=r,
                        endpoints=list(endpoints),
                        current_endpoint=endpoints[r], wait_port=False)
            out.append((main, startup))
    return out


# ---------------------------------------------------------------------------
# DL101/DL102: lockstep schedule matching
# ---------------------------------------------------------------------------


def _by_ring(events):
    rings = {}
    for e in events:
        rings.setdefault(e.ring, []).append(e)
    return rings


def _match_schedules(traces, rep):
    """Lockstep simulation: on each ring, every rank must post the same
    collective sequence as rank 0 (the reference).  The first divergence
    per (rank, ring) is the deadlock point; matched pairs are checked for
    shape/dtype/reduction/quant agreement (DL102)."""
    ref_rings = _by_ring(traces[0])
    for r in range(1, len(traces)):
        got_rings = _by_ring(traces[r])
        for ring in sorted(set(ref_rings) | set(got_rings), key=str):
            ref = ref_rings.get(ring, [])
            got = got_rings.get(ring, [])
            diverged = False
            for k, (ea, eb) in enumerate(zip(ref, got)):
                # a matched collective is the same op on the same tensor;
                # a different var at the same position means the SEQUENCE
                # shifted (an exchange lost or gained upstream), which is
                # the deadlock — not an attr disagreement
                if ea.op_type != eb.op_type or ea.var != eb.var:
                    rep.add(ERROR, "DL101",
                            "collective #%d on ring %s is %s on rank %d "
                            "but %s on rank 0 — the world deadlocks at "
                            "this exchange" % (k, ring, eb.describe(), r,
                                               ea.describe()),
                            eb.block_idx, eb.op_idx, rank=r,
                            block_path=eb.block_path,
                            var_names=tuple(n for n in (eb.var, ea.var)
                                            if n),
                            suggestion="re-transpile every rank from the "
                            "same pristine program and flags")
                    diverged = True
                    break
                _match_attrs(ea, eb, r, k, ring, rep)
            if diverged or len(ref) == len(got):
                continue
            if len(got) < len(ref):
                missing = ref[len(got)]
                rep.add(ERROR, "DL101",
                        "rank %d posts only %d collective(s) on ring %s "
                        "but rank 0 posts %d — rank 0 blocks forever in "
                        "collective #%d %s (rank 0 block %d op %d)"
                        % (r, len(got), ring, len(ref), len(got),
                           missing.describe(), missing.block_idx,
                           missing.op_idx),
                        missing.block_idx, missing.op_idx, rank=r,
                        block_path=missing.block_path,
                        var_names=(missing.var,) if missing.var else (),
                        suggestion="rank %d's program lost this exchange "
                        "(stale/tampered transpile) — rebuild it" % r)
            else:
                extra = got[len(ref)]
                rep.add(ERROR, "DL101",
                        "rank %d posts %d collective(s) on ring %s but "
                        "rank 0 posts only %d — rank %d blocks forever "
                        "in its extra collective #%d %s"
                        % (r, len(got), ring, len(ref), r, len(ref),
                           extra.describe()),
                        extra.block_idx, extra.op_idx, rank=r,
                        block_path=extra.block_path,
                        var_names=(extra.var,) if extra.var else (),
                        suggestion="rank %d's program gained an exchange "
                        "no peer posts — rebuild it" % r)


def _match_attrs(ea, eb, rank, k, ring, rep):
    """DL102 on one matched pair: a shape/dtype/scale/quant disagreement
    doesn't hang, it silently corrupts every participating tensor."""
    diffs = []
    if ea.shape != eb.shape:
        diffs.append("shape %s vs %s" % (
            list(eb.shape or ()), list(ea.shape or ())))
    if ea.dtype != eb.dtype:
        diffs.append("dtype %s vs %s" % (eb.dtype, ea.dtype))
    for attr in _MATCH_ATTRS:
        a, b = ea.attrs.get(attr), eb.attrs.get(attr)
        if a != b:
            diffs.append("%s %r vs %r" % (attr, b, a))
    if not diffs:
        return
    rep.add(ERROR, "DL102",
            "collective #%d on ring %s (%s) disagrees between rank %d "
            "and rank 0: %s" % (k, ring, eb.op_type, rank,
                                "; ".join(diffs)),
            eb.block_idx, eb.op_idx, rank=rank, block_path=eb.block_path,
            var_names=(eb.var,) if eb.var else (),
            suggestion="matched collectives must agree on payload "
            "geometry and reduction/quantization attrs on every rank")


# ---------------------------------------------------------------------------
# DL103: collectives under rank-divergent control flow
# ---------------------------------------------------------------------------


def _check_divergent_control_flow(traces, rep):
    seen = set()
    for r, events in enumerate(traces):
        for e in events:
            if not e.divergent:
                continue
            key = (e.block_idx, e.op_idx, e.op_type, e.via)
            if key in seen:
                continue  # identical programs: report once, not per rank
            seen.add(key)
            rep.add(WARNING, "DL103",
                    "collective %s runs under control flow conditioned "
                    "on %r, which is derived from per-rank data — ranks "
                    "may take different arms and the exchange is only "
                    "conditionally matched (latent hang)"
                    % (e.describe(), e.via or "?"),
                    e.block_idx, e.op_idx, rank=r,
                    block_path=e.block_path,
                    var_names=(e.var,) if e.var else (),
                    suggestion="make the condition rank-uniform (e.g. "
                    "allreduce it) or hoist the collective out of the "
                    "branch")


# ---------------------------------------------------------------------------
# DL104: ring/world membership vs the declared mesh
# ---------------------------------------------------------------------------


def _check_world_coverage(worlds, traces, nranks, mesh, rep,
                          declared_world=None):
    if mesh:
        product = 1
        for d in mesh:
            product *= int(d)
        if int(mesh[0]) != int(nranks):
            rep.add(ERROR, "DL104",
                    "declared mesh %s has data axis %d but the "
                    "collective world exchanges across %d rank(s) — "
                    "the rings do not cover the mesh"
                    % ("x".join(str(d) for d in mesh), int(mesh[0]),
                       nranks),
                    suggestion="the mesh's data axis must equal the "
                    "collective world (model/pipeline axes shard within "
                    "a rank)")
        if declared_world is not None and product != int(declared_world):
            rep.add(ERROR, "DL104",
                    "declared mesh %s covers %d device(s) but the world "
                    "declares %d — %d device(s) would never join any "
                    "ring" % ("x".join(str(d) for d in mesh), product,
                              int(declared_world),
                              abs(product - int(declared_world))),
                    suggestion="pick a mesh whose dp*tp product equals "
                    "the world size")
    for r, (main, startup) in enumerate(worlds):
        meta = getattr(main, "_collective_meta", None) or {}
        if meta.get("nranks") and int(meta["nranks"]) != int(nranks):
            rep.add(ERROR, "DL104",
                    "rank %d was transpiled for a %s-rank world but the "
                    "declared world has %d" % (r, meta["nranks"], nranks),
                    rank=r,
                    suggestion="re-transpile for the declared endpoints")
        eps = meta.get("endpoints") or ()
        if eps and len(eps) != int(nranks):
            rep.add(ERROR, "DL104",
                    "rank %d's endpoint list has %d member(s) but the "
                    "declared world has %d" % (r, len(eps), nranks),
                    rank=r)
        init = {}
        for blk in startup.blocks:
            for op_idx, op in _runtime_ops(blk):
                if op.type != "c_comm_init":
                    continue
                ring = op.attr("ring_id")
                init[ring] = (op_idx, op)
                got = op.attr("nranks")
                if got is not None and int(got) != int(nranks):
                    rep.add(ERROR, "DL104",
                            "rank %d initializes ring %s for %d rank(s) "
                            "but the declared world has %d"
                            % (r, ring, int(got), nranks),
                            blk.idx, op_idx, rank=r,
                            suggestion="startup c_comm_init must cover "
                            "the whole declared world")
        used = {e.ring for e in traces[r] if e.ring is not None}
        for ring in sorted(used - set(init), key=str):
            ev = next(e for e in traces[r] if e.ring == ring)
            rep.add(ERROR, "DL104",
                    "rank %d posts collectives on ring %s but startup "
                    "never runs c_comm_init for it — the communicator "
                    "does not exist" % (r, ring),
                    ev.block_idx, ev.op_idx, rank=r,
                    block_path=ev.block_path,
                    suggestion="transpile startup and main together so "
                    "every used ring is initialized")


# ---------------------------------------------------------------------------
# MEM001-003: static liveness / peak-HBM estimator
# ---------------------------------------------------------------------------


def _var_bytes(v, batch, mesh_axes, shape_override=None):
    """Per-replica bytes of one program var.  ``-1`` dims resolve to
    `batch`; a ``Variable.sharding`` annotation divides the sharded dims
    by the mesh axis size (ZeRO-1 state slots, SPMD params); bare data
    feeds are batch-sharded over the data axis."""
    shape = shape_override if shape_override is not None else (v.shape or ())
    dims = [int(batch) if int(d) < 0 else int(d) for d in shape]
    axes = mesh_axes or {}
    sharding = getattr(v, "sharding", None)
    if sharding:
        for i, ax in enumerate(sharding):
            if ax and i < len(dims) and int(axes.get(ax, 1)) > 1:
                dims[i] = -(-dims[i] // int(axes[ax]))
    elif getattr(v, "is_data", False) and dims \
            and shape_override is None and int(axes.get("data", 1)) > 1:
        dims[0] = -(-dims[0] // int(axes["data"]))
    numel = 1
    for d in dims:
        numel *= max(int(d), 0)
    return numel * _DTYPE_BYTES.get(getattr(v, "dtype", None), 4)


# horizontal optimizer fusion (ir.py fuse_optimizer_ops_pass) lowers each
# group through flat concatenated buffers: XLA materializes one
# full-group-size temp per duplicable state slot (bert-tiny buffer
# assignment: fused adam over all 2-D params shows 4 flat f32[total]
# temps — param/grad/m1/m2 — dominating the temp slab).  Scalar
# accumulators (beta pows) don't rate a slot.
_FUSED_FLAT_SLOTS = {
    "adam": ("Param", "Grad", "Moment1", "Moment2"),
    "momentum": ("Param", "Grad", "Velocity"),
    "sgd": ("Param", "Grad"),
}


def _fused_optimizer_loads(program, block, nbytes):
    """Point loads [(op_idx, bytes)] for the flat temp buffers of fused
    optimizer updates.  Covers both an already-fused program (the
    executor applies the pass in place before check_before_compile) and
    a pristine one — there the fusion the executor WILL apply is
    predicted with the pass's own grouping rules (per type+LR+dtype,
    rank-capped, >= MIN_GROUP members)."""
    loads = []
    fused_seen = False
    for i, op in enumerate(block.ops):
        base = op.type[len("fused_"):] if op.type.startswith("fused_") \
            else None
        if base in _FUSED_FLAT_SLOTS:
            fused_seen = True
            group = sum(nbytes(n) for n in op.input("Param"))
            loads.append((i, group * len(_FUSED_FLAT_SLOTS[base])))
    if fused_seen:
        return loads
    from .. import flags

    if not flags.flag("fuse_optimizer_ops"):
        return loads
    max_rank = int(flags.flag("fuse_optimizer_max_rank") or 0)
    groups = {}
    for i, op in enumerate(block.ops):
        if op.type not in _FUSED_FLAT_SLOTS:
            continue
        pname = op.input("Param")[0]
        pv = block._find_var_recursive(pname)
        if pv is None or pv.shape is None:
            continue
        if max_rank and len(pv.shape) > max_rank:
            continue
        lr = (op.input("LearningRate") or [None])[0]
        last_idx, total, count = groups.get((op.type, lr, pv.dtype),
                                            (0, 0, 0))
        groups[(op.type, lr, pv.dtype)] = (i, total + nbytes(pname),
                                           count + 1)
    for (op_type, _lr, _dt), (last_idx, total, count) in groups.items():
        if count >= 4:  # FuseOptimizerOpsPass.MIN_GROUP
            loads.append((last_idx,
                          total * len(_FUSED_FLAT_SLOTS[op_type])))
    return loads


def estimate_program_hbm(program, feed_names=None, fetch_names=(), batch=1,
                         mesh_axes=None, feed_shapes=None):
    """Interval-liveness peak-HBM estimate for ONE rank's program,
    pre-compile.  Mirrors what XLA's ``memory_analysis`` budget counts:

      resident   every persistable the step touches (params, optimizer
                 state, bf16 carries) — argument buffers, live end to end
      feeds      data arguments (live end to end: args are not donated)
      transient  interval liveness of every intermediate — def at first
                 write, dead after last read; fetched intermediates stay
                 live to program end (they become output buffers)

    ``peak_bytes = resident + feeds + max_t transient(t)``.  Sub-block
    transient peaks load the parent op's time step.  `feed_shapes` maps
    feed name -> concrete shape (the executor passes the real batch);
    otherwise ``-1`` dims resolve to `batch`."""
    block = program.global_block()
    feed_shapes = dict(feed_shapes or {})
    if feed_names is None:
        feed_names = [n for n, v in sorted(block.vars.items())
                      if getattr(v, "is_data", False)]
    feed_set = set(feed_names)
    fetch_set = set(fetch_names or ())
    if feed_shapes and batch == 1:
        for shp in feed_shapes.values():
            if shp:
                batch = max(batch, int(shp[0]))

    def nbytes(name, blk):
        v = blk._find_var_recursive(name)
        if v is None or getattr(v, "type", None) == "LOD_TENSOR_ARRAY":
            return 0
        return _var_bytes(v, batch, mesh_axes,
                          shape_override=feed_shapes.get(name))

    resident_names, feed_bytes = set(), 0
    for name in feed_set:
        feed_bytes += nbytes(name, block)

    def transient_peak(blk, extra_loads=()):
        ops = [(i, op) for i, op in enumerate(blk.ops)
               if op.type not in ("feed", "fetch")]
        first_write, last_read = {}, {}
        sub_loads = list(extra_loads)
        for i, op in ops:
            sub = _sub_block_idx(op)
            if sub is not None and sub < len(program.blocks):
                sub_loads.append((i, transient_peak(program.blocks[sub])))
            for name in op.input_arg_names:
                if name:
                    last_read[name] = i
            for name in op.output_arg_names:
                if name:
                    first_write.setdefault(name, i)
                    last_read.setdefault(name, i)
        n = len(blk.ops) + 1
        delta = [0] * (n + 1)
        for name, start in first_write.items():
            if name in feed_set:
                continue
            v = blk._find_var_recursive(name)
            if v is None or v.persistable:
                resident_names.add(name)
                continue
            b = nbytes(name, blk)
            if not b:
                continue
            end = n - 1 if name in fetch_set else last_read.get(name, start)
            delta[start] += b
            delta[end + 1] -= b
        for i, load in sub_loads:
            delta[i] += load
            delta[i + 1] -= load
        peak = cur = 0
        for d in delta:
            cur += d
            peak = max(peak, cur)
        return peak

    transient = transient_peak(block, _fused_optimizer_loads(
        program, block, lambda name: nbytes(name, block)))
    # persistables read from the scope (ro/rw args) — including ones only
    # ever read, which the transient scan above never sees
    for blk in program.blocks:
        for op in blk.ops:
            if op.type in ("feed", "fetch"):
                continue
            for name in list(op.input_arg_names) + list(op.output_arg_names):
                if not name or name in feed_set:
                    continue
                v = blk._find_var_recursive(name)
                if v is not None and v.persistable:
                    resident_names.add(name)
    resident = sum(nbytes(name, block) for name in sorted(resident_names))
    out_bytes = sum(nbytes(name, block) for name in sorted(fetch_set))

    # donation audit: overwritten persistable state is normally donated by
    # the executor (the update aliases the argument buffer); _no_donate
    # programs pay for both copies
    from .lowering import analyze_block

    ext, _written, persist_written = analyze_block(block, feed_names)
    rw_names = [n for n in ext if n in set(persist_written)]
    rw_bytes = sum(nbytes(name, block) for name in rw_names)
    no_donate = bool(getattr(program, "_no_donate", False))
    peak = resident + feed_bytes + transient + (rw_bytes if no_donate else 0)
    return {
        "peak_bytes": int(peak),
        "resident_bytes": int(resident),
        "feed_bytes": int(feed_bytes),
        "transient_peak_bytes": int(transient),
        "output_bytes": int(out_bytes),
        "rw_bytes": int(rw_bytes),
        "rw_names": list(rw_names),
        "no_donate": no_donate,
        "batch": int(batch),
        "n_resident": len(resident_names),
    }


def _fmt_mb(b):
    return "%.1f MB" % (b / 1e6)


def check_memory(program, rep, rank=None, budget=None, batch=1,
                 mesh_axes=None, feed_names=None, fetch_names=(),
                 feed_shapes=None):
    """MEM001 estimate + MEM002 donation audit + MEM003 budget gate for
    one rank's program; returns the estimate dict."""
    est = estimate_program_hbm(program, feed_names=feed_names,
                               fetch_names=fetch_names, batch=batch,
                               mesh_axes=mesh_axes, feed_shapes=feed_shapes)
    # engine-owned paged KV pools (serving/kv_cache.py) are allocated
    # OUTSIDE any Program's scope but are just as resident on the chip —
    # fold live caches into the static peak so a decode replica's MEM003
    # budget gate sees them.  The pool bytes already INCLUDE the prefix
    # cache's evictable blocks: cached prefixes live inside the planned
    # pool (zero-ref blocks parked for reuse, reclaimed on demand), so a
    # warm cache never grows the peak beyond this estimate
    try:
        import sys

        _kvmod = sys.modules.get("paddle_tpu.serving.kv_cache")
        kv_bytes = int(_kvmod.engine_owned_kv_bytes()) if _kvmod else 0
        dec_bytes = int(_kvmod.engine_owned_resident_bytes()) \
            if _kvmod else 0
    except Exception:
        kv_bytes = 0
        dec_bytes = 0
    est["kv_cache_bytes"] = kv_bytes
    est["peak_bytes"] += kv_bytes
    # decode-model weights (target + speculative draft params) are
    # engine-resident the same way the KV pools are
    est["decoder_resident_bytes"] = dec_bytes
    est["peak_bytes"] += dec_bytes
    kv_note = " + kv_cache %s" % _fmt_mb(kv_bytes) if kv_bytes else ""
    if dec_bytes:
        kv_note += " + decoder_params %s" % _fmt_mb(dec_bytes)
    rep.add(INFO, "MEM001",
            "static per-replica peak ~%s (resident %s + feeds %s + "
            "transient %s%s, batch %d)"
            % (_fmt_mb(est["peak_bytes"]), _fmt_mb(est["resident_bytes"]),
               _fmt_mb(est["feed_bytes"]),
               _fmt_mb(est["transient_peak_bytes"]), kv_note,
               est["batch"]),
            rank=rank)
    if est["no_donate"] and est["rw_bytes"]:
        rep.add(WARNING, "MEM002",
                "%s of overwritten persistable state is NOT donated "
                "(_no_donate) — the step holds both the argument and the "
                "updated copy live (%d var(s), e.g. %s)"
                % (_fmt_mb(est["rw_bytes"]), len(est["rw_names"]),
                   est["rw_names"][0]),
                rank=rank, var_names=tuple(est["rw_names"][:4]),
                suggestion="clear program._no_donate or split the "
                "overwritten state out of the shared scope")
    if budget is None:
        from .. import flags

        budget = flags.flag("hbm_budget_bytes")
    budget = int(budget or 0)
    if budget > 0 and est["peak_bytes"] > budget:
        rep.add(ERROR, "MEM003",
                "predicted per-replica peak %s (%d bytes) exceeds the "
                "FLAGS_hbm_budget_bytes budget %s (%d bytes) — this world "
                "would trip the HBM band edge on chip"
                % (_fmt_mb(est["peak_bytes"]), est["peak_bytes"],
                   _fmt_mb(budget), budget),
                rank=rank,
                suggestion="shrink the batch, enable BENCH_REMAT=auto "
                "recompute, or shard optimizer state "
                "(FLAGS_collective_mode=zero1)"
                + (", or shrink the paged KV pool "
                   "(FLAGS_kv_cache_blocks / FLAGS_kv_cache_dtype=int8)"
                   if kv_bytes else ""))
    return est


# ---------------------------------------------------------------------------
# top-level entry points
# ---------------------------------------------------------------------------


def verify_world(base_main, base_startup, nranks, mesh=None, nrings=1,
                 feed_names=None, fetch_names=(), label=None, actual=None,
                 batch=1, mem_budget=None, collective_mode=None,
                 wire_dtype=None, quant_bucket=None, endpoints=None,
                 declared_world=None):
    """Materialize every rank of the declared world from the PRISTINE
    programs and run the full cross-rank analysis (DL101-104 +
    MEM001-003).  Returns a VerifyReport whose ``.hbm`` attribute holds
    the per-rank estimate dicts.

    `actual` maps rank -> (main, startup) to substitute a rank's REAL
    transpiled programs (the elastic standby view, the transpiler's own
    output) for the pristine-derived materialization — that is how a
    tampered or stale rank shows up as DL101/DL102 against its
    honestly-derived siblings.  `collective_mode` / `wire_dtype` /
    `quant_bucket` temporarily override the transpile-affecting flags so
    a zero1-int8 world can be checked from any flag state."""
    from .. import flags
    from . import telemetry

    nranks = int(nranks)
    if nranks < 1:
        raise ValueError("nranks must be >= 1, got %d" % nranks)
    overrides = {}
    if collective_mode is not None:
        overrides["FLAGS_collective_mode"] = collective_mode
    if wire_dtype is not None:
        overrides["FLAGS_allreduce_dtype"] = wire_dtype
    if quant_bucket is not None:
        overrides["FLAGS_allreduce_quant_bucket"] = int(quant_bucket)
    saved = flags.get_flags(list(overrides)) if overrides else {}
    if overrides:
        flags.set_flags(overrides)
    try:
        worlds = materialize_world(base_main, base_startup, nranks,
                                   nrings=nrings, endpoints=endpoints)
    finally:
        if overrides:
            flags.set_flags(saved)
    for r, progs in (actual or {}).items():
        r = int(r)
        if not 0 <= r < nranks:
            raise ValueError("actual rank %d outside world of %d"
                             % (r, nranks))
        main, startup = progs
        worlds[r] = (main, startup if startup is not None
                     else worlds[r][1])

    rep = VerifyReport(label=label or ("world of %d rank(s)%s"
                                       % (nranks, " mesh %s" % (
                                           "x".join(str(d) for d in mesh),)
                                          if mesh else "")))
    mesh_axes = {}
    if mesh:
        mesh_axes["data"] = int(mesh[0])
        if len(mesh) > 1:
            mesh_axes["model"] = int(mesh[1])
    else:
        mesh_axes["data"] = nranks

    traces = [extract_trace(main) for main, _startup in worlds]
    with _guard():
        _match_schedules(traces, rep)
        _check_divergent_control_flow(traces, rep)
        _check_world_coverage(worlds, traces, nranks, mesh, rep,
                              declared_world=declared_world)
        rep.hbm = []
        for r, (main, _startup) in enumerate(worlds):
            rep.hbm.append(check_memory(
                main, rep, rank=r, budget=mem_budget, batch=batch,
                mesh_axes=mesh_axes, feed_names=feed_names,
                fetch_names=fetch_names))

    telemetry.inc("static_check_world_runs_total")
    telemetry.set_gauge("static_check_world_ranks", nranks)
    if rep.hbm:
        telemetry.set_gauge("static_check_world_peak_bytes",
                            max(h["peak_bytes"] for h in rep.hbm))
    for d in rep.errors + rep.warnings:
        telemetry.inc("static_check_world_findings", 1, rule=d.rule)
    return rep


def check_world_transpiled(pristine_main, pristine_startup, main, startup,
                           rank, nranks, nrings=1):
    """Post-transpile hook (``Collective.transpile``): in ERROR mode,
    materialize the whole world from the pristine clones and check this
    rank's actual output against its siblings — a stale or divergent
    rewrite raises before anything compiles.  Warn mode skips the
    world-level pass (the executor's compile hook still runs the cheap
    single-rank subset); the materializer's own transpiles never
    recurse."""
    if _materializing():
        return None
    mode = analysis._mode()
    if mode != "error":
        return None
    if pristine_main is None or pristine_startup is None:
        return None
    rep = verify_world(pristine_main, pristine_startup, nranks,
                       nrings=nrings,
                       actual={int(rank): (main, startup)},
                       label="post-transpile world of %d (rank %d)"
                             % (nranks, rank))
    return analysis._dispatch(rep, mode)


def annotate_rank_checks(program, rep, feed_names=(), fetch_names=(),
                         feed_shapes=None):
    """The single-rank subset for the executor's ``check_before_compile``
    escalation: DL103 (divergent control flow over this rank's own
    program) + MEM001-003.  No sibling materialization — the executor
    has no pristine base program — so DL101/102/104 stay with
    verify_world's callers."""
    meta = getattr(program, "_collective_meta", None) or {}
    trace = extract_trace(program)
    _check_divergent_control_flow([trace], rep)
    if meta.get("nranks"):
        used = {e.ring for e in trace if e.ring is not None}
        # DL104-lite: rings are per-world resources; a collective on a
        # ring the transpiler never allocated cannot have a communicator
        nrings = int(meta.get("nrings") or 1)
        for ring in sorted(used, key=str):
            if ring is not None and int(ring) >= nrings:
                ev = next(e for e in trace if e.ring == ring)
                rep.add(ERROR, "DL104",
                        "collective on ring %s but this world only "
                        "initializes rings 0..%d" % (ring, nrings - 1),
                        ev.block_idx, ev.op_idx,
                        block_path=ev.block_path)
    mesh_axes = {"data": int(meta["nranks"])} if meta.get("nranks") else None
    check_memory(program, rep, batch=1, mesh_axes=mesh_axes,
                 feed_names=list(feed_names) or None,
                 fetch_names=fetch_names, feed_shapes=feed_shapes)
    return rep
