"""Static Program verifier: abstract interpretation over blocks before
lowering.

The reference framework validates ops only at runtime (``OperatorBase::Run``
plus scattered ``PADDLE_ENFORCE``s, operator.cc:947), so a malformed program
— a dangling input, dtype drift between forward and grad, a param assigned
to two pservers — surfaces as an opaque XLA lowering error or silent wrong
numbers deep inside ``executor.run``.  This module checks the Program IR
statically and emits structured diagnostics (severity, rule id, op index,
var names, suggested fix), the TensorFlow shape-inference-at-construction
design applied to this runtime's four program rewriters (backward, IR
passes, DistributeTranspiler, lowering).

Rule families
-------------
well-formedness   WF001 use-before-def / dangling input
                  WF002 unknown op type
                  WF003 unused op output                        (info)
                  WF004 op unreachable from the fetch targets   (warning)
                  WF005 undeclared input/output slot
type/shape flow   TS001 dtype mismatch (declared vs re-inferred)
                  TS002 shape contradiction (declared vs re-inferred)
                  TS003 grad var inconsistent with its forward var
donation/alias    DA001 donated param read after its in-place update
                  DA002 donated param is a fetch target          (info)
                  DA003 double write without a read dependency   (warning)
distributed lint  DL001 param not assigned to exactly one pserver
                  DL002 param/grad send-recv pairing broken
                  DL003 collective ring_id missing/negative/mixed
                  DL004 side-effecting op duplicated into trainer + pserver
                  DL005 gradient-scale constant stale vs collective world
                  DL006 ZeRO-1 shard coverage / dequant scale / shard world

Gating: ``FLAGS_static_check`` = ``off`` | ``warn`` (default) | ``error``.
``off`` costs one flag read per executor compile (the telemetry early-return
pattern); ``warn`` logs a ``ProgramVerifyWarning`` and bumps the
``static_check_warnings`` telemetry counter; ``error`` raises a single
readable ``ProgramVerificationError`` report instead of an XLA traceback.
Entry points: the executor compile path (cache-miss only), the
post-transpile hook in ``transpiler/distribute_transpiler.py``, and the
standalone ``tools/proglint.py`` CLI.
"""

import warnings

__all__ = [
    "Diagnostic",
    "VerifyReport",
    "ProgramVerifyWarning",
    "ProgramVerificationError",
    "RULES",
    "verify_program",
    "verify_transpiled",
    "check_before_compile",
    "check_transpiled",
]

ERROR = "error"
WARNING = "warning"
INFO = "info"

# rule id -> one-line catalog entry (README "Static checking" renders this)
RULES = {
    "WF001": "input read before any definition (dangling input)",
    "WF002": "unknown op type (no registry entry)",
    "WF003": "op output produced but never consumed",
    "WF004": "op cannot reach any fetch target or persistable state",
    "WF005": "input/output slot not declared by the op's registry entry",
    "TS001": "declared dtype disagrees with re-inferred dtype",
    "TS002": "declared shape contradicts re-inferred shape",
    "TS003": "grad var shape/dtype disagrees with its forward var",
    "DA001": "donated var read after its in-place update",
    "DA002": "donated var is a fetch target (fetch sees the updated value)",
    "DA003": "var written twice with no read of the first value",
    "DL001": "param not assigned to exactly one pserver",
    "DL002": "param/grad send-recv pairing broken",
    "DL003": "collective op ring_id missing, negative, or mixed",
    "DL004": "side-effecting op duplicated into trainer and pserver",
    "DL005": "gradient-scale constant stale vs collective world size",
    "DL006": "ZeRO-1 shard coverage / dequant-scale / shard-world broken",
    # world-level rules (core/world_analysis.py): every rank's transpiled
    # program is materialized and the collective schedules are matched in
    # lockstep — these catch the cross-rank failures a one-rank check
    # cannot see (the static deadlock class)
    "DL101": "cross-rank collective sequence mismatch (static deadlock)",
    "DL102": "matched collectives disagree on shape/dtype/reduction/quant",
    "DL103": "collective emitted under rank-divergent control flow",
    "DL104": "ring/world membership does not cover the declared mesh",
    # static memory estimator (same liveness pass): per-replica bytes with
    # NamedSharding-aware attribution, pre-compile
    "MEM001": "static per-replica peak-HBM estimate (informational)",
    "MEM002": "donation opportunity the executor is not exploiting",
    "MEM003": "predicted peak HBM exceeds FLAGS_hbm_budget_bytes",
    # concurrency rules (core/concurrency_analysis.py, tools/threadlint.py):
    # AST-only lint of the thread-heavy Python runtime — the layer the
    # program verifiers cannot see
    "CC101": "lock-order inversion (acquisition-graph cycle or declared "
             "LOCK_ORDER violated)",
    "CC102": "blocking call (RPC, sleep, subprocess, file I/O, join, "
             "compile/step) while holding a lock",
    "CC103": "attribute guarded by a lock in some methods but accessed "
             "lock-free on a thread path",
    "CC104": "Condition.wait without an enclosing while predicate-recheck "
             "loop",
    "CC105": "callback declared fired-unlocked invoked while holding the "
             "owner's lock",
    "CC106": "Thread started without daemon=True or a tracked join() path",
}


class ProgramVerifyWarning(UserWarning):
    """Category for warn-mode diagnostics (filterable without muting all
    UserWarnings)."""


class Diagnostic:
    """One structured finding: severity, rule id, location, vars, fix."""

    __slots__ = ("severity", "rule", "message", "block_idx", "op_idx",
                 "var_names", "suggestion", "block_path", "rank")

    def __init__(self, severity, rule, message, block_idx=None, op_idx=None,
                 var_names=(), suggestion=None, block_path=None, rank=None):
        self.severity = severity
        self.rule = rule
        self.message = message
        self.block_idx = block_idx
        self.op_idx = op_idx
        self.var_names = tuple(var_names)
        self.suggestion = suggestion
        # enclosing control-flow chain of block_idx, e.g.
        # "while@block0.op3 > conditional_block@block1.op2" (None/"" at top
        # level) — makes sub-block findings actionable from proglint output
        self.block_path = block_path
        # rank the finding belongs to, for world-level (DL1xx/MEM) rules
        self.rank = rank

    def location(self):
        if self.op_idx is None:
            where = "program"
        else:
            where = "block %s op %s" % (
                0 if self.block_idx is None else self.block_idx, self.op_idx)
            if self.block_path:
                where += " in %s" % self.block_path
        if self.rank is not None:
            where = "rank %s %s" % (self.rank, where)
        return where

    def format(self):
        line = "%s %s [%s]: %s" % (self.rule, self.severity.upper(),
                                   self.location(), self.message)
        if self.suggestion:
            line += "\n    fix: %s" % self.suggestion
        return line

    def __repr__(self):
        return "Diagnostic(%s, %s, %s)" % (self.rule, self.severity,
                                           self.location())


class VerifyReport:
    """Ordered diagnostic list with severity views and a readable render."""

    def __init__(self, diagnostics=(), label="program"):
        self.diagnostics = list(diagnostics)
        self.label = label

    def add(self, *args, **kwargs):
        self.diagnostics.append(Diagnostic(*args, **kwargs))

    def extend(self, other):
        self.diagnostics.extend(other.diagnostics)

    @property
    def errors(self):
        return [d for d in self.diagnostics if d.severity == ERROR]

    @property
    def warnings(self):
        return [d for d in self.diagnostics if d.severity == WARNING]

    @property
    def infos(self):
        return [d for d in self.diagnostics if d.severity == INFO]

    def by_rule(self, rule):
        return [d for d in self.diagnostics if d.rule == rule]

    @property
    def ok(self):
        """No errors and no warnings (infos are advisory)."""
        return not self.errors and not self.warnings

    def format(self, max_items=50, include_info=True):
        shown = [d for d in self.diagnostics
                 if include_info or d.severity != INFO]
        head = "static check of %s: %d error(s), %d warning(s), %d info" % (
            self.label, len(self.errors), len(self.warnings),
            len(self.infos))
        lines = [head]
        for d in shown[:max_items]:
            lines.append("  " + d.format().replace("\n", "\n  "))
        if len(shown) > max_items:
            lines.append("  ... %d more" % (len(shown) - max_items))
        return "\n".join(lines)

    def __repr__(self):
        return "<VerifyReport %s: %dE/%dW/%dI>" % (
            self.label, len(self.errors), len(self.warnings),
            len(self.infos))


class ProgramVerificationError(RuntimeError):
    """Raised by FLAGS_static_check=error: the full diagnostic report, not
    an XLA traceback."""

    def __init__(self, report):
        self.report = report
        super().__init__(report.format())


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------

# ops the executor strips before lowering (legacy plumbing)
_PLUMBING = ("feed", "fetch")

# ops whose execution has effects beyond their declared outputs: always live
# for the WF004 reachability walk
_SIDE_EFFECT_OPS = frozenset((
    "while", "conditional_block", "recurrent", "py_func",
    "send", "recv", "send_barrier", "fetch_barrier", "prefetch",
    "listen_and_serv", "save", "save_combine", "load", "load_combine",
    "print", "assert", "c_sync_calc_stream", "c_sync_comm_stream",
    "c_gen_nccl_id", "c_comm_init", "c_wait_comm", "c_wait_compute",
))

# program-level collectives (mirrors core/lowering._AXIS_OPS + broadcastish
# variants); DL003 checks their ring_id discipline
_COLLECTIVE_OPS = frozenset((
    "c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
    "c_allreduce_prod", "c_broadcast", "c_allgather", "c_reducescatter",
    "c_shard_slice", "c_allreduce_qsum", "c_reducescatter_q",
    "c_allgather_q", "allreduce", "broadcast",
))

# the reduction collectives that carry the folded 1/nranks averaging scale
# (transpiler/collective.py); DL005 checks the attr against the world
_SCALED_REDUCE_OPS = frozenset((
    "c_allreduce_sum", "c_reducescatter", "c_allreduce_qsum",
    "c_reducescatter_q",
))

_GRAD_SUFFIX = "@GRAD"


def _is_gradish(name):
    return name.endswith(_GRAD_SUFFIX) or (_GRAD_SUFFIX + "@") in name


def _runtime_ops(block):
    """(op_idx, op) pairs excluding legacy feed/fetch plumbing; indices are
    positions in block.ops so diagnostics point at the real op list."""
    return [(i, op) for i, op in enumerate(block.ops)
            if op.type not in _PLUMBING]


def _block_paths(program):
    """Map block idx -> enclosing control-flow chain as a readable string
    (e.g. ``"while@block0.op3 > conditional_block@block1.op2"``; "" for the
    global block).  Built from the ``sub_block`` attr the control-flow
    layers stamp on while/conditional_block/recurrent ops, so a diagnostic
    raised inside a nested sub-block names the op chain that reaches it."""
    parent_edge = {}  # child block idx -> (op type, parent block idx, op idx)
    for blk in program.blocks:
        for op_idx, op in enumerate(blk.ops):
            sub = op.attr("sub_block")
            if sub is None:
                continue
            sub = getattr(sub, "idx", sub)  # attr may hold a Block or an int
            parent_edge[int(sub)] = (op.type, blk.idx, op_idx)
    paths = {}
    for blk in program.blocks:
        segs, idx, seen = [], blk.idx, set()
        while idx in parent_edge and idx not in seen:
            seen.add(idx)
            op_type, pidx, oidx = parent_edge[idx]
            segs.append("%s@block%d.op%d" % (op_type, pidx, oidx))
            idx = pidx
        paths[blk.idx] = " > ".join(reversed(segs))
    return paths


def _opdef_or_none(op_type):
    from .registry import get_op_def

    try:
        return get_op_def(op_type)
    except ValueError:
        return None
    except Exception:
        return None


def _shapes_conflict(declared, inferred):
    """True when two declared shapes cannot describe the same tensor:
    different rank, or a dim where both are static and differ (-1 is the
    symbolic batch wildcard and matches anything)."""
    if declared is None or inferred is None:
        return False
    if len(declared) != len(inferred):
        return True
    for d, i in zip(declared, inferred):
        if d >= 0 and i >= 0 and d != i:
            return True
    return False


def _canon_dtype(dtype):
    """Canonicalize declared dtypes through the same 64->32 bit truncation
    JAX applies when x64 is disabled, so TS001 compares what actually runs
    (the IR declares reference dtypes like int64; eval_shape yields the
    truncated int32)."""
    if dtype is None:
        return None
    from jax import config as jax_config

    if not getattr(jax_config, "jax_enable_x64", False):
        return {"int64": "int32", "uint64": "uint32",
                "float64": "float32"}.get(dtype, dtype)
    return dtype


def _dtype_kind(dtype):
    if dtype is None:
        return None
    if dtype.startswith(("float", "bfloat")):
        return "float"
    if dtype == "bool":
        return "bool"
    return "int"


# ---------------------------------------------------------------------------
# family 1: well-formedness
# ---------------------------------------------------------------------------


def _ancestor_names(block):
    names = set()
    blk = block.parent_block
    while blk is not None:
        names.update(blk.vars)
        blk = blk.parent_block
    return names


def _check_wellformed(program, feed_names, fetch_names, scope_names, rep):
    feed = set(feed_names)
    fetch = set(fetch_names)
    scope = set(scope_names or ())

    # reads across ALL blocks: sub-block ops consume outer names through the
    # trace env without appearing in the outer block's op list
    global_reads = set()
    for blk in program.blocks:
        for _, op in _runtime_ops(blk):
            global_reads.update(n for n in op.input_arg_names if n)

    for blk in program.blocks:
        defined = feed | scope | _ancestor_names(blk)
        ops = _runtime_ops(blk)
        for op_idx, op in ops:
            opdef = _opdef_or_none(op.type)
            if opdef is None:
                rep.add(ERROR, "WF002",
                        "op %r is not registered" % op.type,
                        blk.idx, op_idx,
                        suggestion="register it via core.registry."
                        "register_op or remove the op")
                # unknown slots can't be checked; still track writes below
            else:
                bad_in = [s for s in op.inputs if s not in opdef.input_slots]
                bad_out = [s for s in op.outputs
                           if s not in opdef.output_slots]
                for s in bad_in:
                    rep.add(ERROR, "WF005",
                            "op %s has no input slot %r (declares %s)"
                            % (op.type, s, list(opdef.input_slots)),
                            blk.idx, op_idx, op.input(s))
                for s in bad_out:
                    rep.add(ERROR, "WF005",
                            "op %s has no output slot %r (declares %s)"
                            % (op.type, s, list(opdef.output_slots)),
                            blk.idx, op_idx, op.output(s))

            optional = set()
            if opdef is not None:
                optional = {s for s in op.inputs
                            if s in opdef.optional_inputs
                            or s.startswith(("GRAD@", "Out@"))}
            for slot, names in op.inputs.items():
                for n in names:
                    if not n or n in defined:
                        continue
                    if _is_gradish(n):
                        continue  # implicit-zero grads are legitimate holes
                    if slot in optional:
                        continue  # lowering resolves absent optionals to None
                    v = blk._find_var_recursive(n)
                    if v is None:
                        rep.add(ERROR, "WF001",
                                "op %s reads %r which has no variable entry "
                                "in any reachable block" % (op.type, n),
                                blk.idx, op_idx, (n,),
                                suggestion="declare the variable or fix the "
                                "name in slot %r" % slot)
                        continue
                    if v.persistable or v.is_data:
                        continue  # scope-resident / feed target
                    if getattr(v, "type", None) == "lod_tensor_array":
                        continue  # trace-local; first array_write creates it
                    rep.add(ERROR, "WF001",
                            "op %s reads %r before any op produces it (not "
                            "persistable, not a feed)" % (op.type, n),
                            blk.idx, op_idx, (n,),
                            suggestion="feed it, mark it persistable, or "
                            "reorder the producing op before op %d" % op_idx)
            for n in op.output_arg_names:
                if n:
                    defined.add(n)

        # WF003: outputs nobody consumes (advisory — auxiliary outputs like
        # softmax_with_cross_entropy's Softmax are routinely unused)
        for op_idx, op in ops:
            for n in op.output_arg_names:
                if not n or n in global_reads or n in fetch:
                    continue
                v = blk._find_var_recursive(n)
                if v is not None and (v.persistable or v.is_data):
                    continue
                if _is_gradish(n):
                    continue  # param grads are consumed by the runtime (PS
                    # send / fetch-time grad exchange), not always by an op
                rep.add(INFO, "WF003",
                        "output %r of op %s is never read, fetched, or "
                        "persisted" % (n, op.type),
                        blk.idx, op_idx, (n,))

    _check_reachability(program, fetch_names, rep)


def _check_reachability(program, fetch_names, rep):
    """WF004: reverse reachability from the fetch targets + persistable
    writes.  Needs fetch targets to mean anything — skipped without them."""
    if not fetch_names:
        return
    block = program.global_block()
    ops = _runtime_ops(block)
    needed = set(fetch_names)
    # PS trainer: param grads have no in-program consumer — the executor's
    # per-step grad exchange fetches and ships them (core/executor.py
    # ps_grad_names), so they are live roots for reachability
    ps_meta = getattr(program, "_ps_trainer", None)
    if ps_meta:
        needed.update(ps_meta.get("param_grad", {}).values())
    live = set()
    sub_reads = set()
    for blk in program.blocks:
        if blk.idx == 0:
            continue
        for _, op in _runtime_ops(blk):
            sub_reads.update(n for n in op.input_arg_names if n)
    for op_idx, op in reversed(ops):
        opdef = _opdef_or_none(op.type)
        is_live = (
            op.type in _SIDE_EFFECT_OPS
            or op.type in _COLLECTIVE_OPS
            or (opdef is not None and opdef.stateful)
            or op.has_attr("sub_block")
        )
        if not is_live:
            for n in op.output_arg_names:
                if not n:
                    continue
                if n in needed or n in sub_reads:
                    is_live = True
                    break
                v = block._find_var_recursive(n)
                if v is not None and v.persistable:
                    is_live = True
                    break
                # a parameter's gradient is the PRODUCT of a grad program:
                # the runtime (optimizer application, PS send, user fetch
                # of append_backward results) consumes it, not an op
                base = n.split("@RENAME@")[0].split("@D")[0]
                if base.endswith(_GRAD_SUFFIX):
                    fwd = block._find_var_recursive(
                        base[: -len(_GRAD_SUFFIX)])
                    if fwd is not None and fwd.persistable:
                        is_live = True
                        break
        if is_live:
            live.add(op_idx)
            needed.update(n for n in op.input_arg_names if n)
    for op_idx, op in ops:
        if op_idx not in live:
            rep.add(WARNING, "WF004",
                    "op %s (outputs %s) cannot reach any fetch target or "
                    "persistable state — dead code"
                    % (op.type, [n for n in op.output_arg_names if n]),
                    block.idx, op_idx,
                    tuple(n for n in op.output_arg_names if n),
                    suggestion="remove the op or fetch one of its outputs")


# ---------------------------------------------------------------------------
# family 2: type / shape flow
# ---------------------------------------------------------------------------


def _check_type_shape(program, rep):
    """Re-run the registry's shape inference (symbolic batch dim) over a
    CLONE of the program and compare against the declared metadata.  The
    clone is essential: ``run_infer_shape`` writes shapes/dtypes into the
    block, and the verifier must never mutate the program it checks."""
    clone = program.clone()
    for blk in clone.blocks:
        for op_idx, op in _runtime_ops(blk):
            opdef = _opdef_or_none(op.type)
            if opdef is None:
                continue  # WF002 already reported
            in_names = set(op.input_arg_names)
            declared = {}
            for n in op.output_arg_names:
                if not n or n in declared or n in in_names:
                    continue  # in-place outputs keep their declared meta
                v = blk._find_var_recursive(n)
                if v is None:
                    continue
                declared[n] = (v.shape, v.dtype)
                v.dtype = None  # let inference re-derive the dtype
            try:
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")
                    opdef.run_infer_shape(op, blk)
            except Exception:
                for n, (shape, dtype) in declared.items():
                    v = blk._find_var_recursive(n)
                    v.shape, v.dtype = shape, dtype
                continue
            for n, (shape, dtype) in declared.items():
                v = blk._find_var_recursive(n)
                if v.dtype is None:
                    v.dtype = dtype  # inference had no opinion
                elif (dtype is not None
                      and _canon_dtype(v.dtype) != _canon_dtype(dtype)):
                    rep.add(ERROR, "TS001",
                            "op %s output %r is declared %s but the "
                            "lowering produces %s"
                            % (op.type, n, dtype, v.dtype),
                            blk.idx, op_idx, (n,),
                            suggestion="fix the var's declared dtype (or "
                            "the op's lowering/infer_shape)")
                if shape is not None and _shapes_conflict(shape, v.shape):
                    rep.add(ERROR, "TS002",
                            "op %s output %r is declared shape %s but the "
                            "lowering produces %s"
                            % (op.type, n, list(shape), list(v.shape)),
                            blk.idx, op_idx, (n,),
                            suggestion="fix the var's declared shape (or "
                            "the op's lowering/infer_shape)")

    _check_grad_consistency(program, rep)


def _check_grad_consistency(program, rep):
    """TS003: every ``X@GRAD`` var must agree with its forward var ``X`` —
    grad-program vs forward consistency through backward.py's naming.
    Pass-local renames (``@RENAME@k``, ``@D2``) are stripped first.  AMP
    mixed precision legitimately narrows float widths, so only kind-level
    dtype drift (float vs int/bool) and shape contradictions are flagged."""
    for blk in program.blocks:
        for name, gvar in list(blk.vars.items()):
            base = name.split("@RENAME@")[0].split("@D")[0]
            if not base.endswith(_GRAD_SUFFIX):
                continue
            fwd_name = base[: -len(_GRAD_SUFFIX)]
            fvar = blk._find_var_recursive(fwd_name)
            if fvar is None:
                continue
            if _shapes_conflict(fvar.shape, gvar.shape):
                rep.add(WARNING, "TS003",
                        "grad var %r has shape %s but forward var %r has "
                        "shape %s"
                        % (name, list(gvar.shape), fwd_name,
                           list(fvar.shape)),
                        blk.idx, None, (name, fwd_name),
                        suggestion="the grad maker or infer_shape for the "
                        "producing op disagrees with the forward")
            fk, gk = _dtype_kind(fvar.dtype), _dtype_kind(gvar.dtype)
            if fk is not None and gk is not None and fk != gk:
                rep.add(WARNING, "TS003",
                        "grad var %r is %s but forward var %r is %s"
                        % (name, gvar.dtype, fwd_name, fvar.dtype),
                        blk.idx, None, (name, fwd_name))


# ---------------------------------------------------------------------------
# family 3: donation / aliasing hazards
# ---------------------------------------------------------------------------


def _check_donation(program, feed_names, fetch_names, rep):
    """The executor donates every persistable var the block overwrites
    (core/lowering.py BlockPlan rw_names + the FLAGS_layout_match_params
    carry dict), so its pre-step buffer is dead the moment the update runs.
    DA001 flags a read of such a var AFTER its in-place update: the reader
    silently observes the updated value and, under donation, the buffer it
    "remembers" no longer exists.  DA003 is program-level race detection:
    two writes to one scope var where the second write never reads the
    first — no data dependency orders them, so a rewriter that reorders
    ops (IR passes, transpilers) silently changes which value survives."""
    from .lowering import analyze_block

    if getattr(program, "_no_donate", False):
        donated = set()
    else:
        block = program.global_block()
        try:
            ext, _written, persist_written = analyze_block(block, feed_names)
        except Exception:
            return
        donated = set(ext) & set(persist_written)

    from ..framework import OP_ROLE_KEY, OpRole

    block = program.global_block()
    ops = _runtime_ops(block)

    writes = {}
    for op_idx, op in ops:
        for n in op.output_arg_names:
            if n:
                writes.setdefault(n, []).append(op_idx)

    for name in sorted(donated):
        idxs = writes.get(name, ())
        if not idxs:
            continue
        first_w = idxs[0]
        wop = block.ops[first_w]
        role = int(wop.attr(OP_ROLE_KEY) or 0)
        if not role & OpRole.Optimize:
            # a pure (re)definition — an LR-schedule counter increment or
            # a metric accumulator — where the later read WANTS the new
            # value; only optimizer updates invalidate a param's old buffer
            continue
        for op_idx, op in ops:
            if op_idx <= first_w:
                continue
            ins = op.input_arg_names
            if name in ins and name not in op.output_arg_names:
                rep.add(ERROR, "DA001",
                        "op %s reads donated var %r after op %d updated it "
                        "in place — the pre-update buffer is consumed by "
                        "donation and the read observes the new value"
                        % (op.type, name, first_w),
                        block.idx, op_idx, (name,),
                        suggestion="read %r before the update at op %d, or "
                        "snapshot it into a fresh var first"
                        % (name, first_w))
                break

    fetched_donated = sorted(donated & set(fetch_names))
    for name in fetched_donated:
        rep.add(INFO, "DA002",
                "fetch target %r is donated and updated in this block; the "
                "fetch observes the post-update value" % name,
                var_names=(name,))

    # DA003: double write with no intervening read of the first value
    for name, idxs in sorted(writes.items()):
        if len(idxs) < 2:
            continue
        v = block._find_var_recursive(name)
        if v is None or not v.persistable:
            continue  # trace-local SSA renames handle temporaries
        for prev, nxt in zip(idxs, idxs[1:]):
            nop = block.ops[nxt]
            if name not in nop.input_arg_names:
                rep.add(WARNING, "DA003",
                        "op %s overwrites %r (already written by op %d) "
                        "without reading it — no data dependency orders "
                        "the two writes" % (nop.type, name, prev),
                        block.idx, nxt, (name,),
                        suggestion="drop the dead first write or make the "
                        "second write read the var")
                break


# ---------------------------------------------------------------------------
# family 4: distributed lint
# ---------------------------------------------------------------------------


def _check_collectives(program, rep, expected_nranks=None):
    """DL003 ring_id discipline + DL005 world-size agreement for
    program-level collectives.

    DL005 compares every world-size-derived constant against the expected
    collective world size: the transpiler stamps programs with
    ``_collective_meta`` (nranks/endpoints/rank) at transpile time, and the
    elastic re-quorum layer passes ``expected_nranks`` for the NEW world —
    a stale 1/nranks gradient scale or c_comm_init nranks attr means the
    program was transpiled for a cluster that no longer exists."""
    from ..framework import OP_ROLE_KEY, OpRole

    meta = getattr(program, "_collective_meta", None) or {}
    nranks = expected_nranks if expected_nranks else meta.get("nranks")
    if (expected_nranks and meta.get("nranks")
            and int(meta["nranks"]) != int(expected_nranks)):
        rep.add(ERROR, "DL005",
                "program was transpiled for %d ranks but the collective "
                "world now has %d members"
                % (meta["nranks"], expected_nranks),
                suggestion="re-run GradAllReduce.transpile for the new "
                "endpoint list before recompiling")
    paths = _block_paths(program)
    for blk in program.blocks:
        rings = []
        missing = []
        has_allreduce = False
        for op_idx, op in _runtime_ops(blk):
            if op.type not in _COLLECTIVE_OPS:
                continue
            if op.type.startswith("c_allreduce"):
                has_allreduce = True
            ring = op.attr("ring_id")
            if ring is None:
                missing.append((op_idx, op))
                continue
            if int(ring) < 0:
                rep.add(ERROR, "DL003",
                        "collective op %s has negative ring_id %s"
                        % (op.type, ring), blk.idx, op_idx,
                        block_path=paths.get(blk.idx))
            else:
                rings.append(int(ring))
        for op_idx, op in missing:
            sev = WARNING if not rings else ERROR
            rep.add(sev, "DL003",
                    "collective op %s has no ring_id attr%s"
                    % (op.type,
                       " while others in the block use rings %s"
                       % sorted(set(rings)) if rings else ""),
                    blk.idx, op_idx,
                    block_path=paths.get(blk.idx),
                    suggestion="assign a ring_id (transpiler round-robins "
                    "0..nrings-1)")
        if not nranks or int(nranks) <= 0:
            continue
        for op_idx, op in _runtime_ops(blk):
            if op.type == "c_comm_init":
                got = op.attr("nranks")
                if got is not None and int(got) != int(nranks):
                    rep.add(ERROR, "DL005",
                            "c_comm_init nranks=%d but the collective world "
                            "has %d members" % (int(got), int(nranks)),
                            blk.idx, op_idx,
                            suggestion="re-transpile startup for the "
                            "current endpoint list")
            elif (op.type in _SCALED_REDUCE_OPS
                  and op.attr("scale") is not None
                  and float(op.attr("scale")) != 1.0):
                # the folded-form averaging scale the transpiler stamps on
                # the reduction collective itself: must be exactly 1/world
                # (scale == 1.0 is a plain sum — user collectives keep it)
                got = float(op.attr("scale"))
                if abs(got * int(nranks) - 1.0) > 1e-6:
                    rep.add(ERROR, "DL005",
                            "folded gradient scale %.8g on %s does not "
                            "match 1/%d — program was transpiled for world "
                            "size %s"
                            % (got, op.type, int(nranks),
                               round(1.0 / got) if got else "?"),
                            blk.idx, op_idx,
                            var_names=tuple(op.input("X")),
                            suggestion="re-run the collective transpiler "
                            "so the folded scale matches the %d-member "
                            "world" % int(nranks))
            elif (has_allreduce and op.type == "scale"
                  and op.input_arg_names == op.output_arg_names
                  and int(op.attr(OP_ROLE_KEY) or 0) == int(OpRole.Backward)):
                # the in-place Backward-role scale the transpiler inserts
                # after the loss grad: must be exactly 1/world
                got = float(op.attr("scale") or 0.0)
                if abs(got * int(nranks) - 1.0) > 1e-6:
                    rep.add(ERROR, "DL005",
                            "gradient scale %.8g does not match 1/%d — "
                            "program was transpiled for world size %s"
                            % (got, int(nranks),
                               round(1.0 / got) if got else "?"),
                            blk.idx, op_idx,
                            var_names=tuple(op.input_arg_names),
                            suggestion="re-run GradAllReduce.transpile so "
                            "the loss-grad scale matches the %d-member "
                            "world" % int(nranks))


_ZERO1_DEQUANT_OPS = ("c_allreduce_qsum", "c_reducescatter_q")
_ZERO1_WORLD_OPS = ("c_shard_slice", "c_reducescatter", "c_reducescatter_q",
                    "c_allgather", "c_allgather_q", "c_allreduce_qsum",
                    "c_quant_pack")


def _check_zero1(program, rep, expected_nranks=None):
    """DL006: ZeRO-1 / quantized-exchange structural invariants.

    (a) shard coverage — under ``_collective_meta["mode"] == "zero1"``
        every param in the shard table is owned by EXACTLY one update
        chain: one c_shard_slice, one optimizer write of the shard, one
        c_allgather back (a double-owned shard means two ranks' updates
        race on the same rows; a missing leg means rows never update).
    (b) dequant-scale pinning — a c_allreduce_qsum / c_reducescatter_q
        must read the Scale its own c_quant_pack produced, with matching
        bucket/dtype/nranks geometry.  A drifted scale dequantizes with
        the wrong magnitudes and silently corrupts every gradient.
    (c) shard-world agreement — nranks baked into the shard/quant ops
        must equal the collective world (``expected_nranks`` after an
        elastic re-quorum), mirroring what DL005 does for the scales.
    """
    meta = getattr(program, "_collective_meta", None) or {}
    nranks = expected_nranks if expected_nranks else meta.get("nranks")
    for blk in program.blocks:
        ops = _runtime_ops(blk)
        producers = {}
        for op_idx, op in ops:
            for nm in op.output_arg_names:
                if nm:
                    producers[nm] = (op_idx, op)
        for op_idx, op in ops:
            # (b) dequant pinned to its quantize op
            if op.type in _ZERO1_DEQUANT_OPS:
                xs, ss = op.input("X"), op.input("Scale")
                if len(xs) == 1 and len(ss) == 1:
                    prod = producers.get(xs[0])
                    if prod is None or prod[1].type != "c_quant_pack":
                        rep.add(ERROR, "DL006",
                                "%s payload %r is not the output of a "
                                "c_quant_pack op" % (op.type, xs[0]),
                                blk.idx, op_idx, (xs[0],),
                                suggestion="pack the tensor with "
                                "c_quant_pack in the same block")
                    else:
                        qidx, qop = prod
                        if qop.output("Scale") != ss:
                            rep.add(ERROR, "DL006",
                                    "%s dequantizes with scale %r but its "
                                    "payload was packed with %r (op %d) — "
                                    "the dequant scale must be pinned to "
                                    "the quantize op's"
                                    % (op.type, ss[0],
                                       (qop.output("Scale") or ["?"])[0],
                                       qidx),
                                    blk.idx, op_idx, tuple(ss),
                                    suggestion="wire Scale to op %d's "
                                    "Scale output" % qidx)
                        for a in ("bucket", "dtype", "nranks"):
                            if op.attr(a) != qop.attr(a):
                                rep.add(ERROR, "DL006",
                                        "%s %s=%r drifted from its "
                                        "c_quant_pack's %s=%r (op %d)"
                                        % (op.type, a, op.attr(a), a,
                                           qop.attr(a), qidx),
                                        blk.idx, op_idx, tuple(xs),
                                        suggestion="keep the pack/dequant "
                                        "pair's quantization geometry "
                                        "identical")
            # (c) shard world agreement
            if (nranks and op.type in _ZERO1_WORLD_OPS):
                got = op.attr("nranks")
                if got is not None and int(got) > 1 \
                        and int(got) != int(nranks):
                    rep.add(ERROR, "DL006",
                            "%s was built for nranks=%d but the collective "
                            "world has %d members"
                            % (op.type, int(got), int(nranks)),
                            blk.idx, op_idx,
                            suggestion="re-run the collective transpiler "
                            "for the current world")

    # (a) shard coverage over the global block's update chains
    shards = meta.get("zero1_shards")
    if meta.get("mode") != "zero1" or shards is None:
        return
    from ..framework import OP_ROLE_KEY, OpRole

    block = program.global_block()
    ops = _runtime_ops(block)
    updaters = [(i, op) for i, op in ops
                if int(op.attr(OP_ROLE_KEY) or 0) & OpRole.Optimize
                and op.output("ParamOut")]
    if not updaters:
        return  # startup / inference program: no update chains to cover
    slices, gathers = {}, {}
    for i, op in ops:
        if op.type == "c_shard_slice" and len(op.input("X")) == 1:
            slices.setdefault(op.input("X")[0], []).append((i, op))
        elif op.type in ("c_allgather", "c_allgather_q") \
                and len(op.output("Out")) == 1:
            gathers.setdefault(op.output("Out")[0], []).append((i, op))

    def _owners(name):
        return [i for i, op in updaters if name in op.output("ParamOut")]

    def _exactly_one(idxs, what, param):
        if len(idxs) == 1:
            return
        pin = idxs[-1] if idxs else None
        rep.add(ERROR, "DL006",
                "param %r is covered by %d %s (expected exactly one) — "
                "the shard assignment does not own every row exactly once"
                % (param, len(idxs), what),
                block.idx, pin, (param,),
                suggestion="re-run ShardedGradAllReduce.transpile; every "
                "param must map to one shard-update chain")

    covered = set()
    for param, entry in sorted(shards.items()):
        covered.add(param)
        if entry.get("sharded"):
            sl = slices.get(param, [])
            _exactly_one([i for i, _ in sl], "c_shard_slice ops", param)
            _exactly_one([i for i, _ in gathers.get(param, [])],
                         "c_allgather writes", param)
            if len(sl) == 1:
                shard_var = (sl[0][1].output("Out") or [None])[0]
                _exactly_one(_owners(shard_var), "optimizer shard updates",
                             param)
        else:
            _exactly_one(_owners(param), "optimizer updates", param)
            if param in slices or param in gathers:
                rep.add(ERROR, "DL006",
                        "param %r is marked replicated in the shard table "
                        "but has shard ops in the block" % param,
                        block.idx, slices.get(param, gathers.get(param))
                        [0][0], (param,))
    # every optimizer-updated param must appear in the shard table
    for i, op in updaters:
        for name in op.output("ParamOut"):
            base = name[:-len("@ZSHARD")] if name.endswith("@ZSHARD") \
                else name
            if base not in covered:
                rep.add(ERROR, "DL006",
                        "optimizer updates %r but the ZeRO-1 shard table "
                        "does not cover it" % base, block.idx, i, (base,),
                        suggestion="re-transpile so the shard assignment "
                        "covers every trainable param")


def verify_transpiled(ps_state, rep=None):
    """Distributed lint over a DistributeTranspiler result (PSState):
    placement, send/recv pairing, and trainer/pserver duplication."""
    if rep is None:
        rep = VerifyReport(label="transpiled PS programs")

    trainer = ps_state.trainer_program
    meta = getattr(trainer, "_ps_trainer", None) or {}
    param_to_ep = dict(getattr(ps_state, "param_map", None) or
                       meta.get("param_to_ep", {}))
    param_grad = dict(meta.get("param_grad", {}))
    geo = bool(meta.get("geo"))

    # DL001: every param owned by exactly one pserver, and the trainer's
    # placement map agrees with the servers' owned lists
    owners = {}
    for ep, prog in ps_state.pserver_programs.items():
        smeta = getattr(prog, "_ps_server", None) or {}
        for p in smeta.get("params", ()):
            owners.setdefault(p, []).append(ep)
    for p, eps in sorted(owners.items()):
        if len(eps) != 1:
            rep.add(ERROR, "DL001",
                    "param %r is assigned to %d pservers (%s)"
                    % (p, len(eps), sorted(eps)), var_names=(p,),
                    suggestion="each param must have exactly one owner; "
                    "fix the transpiler placement map")
    for p, ep in sorted(param_to_ep.items()):
        got = owners.get(p, [])
        if not got:
            rep.add(ERROR, "DL001",
                    "param %r is mapped to %s by the trainer but no "
                    "pserver program owns it" % (p, ep), var_names=(p,))
        elif got != [ep]:
            rep.add(ERROR, "DL001",
                    "trainer maps param %r to %s but pserver(s) %s own it"
                    % (p, ep, got), var_names=(p,))
    for p in sorted(set(owners) - set(param_to_ep)):
        rep.add(ERROR, "DL001",
                "pserver(s) %s own param %r the trainer never sends to"
                % (owners[p], p), var_names=(p,))

    # DL002: send/recv var pairing — every placed param needs a grad the
    # trainer ships, every server-side grad key must map back to a placed
    # param, and no grad may serve two params
    for p in sorted(param_to_ep):
        if p not in param_grad:
            rep.add(ERROR, "DL002",
                    "param %r is placed on a pserver but has no grad to "
                    "send" % p, var_names=(p,),
                    suggestion="the optimizer op for %r vanished during "
                    "transpile" % p)
    grad_owner = {}
    for p, g in sorted(param_grad.items()):
        if g in grad_owner:
            rep.add(ERROR, "DL002",
                    "grad %r is paired with both %r and %r"
                    % (g, grad_owner[g], p), var_names=(g, p))
        grad_owner[g] = p
    for ep, prog in ps_state.pserver_programs.items():
        smeta = getattr(prog, "_ps_server", None) or {}
        for g, p in sorted(smeta.get("grad_map", {}).items()):
            if param_grad.get(p) != g:
                rep.add(ERROR, "DL002",
                        "pserver %s expects grad %r for param %r but the "
                        "trainer sends %r"
                        % (ep, g, p, param_grad.get(p)), var_names=(g, p))

    # DL004: a side-effecting (optimizer) op left in BOTH halves applies
    # the update twice per step.  Geo-SGD keeps trainer-local optimizers by
    # design (the server applies deltas, not grads), so it is exempt.
    if not geo:
        from ..framework import OP_ROLE_KEY, OpRole

        def opt_params(prog):
            out = set()
            for op in prog.global_block().ops:
                if int(op.attr(OP_ROLE_KEY) or 0) & OpRole.Optimize:
                    pn = op.input("Param")
                    if pn:
                        out.add((op.type, pn[0]))
            return out

        trainer_opts = opt_params(trainer)
        for ep, prog in ps_state.pserver_programs.items():
            smeta = getattr(prog, "_ps_server", None) or {}
            server_opts = opt_params(smeta.get("optimize_program", None)
                                     or prog)
            for op_type, p in sorted(trainer_opts & server_opts):
                rep.add(ERROR, "DL004",
                        "optimizer op %s(Param=%r) runs on BOTH the "
                        "trainer and pserver %s — the update applies "
                        "twice per step" % (op_type, p, ep),
                        var_names=(p,),
                        suggestion="strip Optimize-role ops from the "
                        "trainer program (non-geo modes)")

    return rep


# ---------------------------------------------------------------------------
# top-level entry points
# ---------------------------------------------------------------------------


def verify_program(program, feed_names=(), fetch_names=(), scope_names=None,
                   label=None, expected_nranks=None):
    """Run all single-program rule families; returns a VerifyReport.

    `feed_names`/`fetch_names` sharpen WF001/WF004/DA002 exactly like the
    executor's view; `scope_names` (names resident in the run scope) keeps
    WF001 precise for programs reading pre-seeded scope vars.
    `expected_nranks` asserts the collective world size the program must be
    transpiled for (DL005) — defaults to the program's own transpile-time
    stamp, so passing the post-requorum world size catches stale rewrites."""
    rep = VerifyReport(label=label or ("program #%d"
                                       % getattr(program, "_uid", -1)))
    checks = (
        lambda: _check_wellformed(program, feed_names, fetch_names,
                                  scope_names, rep),
        lambda: _check_type_shape(program, rep),
        lambda: _check_donation(program, feed_names, fetch_names, rep),
        lambda: _check_collectives(program, rep,
                                   expected_nranks=expected_nranks),
        lambda: _check_zero1(program, rep,
                             expected_nranks=expected_nranks),
    )
    for chk in checks:
        try:
            chk()
        except Exception as exc:  # a verifier crash must never kill a run
            warnings.warn("static check pass failed internally: %r" % exc,
                          ProgramVerifyWarning, stacklevel=2)
    return rep


def _mode():
    from .. import flags

    return flags.flag("static_check") or "off"


def _dispatch(rep, mode):
    """Shared warn/error policy: count every error+warning diagnostic into
    the telemetry registry, warn once with the report, and in error mode
    raise the readable report when any error-severity finding exists."""
    from . import telemetry

    flagged = rep.errors + rep.warnings
    if not flagged:
        return rep
    for d in flagged:
        telemetry.inc("static_check_warnings", 1, rule=d.rule)
    if mode == "error" and rep.errors:
        raise ProgramVerificationError(rep)
    warnings.warn(rep.format(include_info=False), ProgramVerifyWarning,
                  stacklevel=3)
    return rep


_checked = {}
_CHECKED_CAP = 1024


def check_before_compile(program, feed_names, fetch_names, scope=None,
                         feed_shapes=None):
    """Executor compile-path hook (cache-miss only).  Flag-gated:
    ``off`` returns after one flag read; ``warn`` logs + counts; ``error``
    raises ProgramVerificationError.  Results are memoized per (program,
    version, signature) so repeated compiles of one program (new feed
    shapes) don't re-verify.

    Beyond the single-program families, this runs the per-rank subset of
    the world-level checks (core/world_analysis.py): DL103 divergent
    control flow, DL104-lite ring allocation, and the MEM001-003 static
    peak-HBM estimator — `feed_shapes` (name -> concrete shape) lets the
    estimate use the real batch instead of -1 placeholders, so the
    FLAGS_hbm_budget_bytes gate fires pre-compile instead of on chip."""
    mode = _mode()
    if mode == "off":
        return None
    shape_sig = tuple(sorted((n, tuple(s))
                             for n, s in (feed_shapes or {}).items()))
    key = (getattr(program, "_uid", id(program)), program.version,
           tuple(sorted(feed_names)), tuple(fetch_names), shape_sig, mode)
    if key in _checked:
        return _checked[key]
    scope_names = set()
    s = scope
    while s is not None:
        try:
            scope_names.update(s.local_var_names())
        except Exception:
            pass
        s = getattr(s, "parent", None)
    rep = verify_program(program, feed_names, fetch_names, scope_names)
    try:
        from . import world_analysis

        world_analysis.annotate_rank_checks(program, rep, feed_names,
                                            fetch_names,
                                            feed_shapes=feed_shapes)
    except Exception as exc:  # estimator crash must never kill a run
        warnings.warn("static world check failed internally: %r" % exc,
                      ProgramVerifyWarning, stacklevel=2)
    if len(_checked) >= _CHECKED_CAP:
        _checked.clear()
    _checked[key] = rep
    return _dispatch(rep, mode)


def check_transpiled(ps_state):
    """Post-transpile hook (DistributeTranspiler pserver mode): same flag
    policy as check_before_compile, over the trainer/pserver split."""
    mode = _mode()
    if mode == "off":
        return None
    rep = verify_transpiled(ps_state)
    return _dispatch(rep, mode)
