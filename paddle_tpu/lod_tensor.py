"""LoDTensor construction helpers (reference
python/paddle/fluid/lod_tensor.py: create_lod_tensor:24,
create_random_int_lodtensor:114).

Padded-design mapping: the returned TpuTensor holds the flat [total, ...]
data (as the reference does) with the recursive sequence lengths recorded
as lod metadata; the sequence ops consume padded views built by lod.py."""

import numpy as np

from .core.scope import TpuTensor

__all__ = ["create_lod_tensor", "create_random_int_lodtensor"]


def create_lod_tensor(data, recursive_seq_lens, place=None):
    if isinstance(data, TpuTensor):
        t = TpuTensor()
        t.set(np.asarray(data.numpy()))
        t.set_recursive_sequence_lengths(recursive_seq_lens)
        return t
    if isinstance(data, list):
        # ragged python list of SCALAR sequences: flatten to [total, 1]
        # like the reference, which also asserts the caller's lengths match
        # (lod_tensor.py:24 "the length-based LoD ... should be consistent
        # with the data")
        new_lens = [len(seq) for seq in data]
        if (len(recursive_seq_lens) != 1
                or list(recursive_seq_lens[0]) != new_lens):
            raise ValueError(
                "recursive_seq_lens %s does not match the list structure "
                "(lengths %s)" % (recursive_seq_lens, new_lens))
        flat = []
        for seq in data:
            for v in seq:
                if isinstance(v, (list, tuple)):
                    raise ValueError(
                        "list data must hold scalar sequences; pass a "
                        "numpy array for multi-dim rows")
                flat.append(v)
        arr = np.asarray(flat).reshape(-1, 1)
        t = TpuTensor()
        t.set(arr)
        t.set_recursive_sequence_lengths([new_lens])
        return t
    arr = np.asarray(data)
    total = sum(recursive_seq_lens[-1])
    if arr.shape[0] != total:
        raise ValueError(
            "data rows (%d) must equal the sum of the last-level lengths "
            "(%d)" % (arr.shape[0], total))
    t = TpuTensor()
    t.set(arr)
    t.set_recursive_sequence_lengths(recursive_seq_lens)
    return t


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place, low,
                                high):
    total = sum(recursive_seq_lens[-1])
    data = np.random.randint(low, high + 1,
                             [total] + list(base_shape)).astype("int64")
    return create_lod_tensor(data, recursive_seq_lens, place)
