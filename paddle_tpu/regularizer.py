"""Weight-decay regularizers (parity: python/paddle/fluid/regularizer.py)."""

from .framework import default_main_program

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer",
           "append_regularization_ops"]


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        from . import layers

        decay = layers.scale(param, scale=self._coeff)
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        from . import layers

        sign = layers.sign(param)
        return layers.scale(sign, scale=self._coeff)


def append_regularization_ops(parameters_and_grads, regularization=None):
    """grad += coeff * penalty'(param) for each param with a regularizer
    (reference regularizer.py:append_regularization_ops)."""
    program = default_main_program()
    # current_block: under a conditional (GradientMergeOptimizer boundary
    # Switch) the decay ops must land in the branch with their inputs
    block = program.current_block()
    out = []
    for param, grad in parameters_and_grads:
        if grad is None:
            out.append((param, grad))
            continue
        reg = getattr(param, "regularizer", None) or regularization
        if reg is None:
            out.append((param, grad))
            continue
        with program._optimized_guard([param, grad]):
            decay = reg(param, grad, block)
            block.append_op(
                type="sum",
                inputs={"X": [grad, decay]},
                outputs={"Out": [grad]},
            )
        out.append((param, grad))
    return out


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
