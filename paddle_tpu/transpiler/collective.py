"""Collective transpilers: rewrite a single-device program into a
data-parallel program with explicit collectives.

Port of python/paddle/fluid/transpiler/collective.py (Collective:36,
GradAllReduce:178, LocalSGD:269).  The transpiled program carries
c_gen_nccl_id/c_comm_init in startup (structural on TPU — the mesh is the
communicator) and a c_allreduce_sum per gradient in main (the 1/nranks
averaging scale is folded into the reduce as a post-sum multiply — no
standalone scale op), keyed off the op_role_var {param, grad} annotations
exactly like the reference; the executor runs such programs under
shard_map with lax.psum as the allreduce.

Two extensions beyond the reference:

* ShardedGradAllReduce (FLAGS_collective_mode=zero1) applies ZeRO-1
  weight-update sharding (arXiv 2004.13336): per eligible gradient the
  allreduce becomes a reduce-scatter, the optimizer op is rewired to
  update only this rank's 1/nranks dim-0 shard of the param (its
  param-shaped state slots shrink to the shard, cutting optimizer-state
  HBM by nranks), and the updated shards are all-gathered back into the
  replicated param after the last optimizer op.

* FLAGS_allreduce_dtype=bf16|int8 (EQuARX, arXiv 2506.17615) swaps the
  f32 gradient exchange for a quantized one: c_quant_pack buckets the
  gradient with one f32 max-abs scale per (destination rank, bucket) and
  c_allreduce_qsum / c_reducescatter_q move only the narrow payload +
  scales over the wire.  f32 stays the bitwise-parity escape hatch.

Every transpile stamps `_collective_meta` with the world it was built for
plus the shard assignment and the analytic per-rank bytes-on-ICI per step
(`wire_bytes_per_step`) — the verifier (DL005/DL006), the elastic
re-quorum layer, telemetry, and bench.py's bytes-on-ICI column all read
from it.
"""

from ..flags import flag as _flag
from ..framework import OP_ROLE_KEY, OP_ROLE_VAR_KEY, OpRole

__all__ = ["Collective", "GradAllReduce", "ShardedGradAllReduce",
           "LocalSGD", "select_grad_transpiler"]

# the mesh axis name the executor's SPMD path runs collectives over
_DATA_AXIS = "data"
_F32 = 4  # bytes


def select_grad_transpiler(nrings=1):
    """The gradient-exchange transpiler FLAGS_collective_mode selects —
    the single switch shared by fleet's CollectiveOptimizer, the
    DistributeTranspiler collective mode, and the elastic re-quorum
    re-transpile (so a zero1 job re-shards for every new world)."""
    mode = str(_flag("collective_mode") or "allreduce")
    if mode == "zero1":
        return ShardedGradAllReduce(nrings)
    if mode != "allreduce":
        raise ValueError("unknown FLAGS_collective_mode=%r "
                         "(expected allreduce | zero1)" % mode)
    return GradAllReduce(nrings)


def _numel(shape):
    n = 1
    for d in (shape or ()):
        n *= int(d)
    return n


def _static_shape(v):
    return (v is not None and v.shape
            and all(int(d) > 0 for d in v.shape))


def _ceil_div(a, b):
    return -(-a // b)


def _payload_width(dtype):
    return 1 if dtype == "int8" else 2  # bf16


class Collective:
    mode = "allreduce"

    def __init__(self, nrings=1):
        self.nrings = nrings
        self.endpoints = None
        self.current_endpoint = None
        self.rank = 0
        self.nranks = 1
        self.main_program = None
        self.startup_program = None
        # per-ring accumulated exchange bytes: rings are load-balanced by
        # bytes, and sum(values) is the per-rank bytes-on-ICI per step
        self._ring_bytes = [0.0]

    def transpile(self, startup_program, main_program, rank, endpoints,
                  current_endpoint, wait_port=True):
        self.startup_program = startup_program
        self.main_program = main_program
        self.rank = rank
        if isinstance(endpoints, str):
            endpoints = endpoints.split(",")
        self.endpoints = endpoints
        self.current_endpoint = current_endpoint
        self.nranks = len(endpoints)
        self._ring_bytes = [0.0] * max(int(self.nrings), 1)
        # whole-world post-transpile check (FLAGS_static_check=error only):
        # keep pristine clones so world_analysis can materialize the
        # sibling ranks and match this rank's collective schedule against
        # them — a stale or divergent rewrite (DL101/DL102) raises here
        # instead of hanging the world at the first exchange.  The
        # materializer's own transpiles skip this (reentrancy guard).
        pristine_main = pristine_startup = None
        from ..core import analysis as _analysis
        from ..core import world_analysis as _world

        if (self.nranks > 1 and _analysis._mode() == "error"
                and not _world._materializing()):
            pristine_main = main_program.clone()
            pristine_startup = startup_program.clone()
        self._transpile_startup_program()
        self._transpile_main_program()
        # world-size provenance for the static verifier (DL005/DL006) and
        # the elastic re-quorum layer: which cluster this program was built
        # for, how the update is sharded, and what one step costs on ICI
        meta = {"nranks": self.nranks, "rank": rank,
                "endpoints": list(endpoints), "nrings": self.nrings,
                "mode": self.mode,
                "allreduce_dtype": str(_flag("allreduce_dtype") or "f32"),
                "wire_bytes_per_step": float(sum(self._ring_bytes))}
        meta.update(self._meta_extra())
        main_program._collective_meta = dict(meta)
        startup_program._collective_meta = dict(meta)
        self._record_telemetry(meta)
        if pristine_main is not None:
            _world.check_world_transpiled(
                pristine_main, pristine_startup, main_program,
                startup_program, rank, self.nranks, nrings=self.nrings)

    def _meta_extra(self):
        return {}

    def _record_telemetry(self, meta):
        from ..core import telemetry as _tel

        if not _tel.enabled():
            return
        _tel.set_gauge("collective_nranks", meta["nranks"])
        _tel.set_gauge("collective_wire_bytes_per_step",
                       meta["wire_bytes_per_step"])
        shards = meta.get("zero1_shards")
        if shards is not None:
            sharded = [s for s in shards.values() if s["sharded"]]
            _tel.set_gauge("zero1_sharded_params", len(sharded))
            _tel.set_gauge("zero1_replicated_params",
                           len(shards) - len(sharded))
            _tel.set_gauge("zero1_shard_bytes_per_rank",
                           sum(s["bytes_per_rank"] for s in sharded))

    # -- startup: communicator bootstrap ops (collective.py:99-131) ---------
    def _init_communicator(self, program, current_endpoint, endpoints, rank,
                           ring_id, wait_port=True):
        block = program.global_block()
        nccl_id = block.create_var(name="nccl_id_%d" % ring_id,
                                   shape=(1,), dtype="int32")
        other = [e for e in endpoints if e != current_endpoint]
        block.append_op(
            type="c_gen_nccl_id",
            outputs={"Out": [nccl_id]},
            attrs={"rank": rank, "endpoint": current_endpoint,
                   "other_endpoints": other, "ring_id": ring_id},
        )
        block.append_op(
            type="c_comm_init",
            inputs={"X": [nccl_id]},
            attrs={"nranks": len(endpoints), "rank": rank,
                   "ring_id": ring_id},
        )

    def _transpile_startup_program(self):
        for ring_id in range(self.nrings):
            self._init_communicator(self.startup_program,
                                    self.current_endpoint, self.endpoints,
                                    self.rank, ring_id)

    def _transpile_main_program(self):
        raise NotImplementedError

    # -- helpers ------------------------------------------------------------
    def _is_backward_op(self, op):
        role = op.attr(OP_ROLE_KEY)
        return role is not None and int(role) & OpRole.Backward

    def _is_optimizer_op(self, op):
        role = op.attr(OP_ROLE_KEY)
        return role is not None and int(role) & OpRole.Optimize

    def _pick_ring(self, nbytes):
        """Least-loaded ring by accumulated bytes (balances multi-ring
        setups by payload instead of blind round-robin)."""
        ring = min(range(len(self._ring_bytes)),
                   key=lambda r: self._ring_bytes[r])
        self._ring_bytes[ring] += nbytes
        return ring

    def _exchange_dtype(self, block, name):
        """The wire dtype for one tensor: FLAGS_allreduce_dtype, demoted
        to f32 when the tensor can't be quantized (non-f32 or dynamic
        shape — the pack geometry needs static element counts)."""
        dt = str(_flag("allreduce_dtype") or "f32")
        if dt not in ("f32", "bf16", "int8"):
            raise ValueError("unknown FLAGS_allreduce_dtype=%r "
                             "(expected f32 | bf16 | int8)" % dt)
        if dt == "f32":
            return dt
        v = block._find_var_recursive(name)
        if not _static_shape(v) or v.dtype not in (None, "float32"):
            return "f32"
        return dt

    def _quant_geometry(self, numel, bucket):
        """Clamp the bucket to the per-rank chunk so small tensors are not
        padded out to a full bucket (the stamped attr is what the lowering
        packs with, so wire accounting and payload stay consistent)."""
        chunk = _ceil_div(numel, self.nranks)
        bucket = max(1, min(int(bucket), chunk))
        nb = _ceil_div(chunk, bucket)
        return chunk, nb, bucket

    def _quant_wire_bytes(self, nb, bucket, dtype, phases):
        """Per-rank wire bytes of `phases` quantized exchange phases (1 =
        reduce-scatter-shaped all_to_all, 2 = + the requantized
        all-gather): each phase moves (nranks-1) chunks of nb buckets of
        payload plus one f32 scale per bucket."""
        return float(phases * (self.nranks - 1)
                     * nb * (bucket * _payload_width(dtype) + _F32))

    def _insert_grad_allreduce(self, block, insert_at, grad, fold):
        """Replicated-path exchange of one gradient: grad := fold *
        sum_ranks(grad), quantized per FLAGS_allreduce_dtype.  Returns the
        next insert position."""
        n = self.nranks
        v = block._find_var_recursive(grad)
        numel = _numel(v.shape) if _static_shape(v) else 0
        dtype = self._exchange_dtype(block, grad)
        if dtype == "f32":
            ring = self._pick_ring(2.0 * (n - 1) / max(n, 1) * _F32 * numel)
            block._insert_op(
                insert_at,
                type="c_allreduce_sum",
                inputs={"X": [grad]},
                outputs={"Out": [grad]},
                attrs={"ring_id": ring, "scale": fold,
                       OP_ROLE_KEY: OpRole.Backward},
            )
            return insert_at + 1
        _chunk, nb, bucket = self._quant_geometry(
            numel, _flag("allreduce_quant_bucket"))
        ring = self._pick_ring(self._quant_wire_bytes(nb, bucket, dtype, 2))
        pack, scale = self._quant_pack(block, insert_at, grad, ring, dtype,
                                       bucket, nb)
        block._insert_op(
            insert_at + 1,
            type="c_allreduce_qsum",
            inputs={"X": [pack], "Scale": [scale]},
            outputs={"Out": [grad]},
            attrs={"ring_id": ring, "nranks": n, "bucket": bucket,
                   "dtype": dtype, "scale": fold,
                   "orig_shape": [int(d) for d in v.shape],
                   OP_ROLE_KEY: OpRole.Backward},
        )
        return insert_at + 2

    def _quant_pack(self, block, insert_at, grad, ring, dtype, bucket, nb):
        n = self.nranks
        wire = "bfloat16" if dtype == "bf16" else "int8"
        pack = block.create_var(name=grad + "@QPACK",
                                shape=(n, nb, bucket), dtype=wire)
        scale = block.create_var(name=grad + "@QSCALE",
                                 shape=(n, nb), dtype="float32")
        block._insert_op(
            insert_at,
            type="c_quant_pack",
            inputs={"X": [grad]},
            outputs={"Out": [pack], "Scale": [scale]},
            attrs={"ring_id": ring, "nranks": n, "bucket": bucket,
                   "dtype": dtype, OP_ROLE_KEY: OpRole.Backward},
        )
        return pack.name, scale.name

    def _collect_grad_pairs(self, block):
        """(param, grad) pairs from the op_role_var annotations, dedup by
        grad, backward order; plus the first optimizer op index."""
        pairs, seen = [], set()
        first_optimize_idx = None
        for idx, op in enumerate(block.ops):
            if self._is_backward_op(op) and OP_ROLE_VAR_KEY in op.attrs:
                rv = op.attrs[OP_ROLE_VAR_KEY] or []
                assert len(rv) % 2 == 0
                for i in range(0, len(rv) - 1, 2):
                    if rv[i + 1] not in seen:
                        seen.add(rv[i + 1])
                        pairs.append((rv[i], rv[i + 1]))
            if first_optimize_idx is None and self._is_optimizer_op(op):
                first_optimize_idx = idx
        if first_optimize_idx is None:
            first_optimize_idx = len(block.ops)
        return pairs, first_optimize_idx


class GradAllReduce(Collective):
    """One folded-scale c_allreduce_sum (or quant_pack + qsum) per gradient
    between backward and optimize (collective.py:178-266).  The reference's
    standalone scale(1/nranks) on the loss grad is folded into the reduce
    as a post-sum multiply — one op less per gradient, and bitwise-stable
    parity between the replicated and ZeRO-1 paths (both scale after the
    same psum-family reduction)."""

    def _transpile_main_program(self):
        block = self.main_program.global_block()
        pairs, first_optimize_idx = self._collect_grad_pairs(block)
        fold = 1.0 / self.nranks
        insert_at = first_optimize_idx
        for _param, grad in pairs:
            insert_at = self._insert_grad_allreduce(block, insert_at, grad,
                                                    fold)


class ShardedGradAllReduce(Collective):
    """ZeRO-1 weight-update sharding (arXiv 2004.13336).

    Per eligible (param, grad): reduce-scatter the gradient (folding the
    1/nranks average), slice this rank's dim-0 param shard, rewire the
    optimizer op — including what FuseOptimizerOpsPass later folds into
    fused_adam — onto the shards (its param-shaped state vars shrink to
    the shard and carry a ("data", ...) sharding annotation, so each
    replica holds 1/nranks of the optimizer state in HBM), and all-gather
    the updated shards back into the replicated params after the last
    optimizer op.  Ineligible pairs (dim0 not divisible by the world,
    non-elementwise optimizers like lamb, grads with extra consumers such
    as clip/regularizer chains) fall back per-param to the replicated
    exchange, so one program may mix both forms.  Shard assignment is
    dim-0 uniform — every rank owns exactly 1/nranks of each sharded
    param's bytes, balanced by construction — and is stamped into
    `_collective_meta["zero1_shards"]` for DL006 and the tools."""

    mode = "zero1"

    def __init__(self, nrings=1):
        super().__init__(nrings)
        self._shards = {}
        # shard-resident optimizer-state vars (the rewired slots), keyed
        # by scope name: the checkpoint layer reads this to write only
        # this rank's dim-0 slice of each (io.CheckpointManager sharded
        # save) and to reassemble/re-shard on restore after a world
        # change.  Slot vars keep their global names and the scope holds
        # the FULL arrays, so the layout speaks in global dim0 + rows
        # per rank of the world this program was transpiled for.
        self._ckpt_layout = {}

    def _meta_extra(self):
        return {"zero1_shards": dict(self._shards),
                "ckpt_shard_layout": dict(self._ckpt_layout)}

    def _optimizer_ops_by_grad(self, block):
        by_grad = {}
        for op in block.ops:
            if self._is_optimizer_op(op) and len(op.input("Grad")) == 1:
                by_grad.setdefault(op.input("Grad")[0], []).append(op)
        return by_grad

    def _shardable(self, block, param, grad, opt_by_grad, slot_table):
        """(ok, reason): can this (param, grad) take the sharded update?"""
        n = self.nranks
        pv = block._find_var_recursive(param)
        gv = block._find_var_recursive(grad)
        if not _static_shape(pv) or not _static_shape(gv):
            return False, "dynamic shape"
        if tuple(pv.shape) != tuple(gv.shape):
            return False, "grad shape differs from param"
        if gv.dtype not in (None, "float32"):
            return False, "non-f32 grad"
        d0 = int(pv.shape[0])
        if d0 < n or d0 % n != 0:
            return False, "dim0 %d not divisible by world %d" % (d0, n)
        opts = opt_by_grad.get(grad, [])
        if len(opts) != 1:
            return False, "grad feeds %d optimizer ops" % len(opts)
        op = opts[0]
        if op.type not in slot_table:
            return False, "optimizer %r is not elementwise" % op.type
        if op.input("Param") != [param] or op.output("ParamOut") != [param]:
            return False, "optimizer does not update %r in place" % param
        for in_slot, out_slot in slot_table[op.type]:
            names = op.input(in_slot)
            if len(names) != 1 or op.output(out_slot) != names:
                return False, "state slot %s is not in-place" % in_slot
            sv = block._find_var_recursive(names[0])
            if sv is None or tuple(sv.shape or ()) != tuple(pv.shape):
                return False, "state %s is not param-shaped" % in_slot
        # the exchanged grad must feed ONLY this optimizer op — an extra
        # non-backward consumer (grad clip, regularizer accumulation,
        # DGC...) would observe a shard where it expects the full tensor
        for other in block.ops:
            if other is op or self._is_backward_op(other):
                continue
            if grad in other.input_arg_names:
                return False, "grad has non-optimizer consumer %r" % other.type
        return True, "sharded"

    def _transpile_main_program(self):
        from ..optimizer import ZERO1_SHARDABLE_SLOTS

        block = self.main_program.global_block()
        n = self.nranks
        pairs, first_optimize_idx = self._collect_grad_pairs(block)
        opt_by_grad = self._optimizer_ops_by_grad(block)
        fold = 1.0 / n
        insert_at = first_optimize_idx
        gathers = []  # (param, shard var, ring, bytes)
        for param, grad in pairs:
            pv = block._find_var_recursive(param)
            ok, why = (False, "single-rank world") if n <= 1 else \
                self._shardable(block, param, grad, opt_by_grad,
                                ZERO1_SHARDABLE_SLOTS)
            nbytes = _numel(pv.shape) * _F32 if _static_shape(pv) else 0
            if not ok:
                self._shards[param] = {"sharded": False, "reason": why,
                                       "bytes_per_rank": nbytes}
                insert_at = self._insert_grad_allreduce(block, insert_at,
                                                        grad, fold)
                continue
            opt_op = opt_by_grad[grad][0]
            shape = tuple(int(d) for d in pv.shape)
            rows = shape[0] // n
            shard_shape = (rows,) + shape[1:]
            insert_at, gshard = self._insert_reduce_scatter(
                block, insert_at, grad, shape, shard_shape, fold)
            pshard = block.create_var(name=param + "@ZSHARD",
                                      shape=shard_shape, dtype=pv.dtype)
            # weight all-gather: quantized (ZeRO++-style, own-shard-exact)
            # when FLAGS_allreduce_dtype is narrow, else plain f32
            gdtype = self._exchange_dtype(block, param)
            if gdtype == "f32":
                gbucket = 0
                gbytes = (n - 1) / n * _F32 * _numel(shape)
            else:
                _c, gnb, gbucket = self._quant_geometry(
                    _numel(shape), _flag("allreduce_quant_bucket"))
                gbytes = self._quant_wire_bytes(gnb, gbucket, gdtype, 1)
            ring = self._pick_ring(gbytes)
            block._insert_op(
                insert_at,
                type="c_shard_slice",
                inputs={"X": [param]},
                outputs={"Out": [pshard]},
                attrs={"ring_id": ring, "nranks": n,
                       OP_ROLE_KEY: OpRole.Optimize},
            )
            insert_at += 1
            self._rewire_optimizer(block, opt_op, param, grad,
                                   pshard.name, gshard,
                                   ZERO1_SHARDABLE_SLOTS[opt_op.type],
                                   shard_shape)
            self._shards[param] = {
                "sharded": True, "reason": "sharded", "dim0": shape[0],
                "rows_per_rank": rows,
                "bytes_per_rank": _numel(shard_shape) * _F32,
            }
            gathers.append((param, pshard.name, ring, gdtype, gbucket,
                            shape))
        # updated shards -> replicated params, after the LAST optimizer op
        # (keeps the optimizer ops contiguous for FuseOptimizerOpsPass's
        # hazard scan, and the params consistent before the next forward)
        at = max((i for i, op in enumerate(block.ops)
                  if self._is_optimizer_op(op)), default=len(block.ops) - 1)
        at += 1
        for param, pshard, ring, gdtype, gbucket, shape in gathers:
            if gdtype == "f32":
                block._insert_op(
                    at,
                    type="c_allgather",
                    inputs={"X": [pshard]},
                    outputs={"Out": [param]},
                    attrs={"ring_id": ring, "nranks": n,
                           OP_ROLE_KEY: OpRole.Optimize},
                )
            else:
                block._insert_op(
                    at,
                    type="c_allgather_q",
                    inputs={"X": [pshard]},
                    outputs={"Out": [param]},
                    attrs={"ring_id": ring, "nranks": n, "bucket": gbucket,
                           "dtype": gdtype,
                           "orig_shape": [int(d) for d in shape],
                           OP_ROLE_KEY: OpRole.Optimize},
                )
            at += 1

    def _insert_reduce_scatter(self, block, insert_at, grad, shape,
                               shard_shape, fold):
        """grad -> grad@ZSHARD := fold * reduce_scatter(grad); quantized
        per FLAGS_allreduce_dtype.  Returns (next insert_at, shard name)."""
        n = self.nranks
        gshard = block.create_var(name=grad + "@ZSHARD", shape=shard_shape,
                                  dtype="float32")
        dtype = self._exchange_dtype(block, grad)
        if dtype == "f32":
            ring = self._pick_ring((n - 1) / n * _F32 * _numel(shape))
            block._insert_op(
                insert_at,
                type="c_reducescatter",
                inputs={"X": [grad]},
                outputs={"Out": [gshard]},
                attrs={"ring_id": ring, "nranks": n, "scale": fold,
                       OP_ROLE_KEY: OpRole.Backward},
            )
            return insert_at + 1, gshard.name
        _chunk, nb, bucket = self._quant_geometry(
            _numel(shape), _flag("allreduce_quant_bucket"))
        ring = self._pick_ring(self._quant_wire_bytes(nb, bucket, dtype, 1))
        pack, scale = self._quant_pack(block, insert_at, grad, ring, dtype,
                                       bucket, nb)
        block._insert_op(
            insert_at + 1,
            type="c_reducescatter_q",
            inputs={"X": [pack], "Scale": [scale]},
            outputs={"Out": [gshard]},
            attrs={"ring_id": ring, "nranks": n, "bucket": bucket,
                   "dtype": dtype, "scale": fold,
                   "orig_shape": [int(d) for d in shape],
                   OP_ROLE_KEY: OpRole.Backward},
        )
        return insert_at + 2, gshard.name

    def _rewire_optimizer(self, block, op, param, grad, pshard, gshard,
                          slots, shard_shape):
        """Point the update at the shards.  State vars KEEP their names —
        the scope/checkpoints hold the full arrays and the executor's
        sharding annotation (`Variable.sharding`) maps them onto the mesh
        axis, so each replica materializes only its 1/nranks slice."""
        op.inputs["Param"] = [pshard]
        op.inputs["Grad"] = [gshard]
        op.outputs["ParamOut"] = [pshard]
        dim0 = int(shard_shape[0]) * self.nranks
        for in_slot, _out_slot in slots:
            sv = block.var(op.input(in_slot)[0])
            sv.shape = tuple(shard_shape)
            sv.sharding = (_DATA_AXIS,) + (None,) * (len(shard_shape) - 1)
            self._ckpt_layout[sv.name] = {
                "param": param, "dim0": dim0,
                "rows_per_rank": int(shard_shape[0]),
            }
        self.main_program._bump_version()


class LocalSGD(Collective):
    """Local steps + periodic parameter averaging via snapshot diff allreduce
    (collective.py:269-372).  Simplified to every-step averaging of params
    after the optimizer (K=1); the reference's K-step schedule needs
    program-level conditionals, provided via layers.cond later.  The
    1/nranks averaging scale rides the allreduce's folded scale attr."""

    def __init__(self, nrings=1):
        super().__init__(nrings)
        self.snapshot_key = "@SNAPSHOT"

    def _transpile_main_program(self):
        block = self.main_program.global_block()
        params = []
        for op in block.ops:
            if self._is_optimizer_op(op) and OP_ROLE_VAR_KEY in op.attrs:
                rv = op.attrs[OP_ROLE_VAR_KEY]
                for i in range(0, len(rv), 2):
                    params.append(rv[i])
        n = self.nranks
        for param in dict.fromkeys(params):
            v = block._find_var_recursive(param)
            numel = _numel(v.shape) if _static_shape(v) else 0
            ring = self._pick_ring(2.0 * (n - 1) / max(n, 1) * _F32 * numel)
            block.append_op(
                type="c_allreduce_sum",
                inputs={"X": [param]},
                outputs={"Out": [param]},
                attrs={"ring_id": ring, "scale": 1.0 / n,
                       OP_ROLE_KEY: OpRole.Optimize},
            )
