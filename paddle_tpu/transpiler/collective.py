"""Collective transpilers: rewrite a single-device program into a
data-parallel program with explicit collectives.

Port of python/paddle/fluid/transpiler/collective.py (Collective:36,
GradAllReduce:178, LocalSGD:269).  The transpiled program carries
c_gen_nccl_id/c_comm_init in startup (structural on TPU — the mesh is the
communicator) and scale + c_allreduce_sum per gradient in main, keyed off
the op_role_var {param, grad} annotations exactly like the reference; the
executor runs such programs under shard_map with lax.psum as the allreduce.
"""

from ..framework import OP_ROLE_KEY, OP_ROLE_VAR_KEY, OpRole

__all__ = ["Collective", "GradAllReduce", "LocalSGD"]


class Collective:
    def __init__(self, nrings=1):
        self.nrings = nrings
        self.endpoints = None
        self.current_endpoint = None
        self.rank = 0
        self.nranks = 1
        self.main_program = None
        self.startup_program = None

    def transpile(self, startup_program, main_program, rank, endpoints,
                  current_endpoint, wait_port=True):
        self.startup_program = startup_program
        self.main_program = main_program
        self.rank = rank
        if isinstance(endpoints, str):
            endpoints = endpoints.split(",")
        self.endpoints = endpoints
        self.current_endpoint = current_endpoint
        self.nranks = len(endpoints)
        self._transpile_startup_program()
        self._transpile_main_program()
        # world-size provenance for the static verifier (DL005) and the
        # elastic re-quorum layer: which cluster this program was built for
        meta = {"nranks": self.nranks, "rank": rank,
                "endpoints": list(endpoints), "nrings": self.nrings}
        main_program._collective_meta = dict(meta)
        startup_program._collective_meta = dict(meta)

    # -- startup: communicator bootstrap ops (collective.py:99-131) ---------
    def _init_communicator(self, program, current_endpoint, endpoints, rank,
                           ring_id, wait_port=True):
        block = program.global_block()
        nccl_id = block.create_var(name="nccl_id_%d" % ring_id,
                                   shape=(1,), dtype="int32")
        other = [e for e in endpoints if e != current_endpoint]
        block.append_op(
            type="c_gen_nccl_id",
            outputs={"Out": [nccl_id]},
            attrs={"rank": rank, "endpoint": current_endpoint,
                   "other_endpoints": other, "ring_id": ring_id},
        )
        block.append_op(
            type="c_comm_init",
            inputs={"X": [nccl_id]},
            attrs={"nranks": len(endpoints), "rank": rank,
                   "ring_id": ring_id},
        )

    def _transpile_startup_program(self):
        for ring_id in range(self.nrings):
            self._init_communicator(self.startup_program,
                                    self.current_endpoint, self.endpoints,
                                    self.rank, ring_id)

    def _transpile_main_program(self):
        raise NotImplementedError

    # -- helpers ------------------------------------------------------------
    def _is_backward_op(self, op):
        role = op.attr(OP_ROLE_KEY)
        return role is not None and int(role) & OpRole.Backward

    def _is_optimizer_op(self, op):
        role = op.attr(OP_ROLE_KEY)
        return role is not None and int(role) & OpRole.Optimize


class GradAllReduce(Collective):
    """Insert scale(1/nranks) + c_allreduce_sum per gradient between
    backward and optimize (collective.py:178-266)."""

    def __init__(self, nrings=1):
        super().__init__(nrings)

    def _transpile_main_program(self):
        self._insert_scale_loss_grad_ops()
        self._insert_allreduce_ops()

    def _insert_scale_loss_grad_ops(self):
        block = self.main_program.global_block()
        for idx, op in reversed(list(enumerate(block.ops))):
            if self._is_loss_grad_op(op):
                out = op.output_arg_names[0]
                block._insert_op(
                    idx + 1,
                    type="scale",
                    inputs={"X": [out]},
                    outputs={"Out": [out]},
                    attrs={"scale": 1.0 / self.nranks,
                           OP_ROLE_KEY: OpRole.Backward},
                )

    def _is_loss_grad_op(self, op):
        role = op.attr(OP_ROLE_KEY)
        return role is not None and int(role) == (OpRole.Backward | OpRole.Loss)

    def _insert_allreduce_ops(self):
        block = self.main_program.global_block()
        ring_id = -1
        grads = []
        first_optimize_idx = None
        for idx, op in enumerate(block.ops):
            if self._is_backward_op(op) and OP_ROLE_VAR_KEY in op.attrs:
                rv = op.attrs[OP_ROLE_VAR_KEY]
                if not rv:
                    continue
                assert len(rv) % 2 == 0
                for i in range(1, len(rv), 2):
                    grads.append(rv[i])
            if first_optimize_idx is None and self._is_optimizer_op(op):
                first_optimize_idx = idx
        if first_optimize_idx is None:
            first_optimize_idx = len(block.ops)
        insert_at = first_optimize_idx
        for i, grad in enumerate(dict.fromkeys(grads)):
            ring_id = (ring_id + 1) % self.nrings
            block._insert_op(
                insert_at,
                type="c_allreduce_sum",
                inputs={"X": [grad]},
                outputs={"Out": [grad]},
                attrs={"ring_id": ring_id, OP_ROLE_KEY: OpRole.Backward},
            )
            insert_at += 1


class LocalSGD(Collective):
    """Local steps + periodic parameter averaging via snapshot diff allreduce
    (collective.py:269-372).  Simplified to every-step averaging of params
    after the optimizer (K=1); the reference's K-step schedule needs
    program-level conditionals, provided via layers.cond later."""

    def __init__(self, nrings=1):
        super().__init__(nrings)
        self.snapshot_key = "@SNAPSHOT"

    def _transpile_main_program(self):
        block = self.main_program.global_block()
        ring_id = -1
        params = []
        for op in block.ops:
            if self._is_optimizer_op(op) and OP_ROLE_VAR_KEY in op.attrs:
                rv = op.attrs[OP_ROLE_VAR_KEY]
                for i in range(0, len(rv), 2):
                    params.append(rv[i])
        for param in dict.fromkeys(params):
            ring_id = (ring_id + 1) % self.nrings
            block.append_op(
                type="scale",
                inputs={"X": [param]},
                outputs={"Out": [param]},
                attrs={"scale": 1.0 / self.nranks,
                       OP_ROLE_KEY: OpRole.Optimize},
            )
            block.append_op(
                type="c_allreduce_sum",
                inputs={"X": [param]},
                outputs={"Out": [param]},
                attrs={"ring_id": ring_id, OP_ROLE_KEY: OpRole.Optimize},
            )
