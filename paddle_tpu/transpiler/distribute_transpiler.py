"""Parameter-server transpiler (port of
python/paddle/fluid/transpiler/distribute_transpiler.py:230).

Rewrites a single-process program into trainer/pserver halves communicating
through send/recv ops.  The full PS runtime lands with the distributed
milestone (see paddle_tpu/distributed/ps_runtime.py); this module implements
the program splitting: slice_variable round-robin, trainer-side send/recv
injection, and pserver program construction with per-param optimizer blocks.
"""

import math

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig", "slice_variable"]


class DistributeTranspilerConfig:
    """Knobs (reference distribute_transpiler.py:131)."""

    slice_var_up = True
    split_method = None
    min_block_size = 8192
    enable_dc_asgd = False
    mode = "pserver"  # pserver | nccl2 | collective
    print_log = False
    wait_port = True
    runtime_split_send_recv = False
    sync_mode = True
    nccl_comm_num = 1
    use_hierarchical_allreduce = False
    hierarchical_allreduce_inter_nranks = 0
    collective_mode = None
    geo_sgd_mode = False
    geo_sgd_need_push_nums = 100


class VarBlock:
    def __init__(self, varname, offset, size):
        self.varname = varname
        self.offset = offset
        self.size = size

    def __str__(self):
        return "%s:%d:%d" % (self.varname, self.offset, self.size)


def slice_variable(var_list, slice_count, min_block_size):
    """Split variables into blocks round-robined over pservers (reference
    distribute_transpiler.py:70-118)."""
    blocks = []
    for var in var_list:
        split_count = slice_count
        numel = 1
        for d in var.shape:
            numel *= int(d)
        max_pserver_count = int(math.floor(numel / float(min_block_size)))
        if max_pserver_count == 0:
            max_pserver_count = 1
        if max_pserver_count < slice_count:
            split_count = max_pserver_count
        block_size = int(math.ceil(numel / float(split_count)))
        if len(var.shape) >= 2:
            dim1 = 1
            for d in var.shape[1:]:
                dim1 *= int(d)
            remains = block_size % dim1
            if remains != 0:
                block_size += dim1 - remains
        split_count = int(math.ceil(numel / float(block_size)))
        for block_id in range(split_count):
            curr_size = min(block_size, numel - block_id * block_size)
            blocks.append(VarBlock(var.name, block_id, curr_size))
    return blocks


class DistributeTranspiler:
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._transpiled = False

    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint="127.0.0.1:6174"):
        from ..framework import default_main_program, default_startup_program

        self.trainer_id = trainer_id
        self.program = program or default_main_program()
        self.startup_program = startup_program or default_startup_program()
        self.pserver_endpoints = (
            pservers.split(",") if isinstance(pservers, str) else list(pservers)
        )
        self.trainers = trainers
        self.sync_mode = sync_mode

        if self.config.mode in ("nccl2", "collective"):
            # collective modes delegate to the Collective transpilers
            # (FLAGS_collective_mode picks replicated vs ZeRO-1 sharded)
            from .collective import select_grad_transpiler

            t = select_grad_transpiler(self.config.nccl_comm_num)
            eps = ["%d" % i for i in range(trainers)]
            t.transpile(self.startup_program, self.program, trainer_id, eps,
                        "%d" % trainer_id)
            self._transpiled = True
            # post-transpile static lint: ring_id discipline on the
            # collectives the pass just inserted (FLAGS_static_check-gated)
            from ..core.analysis import check_before_compile

            check_before_compile(self.program, [], [])
            return

        from .ps_transpile import transpile_pserver_mode

        self._ps_state = transpile_pserver_mode(self)
        self._transpiled = True
        # post-transpile static lint over the trainer/pserver split:
        # placement (DL001), send/recv pairing (DL002), duplicated
        # side-effecting ops (DL004) — FLAGS_static_check-gated
        from ..core.analysis import check_transpiled

        check_transpiled(self._ps_state)

    def get_trainer_program(self, wait_port=True):
        if self.config.mode in ("nccl2", "collective"):
            return self.program
        if getattr(self, "_ps_state", None) is None:
            raise RuntimeError("call transpile() before get_trainer_program()")
        return self._ps_state.trainer_program

    def get_pserver_program(self, endpoint):
        return self._ps_state.pserver_programs[endpoint]

    def get_pserver_programs(self, endpoint):
        return (self._ps_state.pserver_programs[endpoint],
                self._ps_state.pserver_startups[endpoint])

    def get_startup_program(self, endpoint, pserver_program=None):
        return self._ps_state.pserver_startups[endpoint]
