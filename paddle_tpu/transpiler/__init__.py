from .collective import Collective, GradAllReduce, LocalSGD  # noqa: F401
from .distribute_transpiler import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
)
