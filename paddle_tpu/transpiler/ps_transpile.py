"""Parameter-server program splitting (reference
transpiler/distribute_transpiler.py:495 `transpile`, :230).

Rewrites a trained program into (trainer half, per-pserver halves):

- trainer program: forward + backward only (Optimize/LRSched-role ops
  removed); annotated with ``_ps_trainer`` metadata the executor uses to
  send grads / pull params over the C++ RPC transport after each step
  (distributed/ps.py).
- pserver programs: one `listen_and_serv` op each (executor routes it to
  the blocking server loop) + an optimizer sub-program holding exactly the
  update ops of the params this server owns — the analog of the per-param
  optimize sub-blocks listen_and_serv_op.cc executes.

Placement is whole-param round-robin by size (the reference's
``slice_var_up=False`` configuration; block slicing is a follow-up).
"""

from ..framework import OP_ROLE_KEY, OpRole


class PSState:
    def __init__(self, trainer_program, pserver_programs, pserver_startups,
                 param_map):
        self.trainer_program = trainer_program
        self.pserver_programs = pserver_programs
        self.pserver_startups = pserver_startups
        self.param_map = param_map


def _role(op):
    return int(op.attr(OP_ROLE_KEY) or 0)


def transpile_pserver_mode(t):
    program, startup = t.program, t.startup_program
    eps = t.pserver_endpoints
    block = program.global_block()

    # param -> grad from the Optimize ops' own slots (robust to clipping /
    # regularization rewrites of the grad name)
    opt_ops = [op for op in block.ops if _role(op) & OpRole.Optimize]
    param_grad = {}
    for op in opt_ops:
        pnames = op.input("Param")
        if not pnames:
            continue
        g = op.input("Grad")
        if g:
            param_grad[pnames[0]] = g[0]
    if not param_grad:
        raise ValueError(
            "PS transpile: no optimizer ops found — call "
            "optimizer.minimize(loss) before transpile()")

    # whole-param round-robin by size desc (reference slice_variable's
    # balance goal without block slicing)
    def size_of(name):
        v = block._find_var_recursive(name)
        n = 1
        for d in (v.shape or ()):
            n *= max(int(d), 1)
        return n

    param_to_ep = {}
    loads = {ep: 0 for ep in eps}
    for p in sorted(param_grad, key=size_of, reverse=True):
        ep = min(eps, key=lambda e: loads[e])
        param_to_ep[p] = ep
        loads[ep] += size_of(p)

    geo_mode = bool(getattr(t.config, "geo_sgd_mode", False))
    geo_k = int(getattr(t.config, "geo_sgd_need_push_nums", 100))

    # ---- trainer program ---------------------------------------------------
    # pserver modes strip the update ops (the server optimizes); geo-SGD
    # keeps them — the trainer optimizes LOCALLY and pushes param deltas
    # every K steps (reference geo_sgd_transpiler.py + GeoSgdCommunicator,
    # communicator.h:332)
    trainer_prog = program.clone()
    if not geo_mode:
        tb = trainer_prog.global_block()
        tb.ops = [op for op in tb.ops
                  if not (_role(op) & OpRole.Optimize)
                  and _role(op) != OpRole.LRSched]
        trainer_prog._bump_version()
    trainer_prog._ps_trainer = {
        "endpoints": list(eps),
        "param_to_ep": param_to_ep,
        "param_grad": param_grad,
        "trainer_id": t.trainer_id,
        "trainers": t.trainers,
        "sync": t.sync_mode,
        "geo": geo_mode,
        "geo_push_nums": geo_k,
    }

    # ---- pserver programs -------------------------------------------------
    def startup_for(needed):
        sp = startup.clone()
        sb = sp.global_block()
        sb.ops = [op for op in sb.ops
                  if any(n in needed for n in op.output_arg_names)]
        sp._bump_version()
        return sp

    pserver_programs = {}
    pserver_startups = {}
    for ep in eps:
        owned = [p for p, e in param_to_ep.items() if e == ep]
        opt_prog = program.clone()
        ob = opt_prog.global_block()
        keep = []
        for op in ob.ops:
            role = _role(op)
            if role == OpRole.LRSched:
                keep.append(op)
            elif role & OpRole.Optimize:
                pn = op.input("Param")
                if pn and pn[0] in owned:
                    keep.append(op)
                elif not pn:
                    keep.append(op)  # e.g. global counters
        ob.ops = keep
        opt_prog._bump_version()

        # persistable state this server must initialize: params, their
        # accumulators, lr vars
        needed = set()
        for op in keep:
            for n in list(op.input_arg_names) + list(op.output_arg_names):
                v = ob._find_var_recursive(n)
                if v is not None and v.persistable:
                    needed.add(n)

        sp = startup_for(needed)

        serv_prog = program.clone()
        svb = serv_prog.global_block()
        svb.ops = []
        serv_prog._bump_version()
        svb.append_op(
            type="listen_and_serv",
            inputs={}, outputs={},
            attrs={"endpoint": ep, "Fanin": t.trainers})
        # async mode only: per-param update programs (run on each grad
        # arrival) + a shared program holding LRSched and param-less
        # Optimize ops (global counters), run once per logical step — NOT
        # per arrival, or decay would advance owned*trainers times too fast
        per_param = {}
        lr_prog = None
        if not t.sync_mode:
            for p in owned:
                pp = program.clone()
                ppb = pp.global_block()
                ppb.ops = [op for op in ppb.ops
                           if (_role(op) & OpRole.Optimize)
                           and op.input("Param")
                           and op.input("Param")[0] == p]
                pp._bump_version()
                per_param[p] = pp
            lr_prog = program.clone()
            lb = lr_prog.global_block()
            lb.ops = [op for op in lb.ops
                      if _role(op) == OpRole.LRSched
                      or ((_role(op) & OpRole.Optimize)
                          and not op.input("Param"))]
            lr_prog._bump_version()
        serv_prog._ps_server = {
            "endpoint": ep,
            "params": owned,
            "grad_map": {param_grad[p]: p for p in owned},
            "trainers": t.trainers,
            "optimize_program": opt_prog,
            "optimize_programs": per_param,
            "lr_program": lr_prog,
            "sync": t.sync_mode,
            "geo": geo_mode,
        }
        pserver_programs[ep] = serv_prog
        pserver_startups[ep] = sp

    return PSState(trainer_prog, pserver_programs, pserver_startups,
                   param_to_ep)
