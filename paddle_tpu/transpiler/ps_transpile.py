"""Parameter-server program splitting (reference
transpiler/distribute_transpiler.py:495 `transpile`, :230).

Rewrites a trainer program into (trainer half, per-pserver halves): grads are
sent to their owning pserver, the pserver runs the optimizer sub-program per
received grad, and updated params are pulled back (reference flow §3.4 in
SURVEY.md).
"""


class PSState:
    def __init__(self, trainer_program, pserver_programs, pserver_startups,
                 param_map):
        self.trainer_program = trainer_program
        self.pserver_programs = pserver_programs
        self.pserver_startups = pserver_startups
        self.param_map = param_map


def transpile_pserver_mode(t):
    raise NotImplementedError(
        "parameter-server transpile mode is not implemented yet; use "
        "mode='collective' (fleet collective DP over the mesh) — the PS "
        "runtime (listen_and_serv / send / recv over the C++ RPC backend) "
        "is tracked in SURVEY.md §7 step 8"
    )
