"""fluid.unique_name compatibility alias."""

from .utils.unique_name import generate, guard, switch  # noqa: F401
