"""Disaggregated prefill/decode serving: sealed-KV-block streaming.

A prefill-role replica runs admission + chunked prefill only.  As each
full prompt block seals (engine hook ``on_block_sealed``) its payload is
copied off the carry and streamed to the paired decode-role replica as a
``__kvxfer__`` frame; when the feed pointer reaches the last full-block
boundary (``on_handoff``) a *commit* frame follows carrying the full
prompt + decode params + prefill-side phase timings.  The decode replica
adopts each block into its own refcounted pool via the prefix index
(``DecodeEngine.adopt_kv_block``: allocate, install payload, publish
digest, park evictable) and then the commit frame's ordinary ``submit``
prefix-matches the adopted blocks exactly like a locally-computed cache
hit — generation runs through the existing engine unchanged, and outputs
stay bitwise equal to a monolith because prefill's compiled step is
deterministic: a transferred block is identical to the one the decode
replica would have computed itself (for f32 AND int8 residency — the
wire dtype follows the pool dtype).

Handoff state machine (sender side, per request):

  ``prefill``    registered, engine feeding the prompt
  ``streaming``  >= 1 sealed-block frame queued/sent
  ``adopted``    commit frame sent — the decode half owns the request

Reconciliation rules (a kill on either side frees blocks on both):

- prefill-side terminal without handoff (abort / shed / timeout /
  error): a ``cancel`` frame relays the reply to the decode half, which
  forgets the adopted digests (``forget_adopted`` truly frees
  still-evictable blocks) and publishes the terminal reply/stream chunk
  so the parked client unblocks.
- prefill replica SIGKILLed mid-transfer: the decode half's orphan
  janitor notices an uncommitted adoption whose prefill endpoint stopped
  answering ``__alive__`` probes, frees the adopted blocks, and
  publishes a "timeout" reply — the client's ordinary timeout-replay
  path takes over (zero admitted requests dropped).
- decode half dies: the client's stream GET raises, and its failover
  best-effort ``__abort__``s BOTH halves before replaying (the
  satellite-2 leak fix).

Transfers dedupe per peer: the sender keeps a recently-shipped digest
LRU per decode endpoint, so a warm decode replica skips the wire
entirely (the receiver additionally skips digests already indexed —
"cached" adoption).  Every skipped or rejected transfer is safe: the
commit frame carries the full prompt, so the decode engine simply
recomputes whatever prefix it does not hold.

``kv_xfer_bytes_total{dtype}`` counts full frame bytes per wire dtype —
the int8-residency fleet must move <= 0.55x the bytes of the f32 fleet
on the same traffic.
"""

import threading
import time
from collections import OrderedDict, deque

from ..core import telemetry as _tm
from ..core import tracing as _tr
from ..native.rpc import RpcClient, probe
from . import codec

__all__ = ["KVBlockSender", "AdoptTracker"]

# per-peer recently-shipped digest LRU: bounds sender memory while
# keeping the warm-peer skip effective across far more digests than any
# smoke-sized pool holds
_SHIPPED_CAP = 4096
# uncommitted adoptions younger than this are never probed (normal
# prefill queueing easily spans a few hundred ms)
_ORPHAN_GRACE_S = 2.0
# a stale entry's prefill endpoint is probed with capped exponential
# backoff: each probe that finds the peer alive doubles the delay before
# the next one, so an alive-but-slow prefill half is not hammered once a
# second for the life of a long transfer
_PROBE_BACKOFF_S = 0.5
_PROBE_BACKOFF_CAP_S = 8.0
# an uncommitted adoption older than this is reaped even when its
# prefill half still answers probes (wedged sender, commit frame lost
# after a reconnect) — and it is the only reaper for entries that never
# learned their prefill endpoint
_ORPHAN_HARD_S = 30.0


class KVBlockSender:
    """Prefill-side worker: one FIFO + one thread serializes every frame
    per process, so a request's expect -> block(pos 0..n) -> commit order
    is preserved on the wire (frames ride send_var, which completes only
    after the receiver queued the event)."""

    def __init__(self):
        self._q = deque()
        self._cond = threading.Condition()
        self._clients = {}              # endpoint -> RpcClient
        self._shipped = {}              # endpoint -> OrderedDict(digest)
        self._reqs = {}                 # req_id -> {"peer", "state", ...}
        self._running = True
        self._thread = threading.Thread(target=self._run,
                                        name="kvxfer-send", daemon=True)
        self._thread.start()

    # -- registry ------------------------------------------------------------

    def register(self, req_id, peer, model, wire_dtype):
        with self._cond:
            self._reqs[req_id] = {"peer": peer, "state": "prefill",
                                  "model": model, "dtype": wire_dtype}

    def peer_of(self, req_id):
        with self._cond:
            e = self._reqs.get(req_id)
            return e["peer"] if e else None

    def state_of(self, req_id):
        with self._cond:
            e = self._reqs.get(req_id)
            return e["state"] if e else None

    # -- frame producers (engine hooks / server) -----------------------------

    def send_expect_now(self, req_id, meta):
        """Synchronous expect frame, sent on the caller's thread BEFORE
        the pair var is published: once a client can learn the pair, the
        decode half already knows the request exists (arms the orphan
        janitor).  Returns False when the peer is unreachable — the
        caller falls back to serving the request itself."""
        with self._cond:
            e = self._reqs.get(req_id)
        if e is None:
            return False
        m = dict(meta)
        m.update(kind="expect", req_id=req_id)
        return self._send(e["peer"], req_id, m, ())

    def enqueue_block(self, req_id, pos, digest, arrays):
        with self._cond:
            e = self._reqs.get(req_id)
            if e is None:
                return
            if e["state"] == "prefill":
                e["state"] = "streaming"
            peer = e["peer"]
            shipped = self._shipped.setdefault(peer, OrderedDict())
            if digest in shipped:
                shipped.move_to_end(digest)
                _tm.inc("kv_xfer_skipped_total", dtype=e["dtype"])
                return          # warm peer: skip the wire entirely
            shipped[digest] = True
            while len(shipped) > _SHIPPED_CAP:
                shipped.popitem(last=False)
            meta = {"kind": "block", "req_id": req_id, "pos": int(pos),
                    "digest": digest, "model": e["model"],
                    "dtype": e["dtype"]}
            self._q.append((peer, req_id, meta, list(arrays)))
            self._cond.notify_all()

    def enqueue_commit(self, req_id, meta):
        with self._cond:
            e = self._reqs.get(req_id)
            if e is None:
                return
            m = dict(meta)
            m.update(kind="commit", req_id=req_id)
            self._q.append((e["peer"], req_id, m, ()))
            self._cond.notify_all()

    def enqueue_cancel(self, req_id, reply_meta):
        """Prefill-side terminal without handoff: drop this request's
        queued frames and relay the reply so the decode half frees its
        adoptions and unblocks the parked client."""
        with self._cond:
            e = self._reqs.pop(req_id, None)
            if e is None:
                return
            self._q = deque(f for f in self._q if f[1] != req_id)
            meta = {"kind": "cancel", "req_id": req_id,
                    "reply": dict(reply_meta or {})}
            self._q.append((e["peer"], req_id, meta, ()))
            self._cond.notify_all()

    def mark_adopted(self, req_id):
        """Commit sent: the decode half owns the request now; the entry
        is only kept long enough for abort relays to find the peer."""
        with self._cond:
            e = self._reqs.get(req_id)
            if e is not None:
                e["state"] = "adopted"

    def forget(self, req_id):
        with self._cond:
            self._reqs.pop(req_id, None)

    # -- wire ----------------------------------------------------------------

    def _client(self, peer):
        c = self._clients.get(peer)
        if c is None:
            c = self._clients[peer] = RpcClient(
                peer, connect_timeout=2.0, rpc_deadline=15.0,
                retry_times=0)
        return c

    def _send(self, peer, req_id, meta, arrays):
        frame = codec.pack_kvxfer(meta, arrays)
        # write-through breadcrumb BEFORE the send: a SIGKILL mid-transfer
        # leaves the in-flight frame named in flightrec-<pid>.json
        _tr.note("kvxfer", frame_kind=meta["kind"], req_id=req_id,
                 peer=peer, pos=meta.get("pos", -1),
                 digest=meta.get("digest", "")[:16])
        for _ in range(2):
            try:
                self._client(peer).send_var(
                    codec.KVXFER_KEY + req_id, frame)
                break
            except Exception:
                # poisoned/raced client: reconnect once, then give up —
                # a lost frame only costs the decode half a recompute
                # (and the orphan janitor covers a lost commit)
                dead = self._clients.pop(peer, None)
                if dead is not None:
                    try:
                        dead.close()
                    except Exception:
                        pass
        else:
            _tm.inc("kv_xfer_send_errors_total")
            return False
        if meta["kind"] == "block":
            _tm.inc("kv_xfer_bytes_total", int(frame.nbytes),
                    dtype=meta.get("dtype", "f32"))
            _tm.inc("kv_xfer_blocks_total", dtype=meta.get("dtype", "f32"))
        _tm.inc("kv_xfer_frames_total", kind=meta["kind"])
        return True

    def _run(self):
        while True:
            with self._cond:
                while self._running and not self._q:
                    self._cond.wait(0.2)
                if not self._running and not self._q:
                    return
                peer, req_id, meta, arrays = self._q.popleft()
            self._send(peer, req_id, meta, arrays)
            if meta["kind"] == "commit":
                self.mark_adopted(req_id)
            elif meta["kind"] == "cancel":
                self.forget(req_id)

    def close(self):
        with self._cond:
            self._running = False
            self._cond.notify_all()
        self._thread.join(5.0)
        for c in self._clients.values():
            try:
                c.close()
            except Exception:
                pass
        self._clients.clear()


class AdoptTracker:
    """Decode-side per-request adoption state + orphan janitor.

    An entry lives from the expect (or first block) frame until the
    commit frame arrives; ``on_orphan(req_id, entry)`` fires for an
    uncommitted entry whose prefill endpoint stops answering ``__alive__``
    probes — the server frees the adopted digests and publishes a
    "timeout" reply so the parked client replays instead of hanging.
    Probes back off exponentially per endpoint (capped), and every reaped
    adoption lands in ``kv_xfer_orphans_total{reason=}``:
    ``dead_peer`` (probe failed), ``timeout`` (uncommitted past the hard
    cap with the sender still alive or unknown), ``cancelled`` (explicit
    cancel frame after blocks were adopted)."""

    def __init__(self, on_orphan):
        self._entries = {}
        self._lock = threading.Lock()
        self._on_orphan = on_orphan
        self._stop = threading.Event()
        # endpoint -> [next_probe_interval_s, not_before_monotonic];
        # janitor-thread-only state behind the capped exponential probe
        # backoff (dropped the moment a probe fails, so a relaunched
        # peer starts fresh)
        self._probe_state = {}
        self._thread = threading.Thread(target=self._janitor,
                                        name="kvxfer-janitor", daemon=True)
        self._thread.start()

    def _entry(self, req_id):
        e = self._entries.get(req_id)
        if e is None:
            e = self._entries[req_id] = {
                "model": None, "digests": [], "next_pos": 0,
                "committed": False, "t0": time.monotonic(),
                "prefill_ep": None}
        return e

    def expect(self, req_id, meta):
        with self._lock:
            e = self._entry(req_id)
            e["model"] = meta.get("model") or e["model"]
            e["prefill_ep"] = meta.get("prefill_ep") or e["prefill_ep"]

    def on_block(self, req_id, meta):
        """Validate + record one block frame.  Returns None when the
        frame may be adopted, else a rejection reason.  Skipped positions
        are legal (the sender dedupes already-shipped digests); a
        position at or below one already adopted is the loud hash-chain
        ordering violation."""
        pos = int(meta.get("pos", -1))
        with self._lock:
            e = self._entry(req_id)
            e["model"] = meta.get("model") or e["model"]
            if pos < e["next_pos"]:
                return ("hash-chain position mismatch: pos=%d after "
                        "pos=%d was already adopted" % (pos,
                                                        e["next_pos"] - 1))
            e["next_pos"] = pos + 1
            e["digests"].append(meta.get("digest"))
            return None

    def commit(self, req_id):
        """Commit arrived: the engine owns the blocks' lifecycle now
        (matched blocks are refcounted to the sequence; unmatched ones
        stay ordinary evictable cache entries).  Returns the entry."""
        with self._lock:
            e = self._entries.pop(req_id, None)
            if e is not None:
                e["committed"] = True
            return e

    def cancel(self, req_id):
        """Prefill-side cancel (or orphan): drop the entry and return the
        adopted digests to forget.  An uncommitted entry that had already
        adopted blocks counts as an orphaned adoption
        (``kv_xfer_orphans_total{reason=cancelled}``)."""
        with self._lock:
            e = self._entries.pop(req_id, None)
        if e is not None and not e["committed"] and e["digests"]:
            _tm.inc("kv_xfer_orphans_total", reason="cancelled")
        return e

    def _janitor(self):
        while not self._stop.wait(0.5):
            now = time.monotonic()
            with self._lock:
                stale = [(rid, dict(e)) for rid, e in self._entries.items()
                         if not e["committed"]
                         and now - e["t0"] > _ORPHAN_GRACE_S]
            alive = {}
            for rid, e in stale:
                ep = e["prefill_ep"]
                if now - e["t0"] > _ORPHAN_HARD_S:
                    # wedged-but-alive sender (or one that never sent its
                    # endpoint): the commit is not coming
                    self._reap(rid, "timeout")
                    continue
                if not ep:
                    continue        # hard timeout is the only reaper
                if ep not in alive:
                    st = self._probe_state.setdefault(
                        ep, [_PROBE_BACKOFF_S, 0.0])
                    if now < st[1]:
                        continue    # inside this endpoint's backoff
                    alive[ep] = probe(ep, codec.ALIVE_KEY,
                                      timeout=1.0) is not None
                    if alive[ep]:
                        # answered: back off the NEXT probe, capped
                        st[1] = now + st[0]
                        st[0] = min(_PROBE_BACKOFF_CAP_S, st[0] * 2.0)
                    else:
                        self._probe_state.pop(ep, None)
                if not alive[ep]:
                    self._reap(rid, "dead_peer")

    def _reap(self, rid, reason):
        with self._lock:
            gone = self._entries.pop(rid, None)
        if gone is not None:
            _tm.inc("kv_xfer_orphans_total", reason=reason)
            try:
                self._on_orphan(rid, gone)
            except Exception:
                pass

    def close(self):
        self._stop.set()
        self._thread.join(3.0)
