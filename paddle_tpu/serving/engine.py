"""Continuous-batching serving engine over AnalysisPredictor.

The reference inference stack answers one request at a time
(AnalysisPredictor::Run); under "heavy traffic from millions of users"
(ROADMAP north star) that wastes the accelerator on batch-1 launches and
recompiles on every new request shape.  The engine closes both gaps:

- **admission queue with deadline-aware backpressure**: ``submit`` sheds
  a request (status "shed" + retry_after_ms) instead of queueing it when
  the projected wait — queue depth x the model's EWMA batch service time —
  already exceeds the request's deadline budget, or when the queue is at
  ``FLAGS_serving_max_queue``.  Queued requests whose deadline expires
  before dispatch complete with status "timeout".
- **shape-bucketed batching**: the dispatcher coalesces queued same-model
  requests for up to ``FLAGS_serving_batch_window_ms`` and pads the
  concatenated batch to the smallest configured bucket that fits
  (``FLAGS_serving_buckets``), so every dispatch hits one of a FIXED set
  of executable shapes.
- **AOT bucket prewarm**: ``prewarm()`` runs ``Executor.warmup`` for every
  (model, bucket) against ``FLAGS_compile_cache_dir`` — all executables
  exist before the first request, and the prewarm manifest records where
  each came from (memory/disk/compiled).  After that, a request can only
  ever hit the in-memory executable cache: zero runtime compiles, provable
  from the ``executor_cache_miss_total`` / ``compile_cache_*`` counters.

Serving control plane (PR 16) on top of that:

- **SLO tiers**: a request carries a tier (``paid``/``free``/``batch``),
  whose configured weight (``FLAGS_serving_tier_weights``) scales its
  admission budget — shed when projected wait exceeds deadline x weight
  — orders batch assembly (higher weight dispatches first), and decides
  queue-full eviction (an arriving higher-weight request evicts the
  lowest-weight queued one instead of being shed itself).  Under
  overload the free tier sheds first and paid p99 never starves.
- **drain hook**: ``drain()`` flips the engine into a shedding-only
  state and waits for the queue to empty — the autoscaler's graceful
  scale-down runs it on the victim so retirement lands at a batch
  boundary with zero dropped requests.
- **versioned routing**: ``add_model("fc@v2", ...)`` registers a second
  version beside ``fc``; ``set_route`` splits base-name traffic between
  active and canary versions by a deterministic per-request hash, so the
  rollout controller (serving/rollout.py) can canary, flip, and roll
  back without touching clients.  Reply phases carry the resolved
  version so per-version p99s fall out of the same attribution.

Telemetry: ``serving_queue_depth`` gauge, ``serving_batch_fill`` +
``serving_latency_ms`` + ``serving_execute_ms`` histograms,
``serving_qps`` gauge (5 s window),
``serving_requests_total{model,tenant}``, ``serving_shed_total{reason}``,
``serving_tier_shed_total{tier}``, ``serving_timeout_total``,
``serving_batches_total{model,bucket}``,
``serving_request_errors_total{model}``.
"""

import threading
import time
import uuid
import zlib

import numpy as np

from ..core import telemetry as _tm
from ..core import tracing as _tr
from ..core.executor import scope_guard
from ..utils.fault_injection import maybe_fail

__all__ = ["ServingEngine", "DecodeEngine", "InferReply", "parse_buckets",
           "parse_tier_weights", "tier_weight"]

_QPS_WINDOW_S = 5.0

# Machine-readable concurrency contracts (tools/threadlint.py CC101/CC105;
# core/concurrency_analysis.py merges every module's registry).  The
# engine step lock is always OUTERMOST: adopt/seal paths take the cache
# index and allocator locks (and hand frames to the kvxfer sender) while
# holding the engine condition, never the reverse.  The rollout
# controller's state lock wraps engine route mutations.
LOCK_ORDER = (
    ("RolloutController._lock", "ServingEngine._cond"),
    ("DecodeEngine._cond", "PrefixCache._lock", "BlockAllocator._lock"),
    ("DecodeEngine._cond", "KVBlockSender._cond"),
)

# Batch-boundary hooks fire between batches with the queue lock released
# (documented at their assignment sites); CC105 enforces it.  The
# per-step hooks (on_block_sealed / on_handoff) are intentionally NOT
# here: their contract is "fired under the step lock".
UNLOCKED_CALLBACKS = (
    "ServingEngine.on_batch_boundary",
    "DecodeEngine.on_batch_boundary",
    "DecodeEngine.on_preempt",
)


def _flag(name):
    from .. import flags

    return flags.flag(name)


def parse_buckets(spec=None):
    """\"1,4,16\" (or an int sequence) -> sorted unique bucket tuple."""
    if spec is None:
        spec = _flag("serving_buckets")
    if isinstance(spec, str):
        sizes = [int(s) for s in spec.replace(" ", "").split(",") if s]
    else:
        sizes = [int(s) for s in spec]
    if not sizes or any(s <= 0 for s in sizes):
        raise ValueError("serving buckets must be positive ints: %r" % spec)
    return tuple(sorted(set(sizes)))


def parse_tier_weights(spec=None):
    """\"paid:1.0,free:0.45\" -> {tier: weight}; weights in (0, 1]."""
    if spec is None:
        spec = _flag("serving_tier_weights")
    if isinstance(spec, dict):
        out = {str(k): float(v) for k, v in spec.items()}
    else:
        out = {}
        for part in str(spec).replace(" ", "").split(","):
            if not part:
                continue
            name, _, w = part.partition(":")
            if not name or not w:
                raise ValueError("tier weights want tier:weight, got %r"
                                 % part)
            out[name] = float(w)
    if not out or any(w <= 0.0 or w > 1.0 for w in out.values()):
        raise ValueError("tier weights must be in (0, 1]: %r" % spec)
    return out


def tier_weight(weights, tier):
    """(tier label, weight) for one request.  No tier = full budget
    (pre-tier behavior); an unknown tier gets the lowest configured
    weight rather than a free upgrade."""
    if not tier:
        return "default", 1.0
    w = weights.get(tier)
    return (tier, w) if w is not None else (tier, min(weights.values()))


def _route_hash(req_id):
    """Deterministic [0, 1) split point per request (canary routing) —
    stable across replicas so a replayed request lands on the same
    version wherever it fails over to."""
    return (zlib.crc32(req_id.encode("utf-8")) & 0xFFFFFFFF) / 2.0 ** 32


class InferReply:
    """Terminal state of one request: status ok|shed|timeout|error."""

    __slots__ = ("status", "outputs", "error", "retry_after_ms",
                 "latency_ms", "phases")

    def __init__(self, status, outputs=None, error=None,
                 retry_after_ms=0.0, latency_ms=0.0, phases=None):
        self.status = status
        self.outputs = outputs or {}
        self.error = error
        self.retry_after_ms = float(retry_after_ms)
        self.latency_ms = float(latency_ms)
        # SLO phase attribution (always on, tracing-independent):
        # queue_wait_ms / execute_ms / bucket / rows — the client adds
        # wire_ms as its end-to-end latency minus our latency_ms
        self.phases = phases or {}

    @property
    def ok(self):
        return self.status == "ok"

    def to_meta(self):
        meta = {"status": self.status, "error": self.error,
                "retry_after_ms": round(self.retry_after_ms, 3),
                "latency_ms": round(self.latency_ms, 3),
                "outputs": list(self.outputs)}
        if self.phases:
            meta["phases"] = self.phases
        return meta


class _Pending:
    """Handle returned by submit(): wait() blocks for the InferReply."""

    __slots__ = ("model", "tenant", "feeds", "rows", "deadline",
                 "t_submit", "t_dispatch", "req_id", "callback", "_done",
                 "reply", "traceparent", "span", "qspan", "tier", "weight")

    def __init__(self, model, tenant, feeds, rows, deadline_ms, req_id,
                 callback, traceparent=None, tier="default", weight=1.0):
        self.model = model
        self.tenant = tenant
        self.tier = tier
        self.weight = float(weight)
        self.feeds = feeds
        self.rows = rows
        self.t_submit = time.perf_counter()
        self.t_dispatch = None
        self.deadline = self.t_submit + deadline_ms / 1e3
        self.req_id = req_id
        self.callback = callback
        self._done = threading.Event()
        self.reply = None
        self.traceparent = traceparent  # wire context echoed in the reply
        self.span = None    # serving.request (submit -> complete)
        self.qspan = None   # serving.queue_wait child (submit -> dispatch)

    def complete(self, reply):
        reply.latency_ms = (time.perf_counter() - self.t_submit) * 1e3
        self.reply = reply
        self._done.set()
        if self.callback is not None:
            try:
                self.callback(self)
            except Exception:
                pass

    def wait(self, timeout=None):
        self._done.wait(timeout)
        return self.reply


class _ModelEntry:
    __slots__ = ("name", "predictor", "feed_specs", "svc_ms")

    def __init__(self, name, predictor):
        self.name = name
        self.predictor = predictor
        block = predictor.program().global_block()
        self.feed_specs = {}
        for fname in predictor.get_input_names():
            v = block._find_var_recursive(fname)
            shape = tuple(v.shape)
            if shape and shape[0] in (-1, 0):
                shape = shape[1:]
            self.feed_specs[fname] = (shape, v.dtype)
        # EWMA of one dispatched batch's wall time; seeds pessimistic so
        # the first admission estimates err toward accepting
        self.svc_ms = 0.0


class ServingEngine:
    def __init__(self, buckets=None, max_queue=None, deadline_ms=None,
                 batch_window_ms=None):
        self.buckets = parse_buckets(buckets)
        self.max_queue = int(max_queue if max_queue is not None
                             else _flag("serving_max_queue"))
        self.default_deadline_ms = float(
            deadline_ms if deadline_ms is not None
            else _flag("serving_deadline_ms"))
        self.batch_window_ms = float(
            batch_window_ms if batch_window_ms is not None
            else _flag("serving_batch_window_ms"))
        self.tier_weights = parse_tier_weights()
        self._models = {}
        self._queue = []          # FIFO of _Pending (tiers reorder at
        #                           collect time, not at admission)
        self._routes = {}         # base name -> version route dict
        self._cond = threading.Condition()
        self._running = False
        self._draining = False
        self._thread = None
        self.in_batch = False
        # fleet hook: called (outside the queue lock) after every
        # dispatched batch — the fleet coordinator publishes membership
        # changes here, so a shrink lands at a batch boundary
        self.on_batch_boundary = None
        self._done_times = []     # completion stamps for the QPS gauge

    # -- registry ------------------------------------------------------------

    def add_model(self, name, predictor_or_dir):
        """Register a model under `name`: an AnalysisPredictor, or a
        save_inference_model dir to load one from."""
        from ..inference import AnalysisConfig, AnalysisPredictor

        if isinstance(predictor_or_dir, str):
            cfg = AnalysisConfig(predictor_or_dir)
            cfg.disable_gpu()
            cache = _flag("compile_cache_dir")
            if cache:
                cfg.set_optim_cache_dir(cache)
            predictor_or_dir = AnalysisPredictor(cfg)
        self._models[name] = _ModelEntry(name, predictor_or_dir)
        return self._models[name].predictor

    def models(self):
        return list(self._models)

    def spec(self, model):
        """JSON-able feed/fetch signature for `model` (the __spec__ RPC)."""
        from ..framework import dtype_to_np

        e = self._models[model]
        return {
            "model": model,
            "buckets": list(self.buckets),
            "feeds": {n: {"shape": list(shape),
                          "dtype": np.dtype(dtype_to_np(dt)).str}
                      for n, (shape, dt) in e.feed_specs.items()},
            "outputs": e.predictor.get_output_names(),
        }

    # -- versioned routing (rollout control plane) ---------------------------

    def set_route(self, base, active=None, canary=None, fraction=0.0,
                  state="stable"):
        """Route requests addressed to `base`: `active` serves
        (1 - fraction) of the traffic, `canary` the rest.  Requests
        addressed to a registered version name directly always bypass
        routing.  `state` is bookkeeping for the ``rollout_state`` gauge
        (stable=0, canary=1, flipped=2, rolled_back=3)."""
        active = active or base
        if active not in self._models:
            raise ValueError("unknown active version %r" % active)
        if canary is not None and canary not in self._models:
            raise ValueError("unknown canary version %r" % canary)
        with self._cond:
            self._routes[base] = {
                "active": active,
                "canary": canary,
                "fraction": float(fraction) if canary is not None else 0.0,
                "state": state,
            }
        _tm.set_gauge("rollout_state",
                      {"stable": 0, "canary": 1, "flipped": 2,
                       "rolled_back": 3}.get(state, 0), model=base)

    def clear_route(self, base):
        with self._cond:
            self._routes.pop(base, None)

    def routes(self):
        """{base: route dict} snapshot (the __rollout__ payload)."""
        with self._cond:
            return {b: dict(r) for b, r in self._routes.items()}

    def apply_routes(self, routes):
        """Adopt a broadcast route table wholesale (idempotent; unknown
        version names are skipped so a replica that lacks a model never
        routes into a black hole)."""
        for base, r in (routes or {}).items():
            try:
                self.set_route(base, active=r.get("active"),
                               canary=r.get("canary"),
                               fraction=r.get("fraction", 0.0),
                               state=r.get("state", "stable"))
            except ValueError:
                pass

    def resolve(self, model, req_id):
        """Base name -> version name per the route table; a deterministic
        per-request hash keeps the canary split consistent across
        failover replays."""
        r = self._routes.get(model)
        if not r:
            return model
        if r["canary"] is not None and r["fraction"] > 0.0 \
                and _route_hash(req_id) < r["fraction"]:
            return r["canary"]
        return r["active"]

    # -- AOT bucket prewarm --------------------------------------------------

    def prewarm(self):
        """Executor.warmup every (model, bucket); returns the manifest
        {model: {bucket: {"source", "compile_ms"}}}.  With
        FLAGS_compile_cache_dir set, compiled buckets land in the tier-B
        store and later replicas restore from disk."""
        manifest = {}
        for name, e in self._models.items():
            pred = e.predictor
            per = {}
            for b in self.buckets:
                specs = {n: ((b,) + tuple(shape), None)
                         for n, (shape, _dt) in e.feed_specs.items()}
                got = pred._exe.warmup(
                    pred.program(), feed_specs=specs,
                    fetch_list=pred._fetch_vars, scope=pred._scope)
                per[b] = {"source": got["source"],
                          "compile_ms": round(got["compile_ms"], 3)}
                _tm.inc("serving_prewarm_total", model=name,
                        source=got["source"])
                _tm.event("serving_prewarm", model=name, bucket=b,
                          source=got["source"],
                          ms=round(got["compile_ms"], 3))
            manifest[name] = per
        return manifest

    # -- admission -----------------------------------------------------------

    def _projected_wait_ms(self, entry, depth):
        """Queue-drain estimate: batches ahead x EWMA batch service time."""
        if entry.svc_ms <= 0.0:
            return 0.0
        batches_ahead = depth // max(self.buckets) + 1
        return batches_ahead * entry.svc_ms

    def _shed(self, req, reason, error, retry_after_ms):
        _tm.inc("serving_shed_total", reason=reason)
        _tm.inc("serving_tier_shed_total", tier=req.tier)
        req.complete(InferReply("shed", error=error,
                                retry_after_ms=retry_after_ms,
                                phases={"tier": req.tier,
                                        "model": req.model}))
        return req

    def submit(self, model, feeds, tenant="default", deadline_ms=None,
               callback=None, req_id=None, traceparent=None, tier=None):
        """Enqueue one request; returns a _Pending (wait() for the reply).
        Shed/timeout/error requests complete immediately.  `tier` scales
        the deadline budget by its configured weight, so under pressure
        low-weight tiers shed first (deadline-weighted admission)."""
        deadline_ms = float(deadline_ms or self.default_deadline_ms)
        req_id = req_id or uuid.uuid4().hex
        tier, weight = tier_weight(self.tier_weights, tier)
        # version routing happens at admission: the resolved name decides
        # the model entry, the metrics labels, and the reply attribution
        model = self.resolve(model, req_id)
        req = _Pending(model, tenant, feeds, 0, deadline_ms, req_id,
                       callback, traceparent=traceparent, tier=tier,
                       weight=weight)
        entry = self._models.get(model)
        if entry is None or not self._running:
            req.complete(InferReply(
                "error", error="unknown model %r" % model if entry is None
                else "engine not running"))
            return req
        try:
            req.feeds, req.rows = self._normalize(entry, feeds)
        except Exception as e:
            req.complete(InferReply("error", error=str(e)))
            return req
        _tm.inc("serving_requests_total", model=model, tenant=tenant)
        with self._cond:
            if self._draining:
                # retiring replica: push traffic to the surviving fleet
                return self._shed(req, "draining", "replica draining",
                                  max(entry.svc_ms, 1.0))
            depth = len(self._queue)
            if depth >= self.max_queue:
                wait_ms = self._projected_wait_ms(entry, depth)
                # tier eviction: a full queue sheds its lowest-weight
                # member instead of the arrival when the arrival
                # outranks it — paid traffic is never blocked behind
                # queued free-tier work
                victim = min(self._queue, key=lambda r: (r.weight,
                                                         -r.t_submit)) \
                    if self._queue else None
                if victim is not None and victim.weight < req.weight:
                    self._queue.remove(victim)
                    if victim.qspan is not None:
                        victim.qspan.annotate(evicted=True).end()
                    if victim.span is not None:
                        victim.span.annotate(status="shed").end()
                    self._shed(victim, "tier_evicted",
                               "evicted by %s-tier arrival" % req.tier,
                               max(wait_ms, entry.svc_ms, 1.0))
                else:
                    return self._shed(
                        req, "queue_full", "queue full (%d)" % depth,
                        max(wait_ms, entry.svc_ms, 1.0))
            wait_ms = self._projected_wait_ms(entry, len(self._queue))
            budget_ms = deadline_ms * req.weight
            if wait_ms > budget_ms:
                return self._shed(
                    req, "deadline_budget",
                    "projected wait %.0fms exceeds %s-tier budget %.0fms"
                    % (wait_ms, req.tier, budget_ms),
                    wait_ms - budget_ms + entry.svc_ms)
            # admitted: open the request span (parents under the server's
            # admission span when submit runs inside it) and its
            # queue-wait child, ended at dispatch or deadline expiry
            req.span = _tr.start_span(
                "serving.request", model=model, tenant=tenant,
                rows=req.rows, req_id=req.req_id, tier=tier)
            req.qspan = _tr.start_span("serving.queue_wait",
                                       parent=req.span, depth=depth)
            self._queue.append(req)
            _tm.set_gauge("serving_queue_depth", len(self._queue))
            self._cond.notify_all()
        return req

    def infer(self, model, feeds, tenant="default", deadline_ms=None):
        """Synchronous submit + wait."""
        req = self.submit(model, feeds, tenant=tenant,
                          deadline_ms=deadline_ms)
        deadline_ms = float(deadline_ms or self.default_deadline_ms)
        reply = req.wait(timeout=deadline_ms / 1e3 + 30.0)
        return reply if reply is not None else InferReply(
            "timeout", error="no reply within deadline")

    def _normalize(self, entry, feeds):
        """Validate + coerce request feeds; returns (feeds, rows)."""
        from ..framework import dtype_to_np

        rows = None
        out = {}
        for name, (shape, dt) in entry.feed_specs.items():
            if name not in feeds:
                raise ValueError("missing feed %r" % name)
            arr = np.ascontiguousarray(feeds[name],
                                       dtype=dtype_to_np(dt))
            if tuple(arr.shape[1:]) != tuple(shape):
                raise ValueError(
                    "feed %r: expected trailing shape %s, got %s"
                    % (name, tuple(shape), tuple(arr.shape[1:])))
            if rows is None:
                rows = arr.shape[0]
            elif arr.shape[0] != rows:
                raise ValueError("inconsistent batch rows across feeds")
            out[name] = arr
        if rows is None or rows == 0:
            raise ValueError("empty request")
        if rows > max(self.buckets):
            raise ValueError("request rows %d exceed largest bucket %d"
                             % (rows, max(self.buckets)))
        return out, rows

    # -- dispatcher ----------------------------------------------------------

    def start(self):
        if self._running:
            return self
        self._running = True
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        name="serving-dispatch", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain_s=5.0):
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(drain_s)
            self._thread = None
        with self._cond:
            for req in self._queue:
                req.complete(InferReply("error", error="engine stopped"))
                if req.qspan is not None:
                    req.qspan.end()
                if req.span is not None:
                    req.span.annotate(status="error").end()
            self._queue.clear()

    @property
    def draining(self):
        return self._draining

    def drain(self, timeout_s=30.0):
        """Graceful retirement: stop admitting (new submits shed with
        reason="draining" so clients fail over), then wait until every
        already-admitted request has dispatched and the in-flight batch
        finished.  Returns True when the queue fully drained — the
        autoscaler's scale-down exits the replica only after that, so a
        retirement never drops an admitted request."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        deadline = time.perf_counter() + timeout_s
        while time.perf_counter() < deadline:
            with self._cond:
                if not self._queue and not self.in_batch:
                    return True
            time.sleep(0.01)
        return False

    def _bucket_for(self, rows):
        for b in self.buckets:
            if rows <= b:
                return b
        return max(self.buckets)

    def _collect(self):
        """Under the lock: wait for work, then coalesce same-model
        requests within the batch window up to the largest bucket."""
        while self._running and not self._queue:
            self._cond.wait(0.2)
        if not self._queue:
            return None, []
        model = self._queue[0].model
        window_end = time.perf_counter() + self.batch_window_ms / 1e3
        max_rows = max(self.buckets)
        while self._running:
            rows = sum(r.rows for r in self._queue if r.model == model)
            if rows >= max_rows:
                break
            left = window_end - time.perf_counter()
            if left <= 0:
                break
            self._cond.wait(min(left, 0.002))
        # tier-priority assembly: among this model's queued requests the
        # highest-weight ones board the batch first (FIFO within a
        # tier), so paid traffic overtakes queued free-tier work instead
        # of waiting behind it
        cands = sorted((r for r in self._queue if r.model == model),
                       key=lambda r: (-r.weight, r.t_submit))
        batch, rows = [], 0
        taken = set()
        for r in cands:
            if rows + r.rows <= max_rows:
                batch.append(r)
                taken.add(id(r))
                rows += r.rows
        self._queue[:] = [r for r in self._queue if id(r) not in taken]
        _tm.set_gauge("serving_queue_depth", len(self._queue))
        return model, batch

    def _dispatch_loop(self):
        while True:
            with self._cond:
                if not self._running:
                    return
                model, batch = self._collect()
            if not batch:
                continue
            now = time.perf_counter()
            live = []
            for r in batch:
                if now > r.deadline:
                    _tm.inc("serving_timeout_total", model=r.model)
                    r.complete(InferReply(
                        "timeout", error="deadline expired in queue",
                        phases={"queue_wait_ms":
                                round((now - r.t_submit) * 1e3, 3),
                                "rows": r.rows}))
                    if r.qspan is not None:
                        r.qspan.annotate(expired=True).end()
                    if r.span is not None:
                        r.span.annotate(status="timeout").end()
                else:
                    r.t_dispatch = now
                    if r.qspan is not None:
                        r.qspan.end()
                    live.append(r)
            if live:
                self.in_batch = True
                try:
                    self._run_batch(self._models[model], live)
                finally:
                    self.in_batch = False
            if self.on_batch_boundary is not None:
                try:
                    self.on_batch_boundary()
                except Exception:
                    pass

    @staticmethod
    def _phases(r, execute_ms, bucket):
        """Per-request SLO phase attribution for the reply meta (always
        on — the client derives wire_ms as e2e minus server latency).
        Carries the tier and the RESOLVED model version so per-tier and
        per-version p99s fall out of the same reply stream."""
        t_d = r.t_dispatch if r.t_dispatch is not None else r.t_submit
        return {"queue_wait_ms": round((t_d - r.t_submit) * 1e3, 3),
                "execute_ms": round(execute_ms, 3),
                "bucket": bucket, "rows": r.rows,
                "tier": r.tier, "model": r.model}

    def _run_batch(self, entry, batch):
        rows = sum(r.rows for r in batch)
        bucket = self._bucket_for(rows)
        pred = entry.predictor
        # a batch serves N requests from (up to) N different traces, so
        # the batch span is a root that LINKS them rather than parenting
        bspan = _tr.start_span("serving.batch", model=entry.name,
                               bucket=bucket, rows=rows,
                               requests=len(batch))
        for r in batch:
            bspan.link(r.span.context if r.span is not None else None)
        with _tr.activate(bspan):
            with _tr.span("serving.pad_to_bucket", rows=rows,
                          bucket=bucket):
                feed = {}
                for name in entry.feed_specs:
                    parts = [r.feeds[name] for r in batch]
                    stacked = np.concatenate(parts, axis=0) \
                        if len(parts) > 1 else parts[0]
                    if rows < bucket:
                        pad = np.zeros(
                            (bucket - rows,) + stacked.shape[1:],
                            dtype=stacked.dtype)
                        stacked = np.concatenate([stacked, pad], axis=0)
                    feed[name] = stacked
            # write-through breadcrumb: if this replica is SIGKILLed
            # mid-execute, flightrec-<pid>.json already names the batch
            _tr.note("batch_start", model=entry.name, bucket=bucket,
                     req_ids=[r.req_id for r in batch])
            t0 = time.perf_counter()
            try:
                # named fault point per model VERSION — a chaos/rollback
                # leg arms e.g. "serving.execute.fc@v2:error:1.0" to
                # seed a bad canary without a genuinely broken model
                if maybe_fail("serving.execute." + entry.name) == "error":
                    raise RuntimeError("injected execute fault (%s)"
                                       % entry.name)
                with _tr.span("serving.execute", bucket=bucket):
                    with scope_guard(pred._scope):
                        vals = pred._exe.run(pred.program(), feed=feed,
                                             fetch_list=pred._fetch_vars)
            except Exception as e:
                ms = (time.perf_counter() - t0) * 1e3
                for r in batch:
                    r.complete(InferReply(
                        "error", error=str(e),
                        phases=self._phases(r, ms, bucket)))
                    if r.span is not None:
                        r.span.annotate(status="error").end()
                _tm.inc("serving_batch_errors_total", model=entry.name)
                _tm.inc("serving_request_errors_total", len(batch),
                        model=entry.name)
                bspan.annotate(error=str(e)[:200]).end()
                return
        ms = (time.perf_counter() - t0) * 1e3
        entry.svc_ms = ms if entry.svc_ms <= 0 else \
            0.7 * entry.svc_ms + 0.3 * ms
        outs = [np.asarray(v) for v in vals]
        names = pred.get_output_names()
        off = 0
        for r in batch:
            sliced = {}
            for n, o in zip(names, outs):
                # slice per-request rows when the output carries the batch
                # dim; batch-free outputs replicate to every request
                sliced[n] = o[off:off + r.rows].copy() \
                    if o.ndim and o.shape[0] == bucket else o
            off += r.rows
            r.complete(InferReply("ok", outputs=sliced,
                                  phases=self._phases(r, ms, bucket)))
            if r.span is not None:
                r.span.annotate(status="ok", bucket=bucket).end()
            _tm.observe("serving_latency_ms", r.reply.latency_ms,
                        model=entry.name)
            # per-version execute p99: the rollout gate's scrape-side
            # signal (phase attribution, not end-to-end latency)
            _tm.observe("serving_execute_ms", ms, model=entry.name)
            # per-tier server-side latency (queue wait + execute, the
            # loadgen "server_ms" attribution) as a MERGEABLE histogram:
            # fleetmon's burn-rate SLO rules window its bucket deltas
            _tm.observe("server_ms",
                        r.reply.phases.get("queue_wait_ms", 0.0) + ms,
                        tier=r.tier)
            # goodput numerator/denominator: a reply that beat its
            # deadline is goodput, a late one is only raw throughput
            met = time.perf_counter() <= r.deadline
            _tm.inc("serving_deadline_met_total" if met
                    else "serving_deadline_missed_total", tier=r.tier)
        _tm.inc("serving_batches_total", model=entry.name,
                bucket=str(bucket))
        _tm.observe("serving_batch_fill", rows / float(bucket),
                    model=entry.name)
        bspan.end()
        now = time.time()
        self._done_times.extend([now] * len(batch))
        cut = now - _QPS_WINDOW_S
        while self._done_times and self._done_times[0] < cut:
            self._done_times.pop(0)
        _tm.set_gauge("serving_qps", len(self._done_times) / _QPS_WINDOW_S)


# ===========================================================================
# Autoregressive decode serving: paged KV-cache + token-level batching
# ===========================================================================

class _DecodeSeq:
    """One autoregressive sequence moving through the decode scheduler.

    Prefill is token-feed: the prompt is fed one token per step through
    the SAME bucketed step executable as generation, so mixed-phase
    batches never force a second compiled shape.  ``n_fed`` counts
    positions already written to the KV cache; once it passes the last
    prompt position every step's argmax is a generated token."""

    __slots__ = ("pending", "prompt", "max_new", "eos_id", "on_token",
                 "blocks", "table", "draft_blocks", "draft_table",
                 "n_fed", "next_tok", "out",
                 "t_admit", "t_first", "token_times", "admit_seq",
                 "aborted", "hashes", "published", "cached_tokens",
                 "handoff", "prefill_upto",
                 "replay_upto", "resume_tail",
                 "hist_hashes", "hist_published")

    def __init__(self, pending, prompt, max_new, eos_id, on_token, maxb):
        self.pending = pending
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new)
        self.eos_id = int(eos_id)
        self.on_token = on_token
        self.blocks = []                      # allocator block ids held
        self.table = np.full(maxb, -1, np.int32)
        self.draft_blocks = []                # speculative draft KV lanes
        self.draft_table = np.full(maxb, -1, np.int32)
        self.n_fed = 0
        self.next_tok = self.prompt[0]
        self.out = []
        self.t_admit = None
        self.t_first = None                   # first *generated* token
        self.token_times = []                 # perf_counter per token
        self.admit_seq = 0                    # preemption picks max()
        self.aborted = False
        # prefix-cache state, set at admission: the full-prompt hash
        # chain, how many leading blocks are already indexed (shared hits
        # + this sequence's publishes), and the matched token count
        self.hashes = None
        self.published = 0
        self.cached_tokens = 0
        # disaggregated prefill role: a handoff sequence stops at
        # prefill_upto (the last full-block boundary), streams its sealed
        # blocks to a decode replica, and never generates a token here
        self.handoff = False
        self.prefill_upto = 0
        # replay/resume state: positions below ``replay_upto`` are
        # re-fed from KNOWN history (prompt ++ out) with step outputs
        # discarded — never re-emitted.  A fresh sequence replays
        # exactly its prompt; a resumed (migrated-in) or preempted one
        # replays its already-emitted tokens too, so emission always
        # continues at the next new index.  ``resume_tail`` is an
        # optional migrated partial-block hand-off consumed once at
        # admission; ``hist_hashes``/``hist_published`` extend the
        # prompt hash chain over generated tokens for the history
        # publication that keeps peer prefix indexes warm
        # (FLAGS_session_migration).
        self.replay_upto = len(self.prompt)
        self.resume_tail = None
        self.hist_hashes = []
        self.hist_published = 0

    @property
    def in_prefill(self):
        return self.n_fed < self.replay_upto

    def feed_tok(self, i):
        """Token fed at position ``i`` during replay — the history
        ``prompt ++ out`` (valid for every ``i < replay_upto``)."""
        p = len(self.prompt)
        return self.prompt[i] if i < p else self.out[i - p]

    def feed_slice(self, start, span):
        return [self.feed_tok(i) for i in range(start, start + span)]

    @property
    def total(self):
        return len(self.prompt) + self.max_new

    def reset_for_recompute(self):
        """Preempted (or an aborted migration hand-off): blocks were
        freed; replay known history from scratch.  Emitted tokens are
        KEPT — greedy decode is deterministic, so the replay re-feeds
        ``prompt ++ out`` with outputs discarded and emission resumes
        at the next NEW index, byte-identical to an uninterrupted run.
        (Freed shared blocks only dropped a reference — re-admission
        re-matches the prefix index, now including any published
        history blocks, so the replay usually skips straight past the
        cached prefix again.)"""
        self.blocks = []
        self.table.fill(-1)
        self.draft_blocks = []
        self.draft_table.fill(-1)
        self.n_fed = 0
        self.next_tok = self.prompt[0]
        self.replay_upto = len(self.prompt) + len(self.out)
        self.t_first = None
        self.token_times = []
        self.hashes = None
        self.published = 0
        self.cached_tokens = 0
        self.hist_hashes = []
        self.hist_published = 0


class _DecodeModel:
    __slots__ = ("name", "cfg", "params", "kv_config", "cache", "stepfn",
                 "maxb", "step_ms", "prefix", "__weakref__",
                 # speculative decode (spec_k == 0 means off): the draft
                 # decoder runs k tokens ahead through its own paged pool,
                 # then verifyfn scores all k+1 positions in one target call
                 "spec_k", "draft_cfg", "draft_params", "draft_kv_config",
                 "draft_cache", "rolloutfn", "ingestfn", "verifyfn")

    def __init__(self, name, cfg, params, kv_config, cache, stepfn):
        self.name = name
        self.cfg = cfg
        self.params = params        # jnp arrays (device-resident)
        self.kv_config = kv_config
        self.cache = cache
        self.stepfn = stepfn        # CarriedStepFn over make_paged_step
        self.maxb = -(-cfg.max_seq // kv_config.block_size)
        self.step_ms = 0.0          # EWMA of one decode step
        self.prefix = None          # PrefixCache (FLAGS_prefix_cache)
        self.spec_k = 0
        self.draft_cfg = None
        self.draft_params = None
        self.draft_kv_config = None
        self.draft_cache = None
        self.rolloutfn = None       # draft: k chained proposals per lane
        self.ingestfn = None        # draft: multi-token catch-up writes
        self.verifyfn = None        # target: [B, k+1] multi-token step


class DecodeEngine:
    """Token-level continuous batching over an engine-owned paged
    KV-cache.

    Every iteration of the decode loop:

    1. expires deadline-passed sequences, then admits waiting sequences
       into free lanes while the allocator can cover their prompts (in
       ``request`` mode admission only happens when no lane is active —
       the comparison baseline for the token-level win);
    2. picks the smallest configured lane bucket >= active count and
       rebuilds tok/pos/block_tables/context_lens arrays for it — idle
       lanes point at the reserved scratch block with context_len 0;
    3. runs ONE AOT-compiled step (``CarriedStepFn``; the paged KV carry
       is donated and swapped back into the cache), so mixed-length
       sequences never trigger a runtime compile;
    4. appends each live lane's sampled token, finishing sequences at
       max_new/EOS and freeing their blocks in the SAME iteration so the
       next step's admission sees the space.

    Mid-decode allocation failure preempts the youngest active sequence
    (blocks freed, sequence re-queued for deterministic recompute) —
    counted as ``kv_block_evictions_total``.  Admission-time shortage
    sheds with ``retry_after_ms`` derived from the EWMA step time; all
    pressure decisions budget against ``free + evictable`` (a warm
    prefix cache is reclaimable, never a reason to shed).

    Prefix caching (``FLAGS_prefix_cache``): admission matches each
    prompt's hash chain against the model's ``PrefixCache``, seeds the
    block table with shared (refcounted) blocks, and jumps the feed
    pointer so prefill computes only the uncached tail; prefill-completed
    full prompt blocks are sealed + published back.  Outputs are bitwise
    identical cache-on vs cache-off — a hit only skips recomputing KV
    values the reference run would have produced identically.

    ``FLAGS_decode_prefill_token_budget`` caps the prefill tokens mixed
    into one iteration (round-robin across prefilling lanes; decode
    lanes always run), bounding decode ITL under long-prompt bursts
    without adding compiled shapes."""

    def __init__(self, buckets=None, max_queue=None, deadline_ms=None,
                 mode=None):
        self.buckets = parse_buckets(
            buckets if buckets is not None
            else _flag("serving_decode_buckets"))
        self.max_queue = int(max_queue if max_queue is not None
                             else _flag("serving_max_queue"))
        self.default_deadline_ms = float(
            deadline_ms if deadline_ms is not None
            else _flag("serving_deadline_ms"))
        mode = mode if mode is not None else _flag("serving_decode_mode")
        if mode not in ("token", "request"):
            raise ValueError("serving_decode_mode must be token|request, "
                             "got %r" % (mode,))
        self.mode = mode
        self.tier_weights = parse_tier_weights()
        self._draining = False
        self._models = {}
        self._waiting = []          # FIFO of _DecodeSeq
        self._active = []
        self._cond = threading.Condition()
        self._running = False
        self._thread = None
        self._admit_seq = 0
        self._step_no = 0
        self._rr_prefill = 0        # round-robin pointer (token budget)
        self.in_batch = False
        self.on_batch_boundary = None
        # disaggregated prefill role hooks (serving/disagg.py wires them):
        # on_block_sealed(m, seq, j, digest) fires under the step lock for
        # every sealed full-prompt block of a handoff sequence (including
        # prefix-cache hits at admission — a warm prefill replica still
        # announces the digests); on_handoff(m, seq) fires once the feed
        # pointer reaches prefill_upto, before the blocks are freed
        self.on_block_sealed = None
        self.on_handoff = None
        # live session migration (serving/migrate.py): sequences parked
        # mid-hand-off (export_session -> commit/abort), a bounded ring
        # of recently committed-away req_ids (loud double-migration
        # refusal), and pressure-trigger victims reported at the next
        # batch boundary through ``on_preempt(list of (req_id, model))``
        # — fired with the step lock RELEASED (CC105 contract)
        self._migrating = {}
        self._migrated = []
        self._preempted = []
        self.on_preempt = None

    # -- registry ------------------------------------------------------------

    def add_model(self, name, source, kv_blocks=None, draft=None,
                  speculative_k=None):
        """Register a decode model: `source` is a save_decoder() dir or a
        (DecoderConfig, params) pair.  KV pool size comes from
        kv_blocks / FLAGS_kv_cache_blocks, capped by
        FLAGS_hbm_budget_bytes net of the weights' footprint.

        ``draft`` is an optional (DecoderConfig, params) draft decoder
        (a dir `source` auto-loads its bundled ``<dir>/draft``);
        ``speculative_k`` (default FLAGS_speculative_k) > 0 with a draft
        present turns on speculative decoding: the draft gets its own
        paged pool with the SAME block count as the target (equal token
        capacity keeps the two allocators in lockstep), and three AOT
        step fns replace the single-token one — verify ([B, k+1] target),
        rollout (k chained draft proposals), ingest (draft catch-up)."""
        import jax.numpy as jnp

        from . import decode_model as _dm
        from . import kv_cache as _kvc
        from ..core.executor import CarriedStepFn

        if isinstance(source, str):
            cfg, params = _dm.load_decoder(source)
            if draft is None:
                draft = _dm.load_draft(source)
        else:
            cfg, params = source
        k = int(speculative_k if speculative_k is not None
                else _flag("speculative_k") or 0)
        if draft is None:
            k = 0   # no draft bundle -> non-speculative regardless of k
        resident = sum(int(np.asarray(v).nbytes) for v in params.values())
        draft_resident = 0
        if k > 0:
            dcfg, dparams = draft
            if dcfg.vocab != cfg.vocab:
                raise ValueError("draft vocab %d != target vocab %d"
                                 % (dcfg.vocab, cfg.vocab))
            if dcfg.max_seq != cfg.max_seq:
                raise ValueError("draft max_seq %d != target max_seq %d "
                                 "(block tables must line up)"
                                 % (dcfg.max_seq, cfg.max_seq))
            draft_resident = sum(int(np.asarray(v).nbytes)
                                 for v in dparams.values())
        kv_config = _kvc.KVCacheConfig(
            layers=cfg.layers, heads=cfg.heads, head_dim=cfg.head_dim,
            block_size=int(_flag("kv_block_size")),
            num_blocks=2,  # placeholder; plan_num_blocks decides below
            dtype=str(_flag("kv_cache_dtype")))
        n, capped = _kvc.plan_num_blocks(
            kv_config, model_resident_bytes=resident + draft_resident,
            requested=kv_blocks)
        kv_config.num_blocks = n
        cache = _kvc.PagedKVCache(kv_config)
        prefix = None
        if bool(_flag("prefix_cache")):
            # content-addressed prefix reuse over the SAME pool: sealed
            # full-prompt blocks park evictable at zero refs, the index
            # revives them on a hash-chain match at admission.  The draft
            # pool (speculation) is deliberately NOT indexed: its blocks
            # only steer acceptance, and a tail-only draft prefill can
            # never change the verified output.
            prefix = _kvc.PrefixCache(cache.allocator,
                                      kv_config.block_size, namespace=name)
        jparams = {key: jnp.asarray(v) for key, v in params.items()}
        stepfn = CarriedStepFn(
            _dm.make_paged_step(cfg, kv_config), donate_argnums=(0,),
            name="decode_step",
            key_parts={"kind": "decode_step", "model": name,
                       "cfg": cfg.to_dict(),
                       "kv": {"block_size": kv_config.block_size,
                              "num_blocks": kv_config.num_blocks,
                              "dtype": kv_config.dtype},
                       "pallas": bool(_flag("use_pallas_paged_attention"))})
        entry = _DecodeModel(name, cfg, jparams, kv_config, cache, stepfn)
        entry.prefix = prefix
        if k > 0:
            # draft pool mirrors the target's block COUNT (draft blocks
            # are strictly smaller at fewer layers), so any sequence the
            # target pool can hold, the draft pool can shadow; the budget
            # plan above already counted both param sets, and MEM001
            # reports the exact combined pool bytes afterwards
            draft_kv = _kvc.KVCacheConfig(
                layers=dcfg.layers, heads=dcfg.heads,
                head_dim=dcfg.head_dim, block_size=kv_config.block_size,
                num_blocks=n, dtype=kv_config.dtype)
            base_parts = {"model": name, "kv": {
                "block_size": kv_config.block_size, "num_blocks": n,
                "dtype": kv_config.dtype},
                "pallas": bool(_flag("use_pallas_paged_attention"))}
            entry.spec_k = k
            entry.draft_cfg = dcfg
            entry.draft_params = {key: jnp.asarray(v)
                                  for key, v in dparams.items()}
            entry.draft_kv_config = draft_kv
            entry.draft_cache = _kvc.PagedKVCache(draft_kv)
            entry.verifyfn = CarriedStepFn(
                _dm.make_paged_step_multi(cfg, kv_config, k + 1),
                donate_argnums=(0,), name="decode_verify",
                key_parts=dict(base_parts, kind="decode_verify",
                               cfg=cfg.to_dict(), width=k + 1))
            entry.rolloutfn = CarriedStepFn(
                _dm.make_draft_rollout(dcfg, draft_kv, k),
                donate_argnums=(0,), name="draft_rollout",
                key_parts=dict(base_parts, kind="draft_rollout",
                               cfg=dcfg.to_dict(), k=k))
            entry.ingestfn = CarriedStepFn(
                _dm.make_paged_step_multi(dcfg, draft_kv, k + 1),
                donate_argnums=(0,), name="draft_ingest",
                key_parts=dict(base_parts, kind="draft_ingest",
                               cfg=dcfg.to_dict(), width=k + 1))
        # engine-owned resident weights (target + draft) fold into the
        # MEM001 static peak beside the KV pool bytes
        _kvc.register_resident_bytes(entry, resident + draft_resident)
        self._models[name] = entry
        _tm.event("decode_model_added", model=name, blocks=n,
                  budget_capped=capped, kv_bytes=cache.nbytes,
                  speculative_k=k, prefix_cache=prefix is not None,
                  draft_kv_bytes=entry.draft_cache.nbytes if k else 0)
        return self._models[name]

    def models(self):
        return list(self._models)

    def spec(self, model):
        m = self._models[model]
        out = {"model": model, "type": "decode",
               "vocab": m.cfg.vocab, "max_seq": m.cfg.max_seq,
               "buckets": list(self.buckets), "mode": self.mode,
               "block_size": m.kv_config.block_size,
               "num_blocks": m.kv_config.num_blocks,
               "kv_dtype": m.kv_config.dtype,
               "speculative_k": m.spec_k,
               "prefix_cache": m.prefix is not None}
        if m.spec_k > 0:
            out["draft"] = {"layers": m.draft_cfg.layers,
                            "num_blocks": m.draft_kv_config.num_blocks,
                            "kv_bytes": m.draft_cache.nbytes}
        return out

    # -- AOT bucket prewarm --------------------------------------------------

    def prewarm(self):
        """Compile (or restore from the tier-B disk cache) the decode
        step for EVERY lane bucket before the first request.  After
        this, mixed-length continuous batching can only hit the
        in-memory executables: ``executor_cache_miss_total`` stays flat
        under load — the zero-runtime-compile proof."""
        manifest = {}
        for name, m in self._models.items():
            per = {}
            for b in self.buckets:
                if m.spec_k > 0:
                    # speculation replaces the single-token step with
                    # three fns; warm each per (model, bucket, k)
                    w = m.spec_k + 1
                    warms = {
                        "verify": m.verifyfn.warmup(
                            m.cache.carry(), m.params,
                            np.zeros((b, w), np.int32),
                            np.zeros((b, w), np.int32),
                            np.full((b, m.maxb), -1, np.int32),
                            np.zeros((b, w), np.int32)),
                        "draft_rollout": m.rolloutfn.warmup(
                            m.draft_cache.carry(), m.draft_params,
                            np.zeros(b, np.int32), np.zeros(b, np.int32),
                            np.full((b, m.maxb), -1, np.int32),
                            np.zeros(b, np.int32), np.zeros(b, np.int32)),
                        "draft_ingest": m.ingestfn.warmup(
                            m.draft_cache.carry(), m.draft_params,
                            np.zeros((b, w), np.int32),
                            np.zeros((b, w), np.int32),
                            np.full((b, m.maxb), -1, np.int32),
                            np.zeros((b, w), np.int32)),
                    }
                    per[b] = {}
                    for kind, got in warms.items():
                        per[b][kind] = {
                            "source": got["source"],
                            "compile_ms": round(got["compile_ms"], 3)}
                        _tm.inc("serving_prewarm_total", model=name,
                                source=got["source"])
                        _tm.event("serving_prewarm", model=name, bucket=b,
                                  source=got["source"], decode=True,
                                  fn=kind, k=m.spec_k,
                                  ms=round(got["compile_ms"], 3))
                    continue
                got = m.stepfn.warmup(*self._step_args(
                    m, b, np.zeros(b, np.int32), np.zeros(b, np.int32),
                    np.full((b, m.maxb), -1, np.int32),
                    np.zeros(b, np.int32)))
                per[b] = {"source": got["source"],
                          "compile_ms": round(got["compile_ms"], 3)}
                _tm.inc("serving_prewarm_total", model=name,
                        source=got["source"])
                _tm.event("serving_prewarm", model=name, bucket=b,
                          source=got["source"], decode=True,
                          ms=round(got["compile_ms"], 3))
            manifest[name] = per
        return manifest

    def _step_args(self, m, bucket, tok, pos, tables, lens):
        return (m.cache.carry(), m.params, tok, pos, tables, lens)

    # -- admission -----------------------------------------------------------

    def _retry_after_ms(self, m):
        """Time for roughly one block's worth of tokens to drain."""
        per = m.step_ms if m.step_ms > 0 else 1.0
        return max(per * m.kv_config.block_size, 1.0)

    def handoff_prefill_upto(self, model, prompt_len):
        """Tokens a prefill-role replica computes for this prompt: the
        last full-block boundary below ``len(prompt)`` (the partial tail
        block can never transfer — the prefix chain only keys FULL
        blocks, and ``match`` caps at len-1 so the decode half always
        computes at least one tail token itself).  0 means nothing is
        transferable and the request should be forwarded whole."""
        m = self._models.get(model)
        if m is None or m.prefix is None:
            return 0
        bs = m.kv_config.block_size
        return max(0, ((int(prompt_len) - 1) // bs) * bs)

    def submit(self, model, prompt_ids, max_new_tokens=16, tenant="default",
               deadline_ms=None, eos_id=-1, callback=None, on_token=None,
               req_id=None, traceparent=None, tier=None, handoff=False,
               resume_from=None, resume_tail=None):
        """Enqueue one autoregressive request; returns a _Pending whose
        reply carries outputs={"tokens"} plus TTFT/ITL phases.
        ``on_token(req_id, index, token, done, status)`` fires per
        generated token (the server publishes stream chunks from it);
        the terminal call carries token=None on non-ok completion.

        ``handoff=True`` is the prefill-role mode: the sequence runs
        chunked prefill up to the last full-block boundary, fires the
        ``on_block_sealed``/``on_handoff`` hooks as blocks seal, then
        completes with status "handoff" (never generating a token); the
        paired decode replica owns generation.

        ``resume_from`` (a migrated-in or crash-recovered session) is
        the list of tokens the client already holds: the sequence seeds
        its output with them, admission prefix-matches the full-history
        chain (prompt ++ tokens) instead of the prompt alone, and decode
        resumes at the next NEW index — no received token is ever
        re-emitted.  ``resume_tail`` optionally carries the migrated
        partial tail block ({"digest", "valid", "arrays"}); it is
        validated against the recomputed tail digest and dropped (the
        replay recomputes < 1 block) on any mismatch.  A resume for a
        req_id already live here is loudly refused — double migration
        must never double-run a session."""
        deadline_ms = float(deadline_ms or self.default_deadline_ms)
        prompt_ids = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        tier, weight = tier_weight(self.tier_weights, tier)
        req = _Pending(model, tenant, None, len(prompt_ids), deadline_ms,
                       req_id or uuid.uuid4().hex, callback,
                       traceparent=traceparent, tier=tier, weight=weight)

        def _early(reply):
            """Terminal before admission: also emit the done stream chunk
            so a streaming client unblocks instead of hanging on k=0."""
            req.complete(reply)
            if on_token is not None:
                try:
                    on_token(req.req_id, 0, None, True, reply.status)
                except Exception:
                    pass
            return req

        m = self._models.get(model)
        if m is None or not self._running:
            return _early(InferReply(
                "error", error="unknown decode model %r" % model
                if m is None else "decode engine not running"))
        if not prompt_ids:
            return _early(InferReply("error", error="empty prompt"))
        total = len(prompt_ids) + int(max_new_tokens)
        if total > m.cfg.max_seq:
            return _early(InferReply(
                "error",
                error="prompt+max_new %d exceeds max_seq %d"
                      % (total, m.cfg.max_seq)))
        if any(t < 0 or t >= m.cfg.vocab for t in prompt_ids):
            return _early(InferReply("error", error="token out of vocab"))
        need_cap = m.cache.blocks_for_tokens(total)
        if need_cap > m.cache.allocator.capacity:
            return _early(InferReply(
                "error",
                error="sequence needs %d KV blocks, pool holds %d"
                      % (need_cap, m.cache.allocator.capacity)))
        if m.spec_k > 0 and m.draft_cache.blocks_for_tokens(total) > \
                m.draft_cache.allocator.capacity:
            return _early(InferReply(
                "error",
                error="sequence needs %d draft KV blocks, pool holds %d"
                      % (m.draft_cache.blocks_for_tokens(total),
                         m.draft_cache.allocator.capacity)))
        if handoff:
            upto = self.handoff_prefill_upto(model, len(prompt_ids))
            if upto <= 0:
                return _early(InferReply(
                    "error", error="nothing to hand off: prompt of %d has "
                    "no full %d-token block below its tail"
                    % (len(prompt_ids), m.kv_config.block_size)))
        resume_out = None
        if resume_from is not None:
            toks = [int(t) for t in np.asarray(resume_from).reshape(-1)]
            err = None
            if handoff:
                err = "resume_from resumes decode; handoff is prefill-role"
            elif not toks:
                err = "resume_from carries no tokens"
            elif len(toks) >= int(max_new_tokens):
                err = "resume_from already holds all %d requested " \
                      "tokens" % int(max_new_tokens)
            elif int(eos_id) >= 0 and int(eos_id) in toks:
                err = "resume_from already contains eos"
            elif any(t < 0 or t >= m.cfg.vocab for t in toks):
                err = "resume token out of vocab"
            if err is not None:
                _tm.inc("kv_migrate_resume_total", result="refused",
                        model=model)
                _tm.inc("kv_migrate_refused_total", reason="bad_resume")
                return _early(InferReply("error", error=err))
            resume_out = toks
        _tm.inc("serving_decode_requests_total", model=model, tenant=tenant)
        seq = _DecodeSeq(req, prompt_ids, max_new_tokens, eos_id, on_token,
                         m.maxb)
        if handoff:
            seq.handoff = True
            seq.prefill_upto = upto
        if resume_out is not None:
            seq.out = resume_out
            seq.replay_upto = len(prompt_ids) + len(resume_out)
            seq.resume_tail = resume_tail
        with self._cond:
            if resume_out is not None and (
                    req.req_id in self._migrating or any(
                        s.pending.req_id == req.req_id
                        for s in self._active + self._waiting)):
                _tm.inc("kv_migrate_resume_total", result="refused",
                        model=model)
                _tm.inc("kv_migrate_refused_total", reason="duplicate")
                return _early(InferReply(
                    "error", error="req_id %s is already live here "
                    "(double migration refused)" % req.req_id))
            if self._draining:
                _tm.inc("serving_shed_total", reason="draining")
                _tm.inc("serving_tier_shed_total", tier=tier)
                return _early(InferReply(
                    "shed", error="replica draining",
                    retry_after_ms=self._retry_after_ms(m)))
            if len(self._waiting) >= self.max_queue:
                # tier eviction mirrors ServingEngine: a full waiting
                # queue sheds its lowest-weight member when the arrival
                # outranks it
                victim = min(self._waiting,
                             key=lambda s: (s.pending.weight,
                                            -s.pending.t_submit)) \
                    if self._waiting else None
                if victim is not None and victim.pending.weight < weight:
                    self._waiting.remove(victim)
                    _tm.inc("serving_shed_total", reason="tier_evicted")
                    _tm.inc("serving_tier_shed_total",
                            tier=victim.pending.tier)
                    self._finish(victim, InferReply(
                        "shed",
                        error="evicted by %s-tier arrival" % tier,
                        retry_after_ms=self._retry_after_ms(m)))
                else:
                    _tm.inc("serving_shed_total", reason="queue_full")
                    _tm.inc("serving_tier_shed_total", tier=tier)
                    return _early(InferReply(
                        "shed",
                        error="queue full (%d)" % len(self._waiting),
                        retry_after_ms=self._retry_after_ms(m)))
            # admission-time KV pressure: blocks already promised to the
            # queue ahead plus this prompt must fit the RECLAIMABLE pool
            # (free list + zero-ref evictable cached blocks — a warm
            # prefix cache never causes a spurious shed; alloc reclaims
            # evictable LRU-first on demand) — BOTH pools when
            # speculating (the draft shadows every sequence) — else shed
            # with a drain-time hint instead of queueing behind an
            # out-of-memory head-of-line
            promised = sum(
                m.cache.blocks_for_tokens(s.replay_upto)
                for s in self._waiting if s.pending.model == model)
            need_now = promised + m.cache.blocks_for_tokens(seq.replay_upto)
            free_now = m.cache.allocator.reclaimable
            if m.spec_k > 0:
                # equal block geometry -> the same block count applies;
                # the binding pool is whichever could free fewer blocks
                # (the draft pool never seals, so its reclaimable == free)
                free_now = min(free_now,
                               m.draft_cache.allocator.reclaimable)
            if need_now > free_now:
                _tm.inc("serving_shed_total", reason="kv_oom")
                _tm.inc("serving_tier_shed_total", tier=tier)
                return _early(InferReply(
                    "shed",
                    error="KV pool exhausted (%d reclaimable blocks)"
                          % free_now,
                    retry_after_ms=self._retry_after_ms(m)))
            req.span = _tr.start_span(
                "serving.request", model=model, tenant=tenant,
                decode=True, prompt_tokens=len(prompt_ids),
                max_new=int(max_new_tokens), req_id=req.req_id)
            req.qspan = _tr.start_span("serving.queue_wait",
                                       parent=req.span,
                                       depth=len(self._waiting))
            self._waiting.append(seq)
            if resume_out is not None:
                _tm.inc("kv_migrate_resume_total", result="accepted",
                        model=model)
            _tm.set_gauge("serving_queue_depth",
                          len(self._waiting))
            self._cond.notify_all()
        return req

    def generate(self, model, prompt_ids, max_new_tokens=16, **kw):
        """Synchronous submit + wait."""
        deadline_ms = float(kw.get("deadline_ms")
                            or self.default_deadline_ms)
        req = self.submit(model, prompt_ids,
                          max_new_tokens=max_new_tokens, **kw)
        reply = req.wait(timeout=deadline_ms / 1e3 + 30.0)
        return reply if reply is not None else InferReply(
            "timeout", error="no reply within deadline")

    def abort(self, req_id):
        """Drop a sequence by request id (client replay after a timeout
        sends this so an abandoned prefill frees its blocks).  Returns
        True when a waiting/active sequence was found."""
        with self._cond:
            for i, s in enumerate(self._waiting):
                if s.pending.req_id == req_id:
                    self._waiting.pop(i)
                    _tm.set_gauge("serving_queue_depth", len(self._waiting))
                    self._finish(s, InferReply("aborted",
                                               error="aborted by client"))
                    _tm.inc("serving_abort_total", phase="queued")
                    return True
            for s in self._active:
                if s.pending.req_id == req_id and not s.aborted:
                    s.aborted = True   # decode loop frees at next boundary
                    _tm.inc("serving_abort_total",
                            phase="prefill" if s.in_prefill else "decode")
                    return True
        return False

    # -- sealed-block adoption (the decode half of a disaggregated pair) -----

    def adopt_kv_block(self, model, digest, arrays):
        """Adopt one transferred sealed block into ``model``'s pool:
        allocate a private block, install the payload into the carry,
        publish it under ``digest`` and park it evictable — the commit
        frame's ordinary ``submit`` then prefix-matches it exactly like a
        locally-computed cache hit (refcount + hash-chain invariants come
        from the existing machinery, not a parallel path).  Returns
        "adopted", "cached" (digest already indexed — the warm-replica
        skip), or "rejected:<reason>"; rejection is always safe because
        the commit frame carries the full prompt and the engine simply
        recomputes the prefill locally."""
        m = self._models.get(model)
        if m is None:
            return "rejected:unknown model %r" % (model,)
        if m.prefix is None:
            return "rejected:prefix cache disabled"
        with self._cond:
            if m.prefix.lookup(digest) is not None:
                _tm.inc("kv_xfer_adopt_total", result="cached",
                        model=model)
                return "cached"
            got = m.cache.allocator.alloc(1)
            if got is None:
                _tm.inc("kv_xfer_adopt_total", result="nopool",
                        model=model)
                return "rejected:kv pool exhausted"
            b = got[0]
            try:
                # the step holds self._cond for its whole duration, so
                # swapping the carry here is race-free
                m.cache.import_block(b, arrays)
            except Exception as e:
                m.cache.allocator.free([b])
                _tm.inc("kv_xfer_adopt_total", result="geometry",
                        model=model)
                return "rejected:%s" % e
            if not m.prefix.publish(b, digest):
                # lost a publish race — the digest is resident anyway
                m.cache.allocator.free([b])
                _tm.inc("kv_xfer_adopt_total", result="cached",
                        model=model)
                return "cached"
            # drop our reference: the sealed block parks evictable,
            # resident and revivable until a matching submit arrives
            m.cache.allocator.free([b])
            _tm.inc("kv_xfer_adopt_total", result="adopted", model=model)
            return "adopted"

    def forget_adopted(self, model, digests):
        """Abort reconciliation: un-index + truly free still-evictable
        adopted blocks of a request that died on the prefill half.
        Blocks revived in-use by a live sequence are left to their owner.
        Returns how many index entries existed."""
        m = self._models.get(model)
        if m is None or m.prefix is None:
            return 0
        n = 0
        with self._cond:
            for d in digests:
                if m.prefix.forget(d):
                    n += 1
        if n:
            _tm.inc("kv_xfer_forget_total", n, model=model)
        return n

    # -- live session migration (serving/migrate.py drives these) ------------

    def _refuse_export(self, req_id, reason):
        _tm.inc("kv_migrate_refused_total", reason=reason)
        raise ValueError("cannot migrate %s: %s" % (req_id, reason))

    def export_session(self, req_id):
        """Phase 1 of a migration hand-off: detach a live sequence at
        the current iteration boundary and snapshot everything a peer
        needs to continue it — ``(manifest, payloads)``.

        The manifest is the session descriptor (tokens ride as
        ``_prompt_arr``/``_out_arr`` int32 arrays, stripped onto the
        wire frame's payload by the migrator); ``payloads`` is one
        ``(block_index, digest, arrays, is_tail)`` tuple per shippable
        KV block — every fully-fed history block under its chain
        digest, plus the partial tail block sealed at migration time
        under a domain-separated ``tail_digest``.  The sequence stays
        parked in ``_migrating`` (invisible to the scheduler, blocks
        refcounted) until ``commit_migration`` or ``abort_migration``
        decides its fate — at most one replica ever runs it.

        Refusals raise ValueError and leave the engine unperturbed:
        unknown/finished ids, double migration (parked or recently
        committed away), aborted/handoff sequences, sequences still in
        prefill or replay (re-prefill is cheap and a half-fed block has
        no stable digest), and engines without a prefix cache or with
        ``FLAGS_session_migration`` off."""
        with self._cond:
            seq, waiting = None, False
            for s in self._active:
                if s.pending.req_id == req_id:
                    seq = s
                    break
            if seq is None:
                for s in self._waiting:
                    if s.pending.req_id == req_id:
                        seq, waiting = s, True
                        break
            if seq is None:
                if req_id in self._migrating:
                    self._refuse_export(req_id, "already_migrating")
                if req_id in self._migrated:
                    self._refuse_export(req_id, "already_migrated")
                self._refuse_export(req_id, "unknown")
            if seq.aborted:
                self._refuse_export(req_id, "aborted")
            if seq.handoff:
                self._refuse_export(req_id, "handoff")
            if not seq.out or (not waiting and seq.in_prefill):
                self._refuse_export(req_id, "in_prefill")
            m = self._model_of(seq)
            if m.prefix is None or not bool(_flag("session_migration")):
                self._refuse_export(req_id, "disabled")
            bs = m.kv_config.block_size
            # steady decode keeps n_fed == len(prompt ++ out) - 1 (the
            # last emitted token is fed by the NEXT step); a preempted
            # waiting victim resumes at the same position
            pos = len(seq.prompt) + len(seq.out) - 1 if waiting \
                else seq.n_fed
            nfull = pos // bs
            digests = [self._hist_digest_locked(m, seq, j)
                       for j in range(nfull)]
            payloads = []
            if waiting:
                # preempted victim: its blocks were freed, but published
                # history blocks may still sit evictable — revive what
                # survived and ship that; the destination replays the
                # rest (no tail: the partial block never sealed)
                borrowed = m.prefix.match_digests(digests)
                for j, b in enumerate(borrowed):
                    payloads.append((j, digests[j],
                                     m.cache.export_block(b), False))
                if borrowed:
                    m.cache.allocator.free(borrowed)
            else:
                for j in range(nfull):
                    payloads.append((j, digests[j],
                                     m.cache.export_block(seq.blocks[j]),
                                     False))
                if pos > nfull * bs:
                    from .migrate import tail_digest as _tail_digest
                    td = _tail_digest(
                        digests[-1] if digests else None,
                        seq.feed_slice(nfull * bs, pos - nfull * bs))
                    payloads.append((nfull, td,
                                     m.cache.export_block(seq.blocks[nfull]),
                                     True))
            now = time.perf_counter()
            manifest = {
                "req_id": req_id, "model": seq.pending.model,
                "pos": int(pos), "block_size": int(bs),
                "dtype": str(m.kv_config.dtype), "digests": digests,
                "max_new_tokens": int(seq.max_new),
                "eos_id": int(seq.eos_id),
                "tier": seq.pending.tier, "tenant": seq.pending.tenant,
                "deadline_ms": max(
                    round((seq.pending.deadline - now) * 1e3, 3), 1.0),
                "stream": seq.on_token is not None,
                "spec_k": int(m.spec_k),
                "_prompt_arr": np.asarray(seq.prompt, np.int32),
                "_out_arr": np.asarray(seq.out, np.int32),
            }
            if waiting:
                self._waiting.remove(seq)
                _tm.set_gauge("serving_queue_depth", len(self._waiting))
            else:
                self._active.remove(seq)
            self._migrating[req_id] = seq
            _tm.event("session_export", req_id=req_id, pos=int(pos),
                      model=seq.pending.model, blocks=len(payloads),
                      waiting=waiting)
            return manifest, payloads

    def commit_migration(self, req_id, peer):
        """Phase 3 success: the destination acked "resumed" — free the
        parked victim's blocks and finish it with status "migrated";
        reply phases carry ``migrated_to`` so a waiting client follows
        the session to its new home."""
        with self._cond:
            seq = self._migrating.pop(req_id, None)
            if seq is None:
                return False
            self._migrated.append(req_id)
            del self._migrated[:-256]
            self._free_blocks(seq)
            self._finish(seq, InferReply(
                "migrated", error="session migrated to %s" % peer,
                phases={"migrated_to": peer}))
            self._cond.notify_all()
        _tm.event("session_migrated", req_id=req_id, peer=peer)
        return True

    def abort_migration(self, req_id):
        """Phase 3 failure: the push died or the destination refused —
        re-queue the victim at the FRONT for deterministic local
        recompute.  Its emitted tokens are kept (replay never
        re-emits), so the client sees at most a latency blip.  Zero
        drops, and at most one replica ever runs the session."""
        with self._cond:
            seq = self._migrating.pop(req_id, None)
            if seq is None:
                return False
            self._free_blocks(seq)
            seq.reset_for_recompute()
            self._waiting.insert(0, seq)
            _tm.set_gauge("serving_queue_depth", len(self._waiting))
            self._cond.notify_all()
        return True

    # -- decode loop ---------------------------------------------------------

    def start(self):
        if self._running:
            return self
        self._running = True
        self._thread = threading.Thread(target=self._decode_loop,
                                        name="serving-decode", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain_s=5.0):
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(drain_s)
            self._thread = None
        with self._cond:
            leftovers = self._active + self._waiting + \
                list(self._migrating.values())
            self._active, self._waiting = [], []
            self._migrating = {}
        for s in leftovers:
            self._free_blocks(s)
            self._finish(s, InferReply("error", error="engine stopped"))

    @property
    def draining(self):
        return self._draining

    def drain(self, timeout_s=30.0, migrate=None):
        """Graceful retirement (ServingEngine.drain contract): shed new
        arrivals, wait for every waiting AND active sequence to finish.

        ``migrate`` (``SessionMigrator.drain_push()``) turns the wait
        into drain-by-migration: each live mid-decode session is pushed
        to a surviving peer at a batch boundary instead of being waited
        out — a retiring replica with long generations in flight empties
        in O(transfer), not O(remaining tokens).  A session whose push
        fails (no peer, refusal, wire error) is remembered and simply
        waited out the old way; nothing is ever dropped."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        deadline = time.perf_counter() + timeout_s
        failed = set()
        while time.perf_counter() < deadline:
            cand = None
            with self._cond:
                if not self._waiting and not self._active \
                        and not self._migrating:
                    return True
                if migrate is not None:
                    for s in self._active + self._waiting:
                        rid = s.pending.req_id
                        if rid in failed or s.handoff or s.aborted \
                                or not s.out:
                            continue
                        if s in self._active and s.in_prefill:
                            continue
                        cand = (rid, s.pending.model)
                        break
            if cand is not None:
                # the push itself runs OUTSIDE the step lock (it is an
                # RPC); export_session re-checks liveness under the lock
                ok = False
                try:
                    ok = bool(migrate(cand[0], cand[1]))
                except Exception:
                    ok = False
                if not ok:
                    failed.add(cand[0])
                continue
            time.sleep(0.01)
        return False

    def _model_of(self, seq):
        return self._models[seq.pending.model]

    def _free_blocks(self, seq):
        m = self._model_of(seq)
        if seq.blocks:
            m.cache.allocator.free(seq.blocks)
            seq.blocks = []
            seq.table.fill(-1)
        if seq.draft_blocks:
            m.draft_cache.allocator.free(seq.draft_blocks)
            seq.draft_blocks = []
            seq.draft_table.fill(-1)

    def _finish(self, seq, reply):
        r = seq.pending
        if reply.ok or reply.status in ("timeout", "migrated"):
            now = time.perf_counter()
            phases = {"queue_wait_ms": round(
                ((seq.t_admit or now) - r.t_submit) * 1e3, 3),
                "tokens": len(seq.out),
                "prompt_tokens": len(seq.prompt),
                "cached_tokens": seq.cached_tokens,
                "tier": r.tier, "model": r.model}
            if seq.replay_upto > len(seq.prompt):
                # resumed/replayed sessions: tokens that were already
                # emitted (re-fed, never re-emitted); with cached_tokens
                # this yields the re-prefill cost of a migration
                phases["resumed_tokens"] = \
                    seq.replay_upto - len(seq.prompt)
            if seq.t_first is not None:
                phases["ttft_ms"] = round(
                    (seq.t_first - r.t_submit) * 1e3, 3)
            if len(seq.token_times) > 1:
                gaps = [(b - a) * 1e3 for a, b in
                        zip(seq.token_times, seq.token_times[1:])]
                phases["itl_ms_samples"] = [round(g, 3) for g in gaps]
            if reply.phases:
                phases.update(reply.phases)
            reply.phases = phases
        out_tokens = np.asarray(seq.out, np.int32)
        if reply.ok:
            reply.outputs = {"tokens": out_tokens}
        r.complete(reply)
        if reply.ok:
            # fleet-mergeable per-phase histograms: per-tier server_ms
            # (end-to-end on this replica), per-model TTFT and ITL —
            # fleetmon's SLO rules (decode ITL p99) window their bucket
            # deltas; deadline-met replies/tokens are the goodput
            # numerators, raw completions/tokens the denominators
            _tm.observe("server_ms", reply.latency_ms, tier=r.tier)
            if "ttft_ms" in reply.phases:
                _tm.observe("ttft_ms", reply.phases["ttft_ms"],
                            model=r.model)
            for g in reply.phases.get("itl_ms_samples") or ():
                _tm.observe("itl_ms", g, model=r.model)
            met = time.perf_counter() <= r.deadline
            _tm.inc("serving_deadline_met_total" if met
                    else "serving_deadline_missed_total", tier=r.tier)
            if met:
                _tm.inc("serving_deadline_tokens_total", len(seq.out),
                        tier=r.tier)
        if r.qspan is not None:
            r.qspan.end()
            r.qspan = None
        if r.span is not None:
            r.span.annotate(status=reply.status,
                            tokens=len(seq.out)).end()
            r.span = None
        if seq.on_token is not None and not reply.ok:
            # terminal stream chunk so a streaming client unblocks even
            # on shed/timeout/abort/error
            try:
                seq.on_token(r.req_id, len(seq.out), None, True,
                             reply.status)
            except Exception:
                pass

    def _expire_and_admit(self):
        """Under the lock: time out stale waiters, then admit while
        lanes + blocks allow.  Returns the per-model active map."""
        now = time.perf_counter()
        keep = []
        for s in self._waiting:
            if now > s.pending.deadline:
                _tm.inc("serving_timeout_total", model=s.pending.model)
                self._finish(s, InferReply(
                    "timeout", error="deadline expired in queue"))
            else:
                keep.append(s)
        self._waiting[:] = keep
        max_lanes = max(self.buckets)
        while self._waiting and len(self._active) < max_lanes:
            if self.mode == "request" and self._active:
                break  # request-level baseline: no mid-flight joins
            s = self._waiting[0]
            m = self._model_of(s)
            if self._active and self._active[0].pending.model != \
                    s.pending.model:
                break  # one model per step batch
            # reclaimable = free + zero-ref evictable cached blocks: a
            # warm prefix cache never blocks admission (alloc reclaims
            # LRU-first on demand)
            free = m.cache.allocator.reclaimable
            if m.spec_k > 0:
                free = min(free, m.draft_cache.allocator.reclaimable)
            if m.cache.blocks_for_tokens(s.replay_upto) > free:
                break  # head-of-line waits for blocks to free
            self._waiting.pop(0)
            self._admit_seq += 1
            s.admit_seq = self._admit_seq
            s.t_admit = now
            if m.prefix is not None and s.replay_upto > len(s.prompt):
                # resumed (migrated-in) or preempted replay: match the
                # full-history chain instead of the prompt alone
                self._admit_resume_locked(m, s)
            elif m.prefix is not None:
                # longest-prefix match: seed the block table with shared
                # (ref-taken) blocks and jump the feed pointer past the
                # cached tokens — prefill computes only the uncached tail.
                # The match is capped at len(prompt)-1 tokens, so there is
                # always a next token to feed and every write this
                # sequence makes lands in a PRIVATE tail block.
                shared, cached, hashes = m.prefix.match(s.prompt)
                s.hashes = hashes
                s.published = len(shared)
                s.cached_tokens = cached
                if cached:
                    s.blocks = list(shared)
                    s.table[:len(shared)] = shared
                    s.n_fed = cached
                    s.next_tok = s.feed_tok(cached)
                if s.handoff and self.on_block_sealed is not None:
                    # a warm prefill replica still announces prefix-hit
                    # digests: the decode peer may be cold (the sender's
                    # per-peer dedupe skips already-shipped ones)
                    want = s.prefill_upto // m.kv_config.block_size
                    for j in range(min(len(shared), want)):
                        self.on_block_sealed(m, s, j, hashes[j])
            if s.pending.span is not None:
                s.pending.span.annotate(cached_tokens=s.cached_tokens)
            if s.pending.qspan is not None:
                s.pending.qspan.end()
                s.pending.qspan = None
            self._active.append(s)
        _tm.set_gauge("serving_queue_depth", len(self._waiting))
        # per-model pressure gauges ride the 1s __metrics__ republish:
        # the role-aware autoscaler scales decode replicas on live
        # KV-pool occupancy, routers on the prefix hit rate
        for name, m in self._models.items():
            alloc = m.cache.allocator
            cap = float(alloc.capacity) or 1.0
            _tm.set_gauge("kv_pool_occupancy", alloc.in_use / cap,
                          model=name)
            _tm.set_gauge("kv_pool_reclaimable_ratio",
                          alloc.reclaimable / cap, model=name)
            if m.prefix is not None:
                _tm.set_gauge("prefix_cache_hit_rate", m.prefix.hit_rate(),
                              model=name)

    def _ensure_block(self, seq):
        """Single-token path: cover seq's next write position."""
        return self._ensure_capacity(seq, seq.n_fed + 1)

    def _ensure_capacity(self, seq, upto, draft_upto=0):
        """Grow seq's block table(s) to cover ``upto`` tokens (and the
        draft's to ``draft_upto`` when speculating) with all-or-nothing
        multi-block allocations; preempt the youngest OTHER active
        sequence on pool exhaustion.  False means seq itself was
        defensively completed (should not happen — submit() capped every
        sequence's total need at pool capacity)."""
        m = self._model_of(seq)
        while True:
            ok = m.cache.ensure_table(seq.table, seq.blocks, upto)
            if ok and draft_upto > 0:
                ok = m.draft_cache.ensure_table(
                    seq.draft_table, seq.draft_blocks, draft_upto)
            if ok:
                return True
            victims = [s for s in self._active if s is not seq]
            if not victims:
                self._active.remove(seq)
                self._free_blocks(seq)
                self._finish(seq, InferReply(
                    "error", error="KV pool exhausted with no victim"))
                return False
            v = max(victims, key=lambda s: s.admit_seq)
            self._active.remove(v)
            self._free_blocks(v)
            v.reset_for_recompute()
            self._waiting.insert(0, v)
            if v.out:
                # pressure-trigger migration candidate: reported through
                # on_preempt at the next batch boundary (lock released)
                self._preempted.append((v.pending.req_id,
                                        v.pending.model))
            _tm.inc("kv_block_evictions_total",
                    model=v.pending.model)
            _tm.event("decode_preempt", victim=v.pending.req_id,
                      for_req=seq.pending.req_id)

    def _publish_prefix_locked(self, m, s):
        """Publish every newly-completed FULL prompt block of ``s`` into
        the prefix index (first-publisher-wins; a losing duplicate stays
        private and frees normally).  Only blocks whose every position
        holds a prompt token are eligible — decode-written and partially
        fed blocks can never be published, which is what makes a
        mid-prefill abort safe by construction."""
        if m.prefix is None or s.hashes is None:
            return
        bs = m.kv_config.block_size
        done = min(s.n_fed, len(s.prompt)) // bs
        while s.published < min(done, len(s.hashes)):
            j = s.published
            m.prefix.publish(s.blocks[j], s.hashes[j])
            s.published = j + 1
            if s.handoff and self.on_block_sealed is not None \
                    and j < s.prefill_upto // bs:
                self.on_block_sealed(m, s, j, s.hashes[j])

    def _hist_digest_locked(self, m, s, j):
        """``j``-th full-block digest of the prompt ++ out hash chain
        (memoized in ``s.hist_hashes``; the prompt-only prefix of the
        chain is identical to ``s.hashes``, so it is reused)."""
        bs = m.kv_config.block_size
        while len(s.hist_hashes) <= j:
            i = len(s.hist_hashes)
            if s.hashes is not None and i < len(s.hashes):
                s.hist_hashes.append(s.hashes[i])
                continue
            prev = s.hist_hashes[i - 1] if i else None
            s.hist_hashes.append(m.prefix.extend_chain(
                prev, s.feed_slice(i * bs, bs)))
        return s.hist_hashes[j]

    def _publish_history_locked(self, m, s):
        """Publish every newly-completed history block — full blocks
        whose tokens extend past the prompt — under its prompt ++ out
        chain digest (FLAGS_session_migration).  Eligibility mirrors
        ``_publish_prefix_locked``: a block publishes only once every
        one of its positions is fed, so its KV content is final (later
        writes land in later blocks) and any future matcher replays the
        exact tokens that produced it.  This is what makes crash resume
        O(tokens since the last sealed block): a replica that ran the
        same prompt before holds the whole history chain evictable."""
        if m.prefix is None or s.handoff \
                or not bool(_flag("session_migration")):
            return
        bs = m.kv_config.block_size
        first = len(s.prompt) // bs    # prompt-only blocks: see above
        done = s.n_fed // bs
        if s.hist_published < first:
            s.hist_published = first
        while s.hist_published < done:
            j = s.hist_published
            m.prefix.publish(s.blocks[j],
                             self._hist_digest_locked(m, s, j))
            s.hist_published = j + 1

    def _admit_resume_locked(self, m, s):
        """Resume-path admission (``replay_upto > len(prompt)``): match
        the full-history chain — prompt ++ already-emitted tokens —
        instead of the prompt alone, then adopt the migrated tail
        partial block when every full block below it matched.  Serves
        both a migrated-in session and a preempted local replay (whose
        own published history revives here).  Anything unmatched is
        simply replayed: outputs are bitwise identical either way."""
        bs = m.kv_config.block_size
        pos = s.replay_upto - 1      # the last emitted token is re-fed
        nfull = pos // bs
        s.hashes = m.prefix.chain(s.prompt)
        digests = [self._hist_digest_locked(m, s, j)
                   for j in range(nfull)]
        blocks = m.prefix.match_digests(digests)
        if blocks:
            s.blocks = list(blocks)
            s.table[:len(blocks)] = blocks
            s.n_fed = len(blocks) * bs
        s.published = min(len(blocks), len(s.hashes))
        s.hist_published = len(blocks)
        tail, s.resume_tail = s.resume_tail, None
        if tail is not None and len(blocks) == nfull \
                and nfull * bs < pos:
            from .migrate import tail_digest as _tail_digest
            want = _tail_digest(digests[-1] if digests else None,
                                s.feed_slice(nfull * bs, pos - nfull * bs))
            if tail.get("digest") != want \
                    or int(tail.get("valid", -1)) != pos - nfull * bs:
                # a stale/foreign tail is dropped, not trusted: the
                # replay recomputes it (< 1 block of work)
                _tm.inc("kv_migrate_refused_total",
                        reason="tail_mismatch")
            else:
                got = m.cache.allocator.alloc(1)
                if got is not None:
                    b = got[0]
                    try:
                        m.cache.import_block(b, tail["arrays"])
                    except Exception:
                        m.cache.allocator.free([b])
                    else:
                        # PRIVATE tail block owned by the resumed
                        # sequence — never indexed (partial blocks must
                        # not prefix-match)
                        s.blocks.append(b)
                        s.table[nfull] = b
                        s.n_fed = pos
        s.cached_tokens = s.n_fed
        s.next_tok = s.feed_tok(s.n_fed)

    def _prefill_limit(self, s):
        """Last position this replica feeds for ``s``: the known
        history (prompt, plus replayed tokens for a resume), or the
        handoff boundary for a prefill-role sequence."""
        return s.prefill_upto if s.handoff else s.replay_upto

    def _sweep_handoff_locked(self):
        """Complete handoff sequences whose feed pointer reached the
        boundary (lock held, before the step builds lanes): fire
        ``on_handoff`` while the blocks are still owned — the hook
        snapshots nothing, the sealed blocks were already streamed — then
        free and finish with status "handoff" (the prefill replica's
        terminal state; the decode half owns the client-visible reply)."""
        for s in list(self._active):
            if not s.handoff or s.n_fed < s.prefill_upto:
                continue
            m = self._model_of(s)
            self._active.remove(s)
            if self.on_handoff is not None:
                try:
                    self.on_handoff(m, s)
                except Exception:
                    pass
            self._free_blocks(s)
            self._finish(s, InferReply("handoff"))
            _tm.inc("serving_handoff_total", model=m.name)

    def _plan_lanes_locked(self, chunk):
        """Token-budget prefill scheduling -> (participants, span_caps).

        With ``FLAGS_decode_prefill_token_budget`` unset every active
        lane participates (legacy order).  With a budget B, decode lanes
        ALWAYS run — bounding decode ITL under a prompt burst is the
        point — and prefilling lanes join round-robin until their summed
        prefill spans (up to ``chunk`` tokens each) reach B; the rest sit
        out this iteration and move to the front of the rotation next
        time.  ``span_caps`` maps id(seq) -> this iteration's prefill
        span cap (spec mode feeds multi-token chunks; non-spec feeds one
        token, so the cap only gates participation).  Pure scheduling:
        participants still pad to a configured lane bucket, so no new
        shape is ever compiled."""
        max_lanes = max(self.buckets)
        budget = int(_flag("decode_prefill_token_budget") or 0)
        if budget <= 0:
            return self._active[:max_lanes], {}
        decode = [s for s in self._active if not s.in_prefill]
        prefill = [s for s in self._active if s.in_prefill]
        if prefill:
            r = self._rr_prefill % len(prefill)
            prefill = prefill[r:] + prefill[:r]
        chosen, caps, left = [], {}, budget
        for s in prefill:
            if left <= 0 or len(decode) + len(chosen) >= max_lanes:
                break
            span = min(chunk, self._prefill_limit(s) - s.n_fed, left)
            caps[id(s)] = span
            left -= span
            chosen.append(s)
        self._rr_prefill += max(len(chosen), 1)
        return (decode + chosen)[:max_lanes], caps

    def _bucket_for(self, lanes):
        for b in self.buckets:
            if lanes <= b:
                return b
        return max(self.buckets)

    def _decode_loop(self):
        while True:
            # named fault point OUTSIDE the lock: a "delay" spec slows
            # every decode iteration (slow-replica chaos — keeps
            # sessions alive across a drain/kill window in CI) without
            # holding submitters on the cond during the sleep
            maybe_fail("serving.decode_step")
            with self._cond:
                if not self._running:
                    return
                self._expire_and_admit()
                if not self._active:
                    self._cond.wait(0.05)
                    continue
                step_ok = self._decode_step_locked()
                preempted, self._preempted = self._preempted, []
            if preempted and self.on_preempt is not None:
                # pressure-trigger migration hook (CC105: fired with the
                # lock released; the victims are already back in the
                # waiting queue with their emitted tokens intact)
                try:
                    self.on_preempt(preempted)
                except Exception:
                    pass
            if self.on_batch_boundary is not None:
                try:
                    self.on_batch_boundary()
                except Exception:
                    pass
            if not step_ok:
                time.sleep(0.001)

    def _decode_step_locked(self):
        """One token for every active lane (call with self._cond held).

        NOTE: the step executes under the lock — sequences can only
        join/leave at iteration boundaries, which is exactly the
        continuous-batching contract.  submit()/abort() block for at
        most one step (milliseconds at serving batch sizes), and in
        exchange the active set and block tables need no second lock."""
        m = self._model_of(self._active[0])
        # drop client-aborted + deadline-expired actives first, freeing
        # their blocks before this step's allocations
        now = time.perf_counter()
        for s in list(self._active):
            if s.aborted:
                self._active.remove(s)
                self._free_blocks(s)
                self._finish(s, InferReply("aborted",
                                           error="aborted by client"))
            elif now > s.pending.deadline:
                self._active.remove(s)
                self._free_blocks(s)
                _tm.inc("serving_timeout_total", model=s.pending.model)
                self._finish(s, InferReply(
                    "timeout", error="deadline expired mid-decode"))
        # complete prefill-role sequences whose boundary was reached (by
        # the previous step, or at admission via a warm prefix match)
        self._sweep_handoff_locked()
        if not self._active:
            return True
        if m.spec_k > 0:
            return self._spec_step_locked(m)
        # token-budget prefill scheduling: decode lanes always run;
        # prefilling lanes beyond the budget sit this iteration out
        participants, _caps = self._plan_lanes_locked(1)
        for s in participants:
            if s in self._active and not self._ensure_block(s):
                pass  # defensively completed inside _ensure_block
        lanes = [s for s in participants if s in self._active]
        if not lanes:
            return True
        bucket = self._bucket_for(len(lanes))
        tok = np.zeros(bucket, np.int32)
        pos = np.zeros(bucket, np.int32)
        tables = np.full((bucket, m.maxb), -1, np.int32)
        lens = np.zeros(bucket, np.int32)
        for i, s in enumerate(lanes):
            tok[i] = s.next_tok
            pos[i] = s.n_fed
            tables[i] = s.table
            lens[i] = s.n_fed + 1    # token valid AFTER this step's write
        self._step_no += 1
        sspan = _tr.start_span(
            "serving.decode_step", model=m.name, bucket=bucket,
            lanes=len(lanes), step=self._step_no)
        for s in lanes:
            sspan.link(s.pending.span.context
                       if s.pending.span is not None else None)
        _tr.note("decode_step", model=m.name, step=self._step_no,
                 req_ids=[s.pending.req_id for s in lanes])
        self.in_batch = True
        t0 = time.perf_counter()
        try:
            with _tr.activate(sspan):
                # threadlint: waive CC102 continuous-batching contract: the device step runs under _cond so lane state is frozen for the whole step (see _decode_step_locked docstring); submitters park on the cond, never spin
                carry, nxt, _logits = m.stepfn(
                    *self._step_args(m, bucket, tok, pos, tables, lens))
            m.cache.replace_carry(carry)
            nxt = np.asarray(nxt)
        except Exception as e:
            for s in lanes:
                self._active.remove(s)
                self._free_blocks(s)
                self._finish(s, InferReply("error", error=str(e)))
            _tm.inc("serving_batch_errors_total", model=m.name)
            sspan.annotate(error=str(e)[:200]).end()
            self.in_batch = False
            return False
        self.in_batch = False
        ms = (time.perf_counter() - t0) * 1e3
        m.step_ms = ms if m.step_ms <= 0 else 0.8 * m.step_ms + 0.2 * ms
        t_tok = time.perf_counter()
        n_generated = 0
        for i, s in enumerate(lanes):
            s.n_fed += 1
            # seal + publish any prompt block this write completed (the
            # boundary-crossing write completes the final full block),
            # then any completed history block (session migration)
            self._publish_prefix_locked(m, s)
            self._publish_history_locked(m, s)
            if s.in_prefill:
                s.next_tok = s.feed_tok(s.n_fed)
                continue
            token = int(nxt[i])
            s.next_tok = token
            s.out.append(token)
            s.token_times.append(t_tok)
            if s.t_first is None:
                s.t_first = t_tok
            n_generated += 1
            done = (len(s.out) >= s.max_new or token == s.eos_id)
            if s.on_token is not None:
                try:
                    s.on_token(s.pending.req_id, len(s.out) - 1, token,
                               done, "ok")
                except Exception:
                    pass
            if done:
                self._active.remove(s)
                self._free_blocks(s)   # same-step free: next admission
                self._finish(s, InferReply("ok"))
                _tm.observe("serving_latency_ms",
                            s.pending.reply.latency_ms, model=m.name)
        if n_generated:
            _tm.inc("serving_tokens_generated_total", n_generated,
                    model=m.name)
        _tm.inc("serving_decode_steps_total", model=m.name)
        _tm.observe("decode_batch_occupancy",
                    len(lanes) / float(bucket), model=m.name)
        sspan.annotate(generated=n_generated, ms=round(ms, 3)).end()
        return True

    def _spec_step_locked(self, m):
        """One speculative iteration (lock held): the draft decoder
        proposes k tokens per generating lane through its own paged
        pool, ONE bucketed multi-token target step verifies all k+1
        positions, the longest draft prefix matching the target's greedy
        argmax chain is accepted, and over-reserved blocks roll back to
        both free lists in the same iteration.  Prefill lanes ride the
        same verify step as a chunked prefill (up to k+1 prompt tokens
        per iteration, auto-accepted, mirrored into the draft cache).
        Greedy accept keeps the emitted stream bitwise equal to the
        non-speculative engine; draft quality only moves throughput.

        Verify/ingest lane layout is junk-first: a lane with span < k+1
        valid tokens pads the LEADING columns with context_len-0 writes
        aimed at the first valid position, which the first real column
        then overwrites before anything attends — so short lanes never
        touch positions past their reservation and junk never survives
        into attended history."""
        k = m.spec_k
        width = k + 1
        # token-budget prefill scheduling: caps[id(s)] trims a prefill
        # lane's chunk span when the budget runs low this iteration
        participants, caps = self._plan_lanes_locked(width)
        plans = {}
        for s in participants:
            if s not in self._active:
                continue   # preempted by an earlier lane's allocation
            p = s.n_fed
            if s.in_prefill:
                span = caps.get(id(s),
                                min(width, self._prefill_limit(s) - p))
                spec = False
                # the prompt chunk mirrors into the draft TAIL-ONLY: with
                # a cached prefix p starts past it, so draft positions
                # below p stay zero — that can only lower acceptance,
                # never correctness (verify guards every emitted token)
                draft_upto = p + span
            else:
                span = min(width, s.max_new - len(s.out))
                spec = span > 1         # last token needs no proposals
                # rollout writes up to p+k-1 (position-clamped to the
                # sequence end); a full accept ingests d_k at p+k
                draft_upto = min(p + k + 1, s.total) if spec else 0
            if not self._ensure_capacity(s, p + span, draft_upto):
                continue   # defensively completed
            plans[id(s)] = (span, spec)
        lanes = [s for s in participants
                 if s in self._active and id(s) in plans]
        if not lanes:
            return True
        bucket = self._bucket_for(len(lanes))
        tok = np.zeros((bucket, width), np.int32)
        pos = np.zeros((bucket, width), np.int32)
        lens = np.zeros((bucket, width), np.int32)
        tables = np.full((bucket, m.maxb), -1, np.int32)
        rtok = np.zeros(bucket, np.int32)
        rpos = np.zeros(bucket, np.int32)
        rlens = np.zeros(bucket, np.int32)
        rmax = np.zeros(bucket, np.int32)
        rtables = np.full((bucket, m.maxb), -1, np.int32)
        n_spec = 0
        for i, s in enumerate(lanes):
            span, spec = plans[id(s)]
            p = s.n_fed
            pad = width - span
            tables[i] = s.table
            pos[i, :pad] = p
            feed = s.feed_slice(p, span) if s.in_prefill else [s.next_tok]
            for j in range(span):
                pos[i, pad + j] = p + j
                lens[i, pad + j] = p + j + 1
            for j, t in enumerate(feed):
                tok[i, pad + j] = t
            if spec:
                n_spec += 1
                rtok[i] = s.next_tok
                rpos[i] = p
                rlens[i] = p + 1
                rmax[i] = s.total - 1
                rtables[i] = s.draft_table
        self._step_no += 1
        sspan = _tr.start_span(
            "serving.decode_step", model=m.name, bucket=bucket,
            lanes=len(lanes), step=self._step_no, speculative=True, k=k)
        for s in lanes:
            sspan.link(s.pending.span.context
                       if s.pending.span is not None else None)
        req_ids = [s.pending.req_id for s in lanes]
        self.in_batch = True
        t0 = time.perf_counter()
        props = None
        try:
            with _tr.activate(sspan):
                if n_spec:
                    _tr.note("decode_step", model=m.name,
                             step=self._step_no, phase="draft",
                             req_ids=req_ids)
                    with _tr.span("serving.draft", lanes=n_spec, k=k):
                        # threadlint: waive CC102 draft rollout runs under _cond by the same frozen-lane contract as stepfn in _decode_step_locked
                        dcarry, props = m.rolloutfn(
                            m.draft_cache.carry(), m.draft_params,
                            rtok, rpos, rtables, rlens, rmax)
                    m.draft_cache.replace_carry(dcarry)
                    props = np.asarray(props)
                    for i, s in enumerate(lanes):
                        span, spec = plans[id(s)]
                        if spec:
                            for j in range(span - 1):
                                tok[i, width - span + 1 + j] = props[i, j]
                _tr.note("decode_step", model=m.name, step=self._step_no,
                         phase="verify", req_ids=req_ids)
                with _tr.span("serving.verify", lanes=len(lanes),
                              width=width):
                    # threadlint: waive CC102 target-model verify runs under _cond by the same frozen-lane contract as stepfn in _decode_step_locked
                    carry, nxt, _logits = m.verifyfn(
                        m.cache.carry(), m.params, tok, pos, tables, lens)
                m.cache.replace_carry(carry)
                nxt = np.asarray(nxt)
        except Exception as e:
            for s in lanes:
                self._active.remove(s)
                self._free_blocks(s)
                self._finish(s, InferReply("error", error=str(e)))
            _tm.inc("serving_batch_errors_total", model=m.name)
            sspan.annotate(error=str(e)[:200]).end()
            self.in_batch = False
            return False
        self.in_batch = False
        ms = (time.perf_counter() - t0) * 1e3
        m.step_ms = ms if m.step_ms <= 0 else 0.8 * m.step_ms + 0.2 * ms
        t_tok = time.perf_counter()
        n_generated = 0
        k_proposed = 0
        k_accepted = 0
        ingest = []    # (seq, start_pos, tokens) draft catch-up writes
        for i, s in enumerate(lanes):
            span, spec = plans[id(s)]
            p = s.n_fed
            pad = width - span
            accepted = 0
            if s.in_prefill:
                s.n_fed += span
                self._publish_prefix_locked(m, s)
                self._publish_history_locked(m, s)
                ingest.append((s, p, s.feed_slice(p, span)))
                if s.in_prefill:
                    s.next_tok = s.feed_tok(s.n_fed)
                    continue
                # chunk crossed the prompt boundary: its last column's
                # argmax is the first generated token
                emitted = [int(nxt[i, pad + span - 1])]
            else:
                # accept-longest-prefix: column j's argmax continues the
                # chain only while proposal j matched the previous argmax
                emitted = [int(nxt[i, pad])]
                while accepted < span - 1 and \
                        int(props[i, accepted]) == emitted[-1]:
                    emitted.append(int(nxt[i, pad + accepted + 1]))
                    accepted += 1
                if spec:
                    k_proposed += span - 1
                    k_accepted += accepted
                    _tm.observe("spec_acceptance",
                                accepted / float(span - 1), model=m.name)
                s.n_fed += len(emitted)
            done = False
            for t in emitted:
                s.out.append(t)
                s.token_times.append(t_tok)
                if s.t_first is None:
                    s.t_first = t_tok
                n_generated += 1
                done = (len(s.out) >= s.max_new or t == s.eos_id)
                if s.on_token is not None:
                    try:
                        s.on_token(s.pending.req_id, len(s.out) - 1, t,
                                   done, "ok")
                    except Exception:
                        pass
                if done:
                    break
            # history publication must follow the appends: a multi-token
            # accept advances n_fed past tokens that only exist in
            # ``emitted`` until this point, and the chain digest replays
            # them from prompt ++ out
            self._publish_history_locked(m, s)
            if done:
                self._active.remove(s)
                self._free_blocks(s)   # same-step free, both pools
                self._finish(s, InferReply("ok"))
                _tm.observe("serving_latency_ms",
                            s.pending.reply.latency_ms, model=m.name)
                continue
            s.next_tok = emitted[-1]
            if accepted == k:
                # full accept: the rollout never wrote position p+k; its
                # token is d_k (== the target's g_k), caught up below
                ingest.append((s, p + k, [int(props[i, k - 1])]))
        # free rollback: every block past the accepted frontier returns
        # to its pool in the SAME iteration (context_lens truncation next
        # step masks the stale writes)
        rolled = 0
        for s in lanes:
            if s not in self._active:
                continue
            rolled += m.cache.trim_table(s.table, s.blocks, s.n_fed)
            rolled += m.draft_cache.trim_table(
                s.draft_table, s.draft_blocks, s.n_fed)
        if rolled:
            _tm.inc("spec_blocks_rolled_back_total", rolled, model=m.name)
        ingest = [(s, q, t) for (s, q, t) in ingest if s in self._active]
        if ingest:
            itok = np.zeros((bucket, width), np.int32)
            ipos = np.zeros((bucket, width), np.int32)
            ilens = np.zeros((bucket, width), np.int32)
            itables = np.full((bucket, m.maxb), -1, np.int32)
            for r, (s, q, toks) in enumerate(ingest):
                ipad = width - len(toks)
                itables[r] = s.draft_table
                ipos[r, :ipad] = q
                for j, t in enumerate(toks):
                    ipos[r, ipad + j] = q + j
                    ilens[r, ipad + j] = q + j + 1
                    itok[r, ipad + j] = t
            try:
                with _tr.activate(sspan):
                    _tr.note("decode_step", model=m.name,
                             step=self._step_no, phase="draft",
                             ingest=len(ingest))
                    with _tr.span("serving.draft_ingest",
                                  lanes=len(ingest)):
                        # threadlint: waive CC102 draft-cache ingest runs under _cond by the same frozen-lane contract as stepfn in _decode_step_locked
                        dcarry, _nx, _lg = m.ingestfn(
                            m.draft_cache.carry(), m.draft_params,
                            itok, ipos, itables, ilens)
                m.draft_cache.replace_carry(dcarry)
            except Exception:
                # a stale draft cache only costs acceptance, never
                # correctness — the verify step guards every token
                _tm.inc("spec_ingest_errors_total", model=m.name)
        if n_spec:
            _tm.inc("spec_tokens_proposed_total", k_proposed,
                    model=m.name)
            _tm.inc("spec_tokens_accepted_total", k_accepted,
                    model=m.name)
        if n_generated:
            _tm.inc("serving_tokens_generated_total", n_generated,
                    model=m.name)
        _tm.inc("serving_decode_steps_total", model=m.name)
        _tm.observe("decode_batch_occupancy",
                    len(lanes) / float(bucket), model=m.name)
        sspan.annotate(generated=n_generated, ms=round(ms, 3),
                       k_proposed=k_proposed, k_accepted=k_accepted).end()
        return True
