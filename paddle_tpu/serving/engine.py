"""Continuous-batching serving engine over AnalysisPredictor.

The reference inference stack answers one request at a time
(AnalysisPredictor::Run); under "heavy traffic from millions of users"
(ROADMAP north star) that wastes the accelerator on batch-1 launches and
recompiles on every new request shape.  The engine closes both gaps:

- **admission queue with deadline-aware backpressure**: ``submit`` sheds
  a request (status "shed" + retry_after_ms) instead of queueing it when
  the projected wait — queue depth x the model's EWMA batch service time —
  already exceeds the request's deadline budget, or when the queue is at
  ``FLAGS_serving_max_queue``.  Queued requests whose deadline expires
  before dispatch complete with status "timeout".
- **shape-bucketed batching**: the dispatcher coalesces queued same-model
  requests for up to ``FLAGS_serving_batch_window_ms`` and pads the
  concatenated batch to the smallest configured bucket that fits
  (``FLAGS_serving_buckets``), so every dispatch hits one of a FIXED set
  of executable shapes.
- **AOT bucket prewarm**: ``prewarm()`` runs ``Executor.warmup`` for every
  (model, bucket) against ``FLAGS_compile_cache_dir`` — all executables
  exist before the first request, and the prewarm manifest records where
  each came from (memory/disk/compiled).  After that, a request can only
  ever hit the in-memory executable cache: zero runtime compiles, provable
  from the ``executor_cache_miss_total`` / ``compile_cache_*`` counters.

Telemetry: ``serving_queue_depth`` gauge, ``serving_batch_fill`` +
``serving_latency_ms`` histograms, ``serving_qps`` gauge (5 s window),
``serving_requests_total{model,tenant}``, ``serving_shed_total{reason}``,
``serving_timeout_total``, ``serving_batches_total{model,bucket}``.
"""

import threading
import time
import uuid

import numpy as np

from ..core import telemetry as _tm
from ..core import tracing as _tr
from ..core.executor import scope_guard

__all__ = ["ServingEngine", "InferReply", "parse_buckets"]

_QPS_WINDOW_S = 5.0


def _flag(name):
    from .. import flags

    return flags.flag(name)


def parse_buckets(spec=None):
    """\"1,4,16\" (or an int sequence) -> sorted unique bucket tuple."""
    if spec is None:
        spec = _flag("serving_buckets")
    if isinstance(spec, str):
        sizes = [int(s) for s in spec.replace(" ", "").split(",") if s]
    else:
        sizes = [int(s) for s in spec]
    if not sizes or any(s <= 0 for s in sizes):
        raise ValueError("serving buckets must be positive ints: %r" % spec)
    return tuple(sorted(set(sizes)))


class InferReply:
    """Terminal state of one request: status ok|shed|timeout|error."""

    __slots__ = ("status", "outputs", "error", "retry_after_ms",
                 "latency_ms", "phases")

    def __init__(self, status, outputs=None, error=None,
                 retry_after_ms=0.0, latency_ms=0.0, phases=None):
        self.status = status
        self.outputs = outputs or {}
        self.error = error
        self.retry_after_ms = float(retry_after_ms)
        self.latency_ms = float(latency_ms)
        # SLO phase attribution (always on, tracing-independent):
        # queue_wait_ms / execute_ms / bucket / rows — the client adds
        # wire_ms as its end-to-end latency minus our latency_ms
        self.phases = phases or {}

    @property
    def ok(self):
        return self.status == "ok"

    def to_meta(self):
        meta = {"status": self.status, "error": self.error,
                "retry_after_ms": round(self.retry_after_ms, 3),
                "latency_ms": round(self.latency_ms, 3),
                "outputs": list(self.outputs)}
        if self.phases:
            meta["phases"] = self.phases
        return meta


class _Pending:
    """Handle returned by submit(): wait() blocks for the InferReply."""

    __slots__ = ("model", "tenant", "feeds", "rows", "deadline",
                 "t_submit", "t_dispatch", "req_id", "callback", "_done",
                 "reply", "traceparent", "span", "qspan")

    def __init__(self, model, tenant, feeds, rows, deadline_ms, req_id,
                 callback, traceparent=None):
        self.model = model
        self.tenant = tenant
        self.feeds = feeds
        self.rows = rows
        self.t_submit = time.perf_counter()
        self.t_dispatch = None
        self.deadline = self.t_submit + deadline_ms / 1e3
        self.req_id = req_id
        self.callback = callback
        self._done = threading.Event()
        self.reply = None
        self.traceparent = traceparent  # wire context echoed in the reply
        self.span = None    # serving.request (submit -> complete)
        self.qspan = None   # serving.queue_wait child (submit -> dispatch)

    def complete(self, reply):
        reply.latency_ms = (time.perf_counter() - self.t_submit) * 1e3
        self.reply = reply
        self._done.set()
        if self.callback is not None:
            try:
                self.callback(self)
            except Exception:
                pass

    def wait(self, timeout=None):
        self._done.wait(timeout)
        return self.reply


class _ModelEntry:
    __slots__ = ("name", "predictor", "feed_specs", "svc_ms")

    def __init__(self, name, predictor):
        self.name = name
        self.predictor = predictor
        block = predictor.program().global_block()
        self.feed_specs = {}
        for fname in predictor.get_input_names():
            v = block._find_var_recursive(fname)
            shape = tuple(v.shape)
            if shape and shape[0] in (-1, 0):
                shape = shape[1:]
            self.feed_specs[fname] = (shape, v.dtype)
        # EWMA of one dispatched batch's wall time; seeds pessimistic so
        # the first admission estimates err toward accepting
        self.svc_ms = 0.0


class ServingEngine:
    def __init__(self, buckets=None, max_queue=None, deadline_ms=None,
                 batch_window_ms=None):
        self.buckets = parse_buckets(buckets)
        self.max_queue = int(max_queue if max_queue is not None
                             else _flag("serving_max_queue"))
        self.default_deadline_ms = float(
            deadline_ms if deadline_ms is not None
            else _flag("serving_deadline_ms"))
        self.batch_window_ms = float(
            batch_window_ms if batch_window_ms is not None
            else _flag("serving_batch_window_ms"))
        self._models = {}
        self._queue = []          # FIFO of _Pending
        self._cond = threading.Condition()
        self._running = False
        self._thread = None
        self.in_batch = False
        # fleet hook: called (outside the queue lock) after every
        # dispatched batch — the fleet coordinator publishes membership
        # changes here, so a shrink lands at a batch boundary
        self.on_batch_boundary = None
        self._done_times = []     # completion stamps for the QPS gauge

    # -- registry ------------------------------------------------------------

    def add_model(self, name, predictor_or_dir):
        """Register a model under `name`: an AnalysisPredictor, or a
        save_inference_model dir to load one from."""
        from ..inference import AnalysisConfig, AnalysisPredictor

        if isinstance(predictor_or_dir, str):
            cfg = AnalysisConfig(predictor_or_dir)
            cfg.disable_gpu()
            cache = _flag("compile_cache_dir")
            if cache:
                cfg.set_optim_cache_dir(cache)
            predictor_or_dir = AnalysisPredictor(cfg)
        self._models[name] = _ModelEntry(name, predictor_or_dir)
        return self._models[name].predictor

    def models(self):
        return list(self._models)

    def spec(self, model):
        """JSON-able feed/fetch signature for `model` (the __spec__ RPC)."""
        from ..framework import dtype_to_np

        e = self._models[model]
        return {
            "model": model,
            "buckets": list(self.buckets),
            "feeds": {n: {"shape": list(shape),
                          "dtype": np.dtype(dtype_to_np(dt)).str}
                      for n, (shape, dt) in e.feed_specs.items()},
            "outputs": e.predictor.get_output_names(),
        }

    # -- AOT bucket prewarm --------------------------------------------------

    def prewarm(self):
        """Executor.warmup every (model, bucket); returns the manifest
        {model: {bucket: {"source", "compile_ms"}}}.  With
        FLAGS_compile_cache_dir set, compiled buckets land in the tier-B
        store and later replicas restore from disk."""
        manifest = {}
        for name, e in self._models.items():
            pred = e.predictor
            per = {}
            for b in self.buckets:
                specs = {n: ((b,) + tuple(shape), None)
                         for n, (shape, _dt) in e.feed_specs.items()}
                got = pred._exe.warmup(
                    pred.program(), feed_specs=specs,
                    fetch_list=pred._fetch_vars, scope=pred._scope)
                per[b] = {"source": got["source"],
                          "compile_ms": round(got["compile_ms"], 3)}
                _tm.inc("serving_prewarm_total", model=name,
                        source=got["source"])
                _tm.event("serving_prewarm", model=name, bucket=b,
                          source=got["source"],
                          ms=round(got["compile_ms"], 3))
            manifest[name] = per
        return manifest

    # -- admission -----------------------------------------------------------

    def _projected_wait_ms(self, entry, depth):
        """Queue-drain estimate: batches ahead x EWMA batch service time."""
        if entry.svc_ms <= 0.0:
            return 0.0
        batches_ahead = depth // max(self.buckets) + 1
        return batches_ahead * entry.svc_ms

    def submit(self, model, feeds, tenant="default", deadline_ms=None,
               callback=None, req_id=None, traceparent=None):
        """Enqueue one request; returns a _Pending (wait() for the reply).
        Shed/timeout/error requests complete immediately."""
        deadline_ms = float(deadline_ms or self.default_deadline_ms)
        req = _Pending(model, tenant, feeds, 0, deadline_ms,
                       req_id or uuid.uuid4().hex, callback,
                       traceparent=traceparent)
        entry = self._models.get(model)
        if entry is None or not self._running:
            req.complete(InferReply(
                "error", error="unknown model %r" % model if entry is None
                else "engine not running"))
            return req
        try:
            req.feeds, req.rows = self._normalize(entry, feeds)
        except Exception as e:
            req.complete(InferReply("error", error=str(e)))
            return req
        _tm.inc("serving_requests_total", model=model, tenant=tenant)
        with self._cond:
            depth = len(self._queue)
            if depth >= self.max_queue:
                wait_ms = self._projected_wait_ms(entry, depth)
                _tm.inc("serving_shed_total", reason="queue_full")
                req.complete(InferReply(
                    "shed", error="queue full (%d)" % depth,
                    retry_after_ms=max(wait_ms, entry.svc_ms, 1.0)))
                return req
            wait_ms = self._projected_wait_ms(entry, depth)
            if wait_ms > deadline_ms:
                _tm.inc("serving_shed_total", reason="deadline_budget")
                req.complete(InferReply(
                    "shed",
                    error="projected wait %.0fms exceeds deadline %.0fms"
                          % (wait_ms, deadline_ms),
                    retry_after_ms=wait_ms - deadline_ms + entry.svc_ms))
                return req
            # admitted: open the request span (parents under the server's
            # admission span when submit runs inside it) and its
            # queue-wait child, ended at dispatch or deadline expiry
            req.span = _tr.start_span(
                "serving.request", model=model, tenant=tenant,
                rows=req.rows, req_id=req.req_id)
            req.qspan = _tr.start_span("serving.queue_wait",
                                       parent=req.span, depth=depth)
            self._queue.append(req)
            _tm.set_gauge("serving_queue_depth", len(self._queue))
            self._cond.notify_all()
        return req

    def infer(self, model, feeds, tenant="default", deadline_ms=None):
        """Synchronous submit + wait."""
        req = self.submit(model, feeds, tenant=tenant,
                          deadline_ms=deadline_ms)
        deadline_ms = float(deadline_ms or self.default_deadline_ms)
        reply = req.wait(timeout=deadline_ms / 1e3 + 30.0)
        return reply if reply is not None else InferReply(
            "timeout", error="no reply within deadline")

    def _normalize(self, entry, feeds):
        """Validate + coerce request feeds; returns (feeds, rows)."""
        from ..framework import dtype_to_np

        rows = None
        out = {}
        for name, (shape, dt) in entry.feed_specs.items():
            if name not in feeds:
                raise ValueError("missing feed %r" % name)
            arr = np.ascontiguousarray(feeds[name],
                                       dtype=dtype_to_np(dt))
            if tuple(arr.shape[1:]) != tuple(shape):
                raise ValueError(
                    "feed %r: expected trailing shape %s, got %s"
                    % (name, tuple(shape), tuple(arr.shape[1:])))
            if rows is None:
                rows = arr.shape[0]
            elif arr.shape[0] != rows:
                raise ValueError("inconsistent batch rows across feeds")
            out[name] = arr
        if rows is None or rows == 0:
            raise ValueError("empty request")
        if rows > max(self.buckets):
            raise ValueError("request rows %d exceed largest bucket %d"
                             % (rows, max(self.buckets)))
        return out, rows

    # -- dispatcher ----------------------------------------------------------

    def start(self):
        if self._running:
            return self
        self._running = True
        self._thread = threading.Thread(target=self._dispatch_loop,
                                        name="serving-dispatch", daemon=True)
        self._thread.start()
        return self

    def stop(self, drain_s=5.0):
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(drain_s)
            self._thread = None
        with self._cond:
            for req in self._queue:
                req.complete(InferReply("error", error="engine stopped"))
                if req.qspan is not None:
                    req.qspan.end()
                if req.span is not None:
                    req.span.annotate(status="error").end()
            self._queue.clear()

    def _bucket_for(self, rows):
        for b in self.buckets:
            if rows <= b:
                return b
        return max(self.buckets)

    def _collect(self):
        """Under the lock: wait for work, then coalesce same-model
        requests within the batch window up to the largest bucket."""
        while self._running and not self._queue:
            self._cond.wait(0.2)
        if not self._queue:
            return None, []
        model = self._queue[0].model
        window_end = time.perf_counter() + self.batch_window_ms / 1e3
        max_rows = max(self.buckets)
        while self._running:
            rows = sum(r.rows for r in self._queue if r.model == model)
            if rows >= max_rows:
                break
            left = window_end - time.perf_counter()
            if left <= 0:
                break
            self._cond.wait(min(left, 0.002))
        batch, rest, rows = [], [], 0
        for r in self._queue:
            if r.model == model and rows + r.rows <= max_rows:
                batch.append(r)
                rows += r.rows
            else:
                rest.append(r)
        self._queue[:] = rest
        _tm.set_gauge("serving_queue_depth", len(self._queue))
        return model, batch

    def _dispatch_loop(self):
        while True:
            with self._cond:
                if not self._running:
                    return
                model, batch = self._collect()
            if not batch:
                continue
            now = time.perf_counter()
            live = []
            for r in batch:
                if now > r.deadline:
                    _tm.inc("serving_timeout_total", model=r.model)
                    r.complete(InferReply(
                        "timeout", error="deadline expired in queue",
                        phases={"queue_wait_ms":
                                round((now - r.t_submit) * 1e3, 3),
                                "rows": r.rows}))
                    if r.qspan is not None:
                        r.qspan.annotate(expired=True).end()
                    if r.span is not None:
                        r.span.annotate(status="timeout").end()
                else:
                    r.t_dispatch = now
                    if r.qspan is not None:
                        r.qspan.end()
                    live.append(r)
            if live:
                self.in_batch = True
                try:
                    self._run_batch(self._models[model], live)
                finally:
                    self.in_batch = False
            if self.on_batch_boundary is not None:
                try:
                    self.on_batch_boundary()
                except Exception:
                    pass

    @staticmethod
    def _phases(r, execute_ms, bucket):
        """Per-request SLO phase attribution for the reply meta (always
        on — the client derives wire_ms as e2e minus server latency)."""
        t_d = r.t_dispatch if r.t_dispatch is not None else r.t_submit
        return {"queue_wait_ms": round((t_d - r.t_submit) * 1e3, 3),
                "execute_ms": round(execute_ms, 3),
                "bucket": bucket, "rows": r.rows}

    def _run_batch(self, entry, batch):
        rows = sum(r.rows for r in batch)
        bucket = self._bucket_for(rows)
        pred = entry.predictor
        # a batch serves N requests from (up to) N different traces, so
        # the batch span is a root that LINKS them rather than parenting
        bspan = _tr.start_span("serving.batch", model=entry.name,
                               bucket=bucket, rows=rows,
                               requests=len(batch))
        for r in batch:
            bspan.link(r.span.context if r.span is not None else None)
        with _tr.activate(bspan):
            with _tr.span("serving.pad_to_bucket", rows=rows,
                          bucket=bucket):
                feed = {}
                for name in entry.feed_specs:
                    parts = [r.feeds[name] for r in batch]
                    stacked = np.concatenate(parts, axis=0) \
                        if len(parts) > 1 else parts[0]
                    if rows < bucket:
                        pad = np.zeros(
                            (bucket - rows,) + stacked.shape[1:],
                            dtype=stacked.dtype)
                        stacked = np.concatenate([stacked, pad], axis=0)
                    feed[name] = stacked
            # write-through breadcrumb: if this replica is SIGKILLed
            # mid-execute, flightrec-<pid>.json already names the batch
            _tr.note("batch_start", model=entry.name, bucket=bucket,
                     req_ids=[r.req_id for r in batch])
            t0 = time.perf_counter()
            try:
                with _tr.span("serving.execute", bucket=bucket):
                    with scope_guard(pred._scope):
                        vals = pred._exe.run(pred.program(), feed=feed,
                                             fetch_list=pred._fetch_vars)
            except Exception as e:
                ms = (time.perf_counter() - t0) * 1e3
                for r in batch:
                    r.complete(InferReply(
                        "error", error=str(e),
                        phases=self._phases(r, ms, bucket)))
                    if r.span is not None:
                        r.span.annotate(status="error").end()
                _tm.inc("serving_batch_errors_total", model=entry.name)
                bspan.annotate(error=str(e)[:200]).end()
                return
        ms = (time.perf_counter() - t0) * 1e3
        entry.svc_ms = ms if entry.svc_ms <= 0 else \
            0.7 * entry.svc_ms + 0.3 * ms
        outs = [np.asarray(v) for v in vals]
        names = pred.get_output_names()
        off = 0
        for r in batch:
            sliced = {}
            for n, o in zip(names, outs):
                # slice per-request rows when the output carries the batch
                # dim; batch-free outputs replicate to every request
                sliced[n] = o[off:off + r.rows].copy() \
                    if o.ndim and o.shape[0] == bucket else o
            off += r.rows
            r.complete(InferReply("ok", outputs=sliced,
                                  phases=self._phases(r, ms, bucket)))
            if r.span is not None:
                r.span.annotate(status="ok", bucket=bucket).end()
            _tm.observe("serving_latency_ms", r.reply.latency_ms,
                        model=entry.name)
        _tm.inc("serving_batches_total", model=entry.name,
                bucket=str(bucket))
        _tm.observe("serving_batch_fill", rows / float(bucket),
                    model=entry.name)
        bspan.end()
        now = time.time()
        self._done_times.extend([now] * len(batch))
        cut = now - _QPS_WINDOW_S
        while self._done_times and self._done_times[0] < cut:
            self._done_times.pop(0)
        _tm.set_gauge("serving_qps", len(self._done_times) / _QPS_WINDOW_S)
