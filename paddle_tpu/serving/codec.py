"""Wire codec for the serving protocol (serving/server.py + client.py).

The native tensor-RPC transport (native/rpc.py) moves ONE named ndarray
per frame; an inference request/reply carries several arrays of mixed
dtype plus metadata (model, tenant, deadline, status).  This codec packs
that bundle into a single uint8 tensor: an 8-byte little-endian header
length, a JSON header (metadata + per-array dtype/shape), then the raw
array bytes concatenated — so one ``send_var``/``get_var`` round trip
moves a whole request, and the existing framing/dedupe/retry machinery
applies unchanged.

Wire keys (PS-style __dunder__ namespace, next to ``__metrics__`` and the
elastic ``__alive__``):

  ``__infer__:<req_id>``   client -> server, packed request
                           meta: model / tenant / req_id / deadline_ms
  ``__reply__:<req_id>``   server -> client, packed reply
                           meta: status ok|shed|timeout|error,
                           retry_after_ms on shed, outputs name order
  ``__spec__:<model>``     server-published feed/fetch signature + buckets
                           (loadgen synthesizes valid feeds from it)
  ``__generate__:<id>``    autoregressive request: prompt ids array +
                           meta model / max_new_tokens / stream
  ``__stream__:<id>:<k>``  k-th generated-token chunk (meta token / i /
                           done / status); the client's parked GETs walk
                           k = 0, 1, ... until done — token-level TTFT
                           and inter-token latency fall out client-side
  ``__abort__:<id>``       client gave up (timeout replay): the decode
                           engine drops the sequence and frees its paged
                           KV blocks so an abandoned prefill can't pin
                           the pool

Control-plane keys (PR 16):

  ``__retire__``           coordinator -> replica: stop admitting, drain
                           the queue at a batch boundary, then exit (the
                           autoscaler's graceful scale-down path)
  ``__rollout__``          per-replica published rollout state (packed
                           {"models": {base: {active/canary/fraction/
                           state}}}) — the chaos leg GETs it from every
                           survivor to assert version agreement
  ``__rollout_set__``      coordinator -> replica state broadcast (same
                           payload); idempotent, re-sent periodically so
                           a replica that missed a flip converges
  ``__rollout_ctl__:<id>`` client -> coordinator admin command
                           (start/flip/abort/status); the reply lands on
                           ``__reply__:<id>`` like any request

Requests carry their SLO tier in the meta under ``TIER`` ("paid" /
"free" / "batch"); the engine's deadline-weighted admission sheds
low-weight tiers first under overload, counted per tier in
``serving_tier_shed_total{tier}``.

Distributed tracing (core/tracing.py) rides the meta under the
``TRACEPARENT`` key: the client stamps its root span's W3C-style
``traceparent`` into the request meta, the server parents its admission
span under it, and the reply meta echoes it (plus per-phase timings under
``"phases"``) so one trace_id spans client and replica processes.
"""

import json

import numpy as np

__all__ = ["pack", "unpack", "INFER_KEY", "REPLY_KEY", "SPEC_KEY",
           "ALIVE_KEY", "GEN_KEY", "STREAM_KEY", "ABORT_KEY",
           "RETIRE_KEY", "ROLLOUT_KEY", "ROLLOUT_SET_KEY",
           "ROLLOUT_CTL_KEY", "TRACEPARENT", "TIER"]

INFER_KEY = "__infer__:"
REPLY_KEY = "__reply__:"
SPEC_KEY = "__spec__:"
ALIVE_KEY = "__alive__"
# autoregressive decode: request, per-token stream chunks (suffixed
# ":<index>"), and client-side abandonment (frees the paged KV blocks)
GEN_KEY = "__generate__:"
STREAM_KEY = "__stream__:"
ABORT_KEY = "__abort__:"
# serving control plane: autoscaler drain-and-exit order, rollout state
# (published per replica / broadcast by the coordinator), admin commands
RETIRE_KEY = "__retire__"
ROLLOUT_KEY = "__rollout__"
ROLLOUT_SET_KEY = "__rollout_set__"
ROLLOUT_CTL_KEY = "__rollout_ctl__:"
# meta key carrying the W3C-style trace context across the wire
TRACEPARENT = "traceparent"
# meta key carrying the request's SLO tier (paid|free|batch)
TIER = "tier"


def pack(meta, arrays=()):
    """(meta dict, [ndarray, ...]) -> one uint8 ndarray."""
    arrays = [np.ascontiguousarray(a) for a in arrays]
    header = json.dumps({
        "meta": meta,
        "arrays": [{"dtype": a.dtype.str, "shape": list(a.shape)}
                   for a in arrays],
    }).encode("utf-8")
    parts = [len(header).to_bytes(8, "little"), header]
    parts.extend(a.tobytes() for a in arrays)
    return np.frombuffer(b"".join(parts), dtype=np.uint8).copy()


def unpack(arr):
    """Inverse of pack: uint8 ndarray -> (meta dict, [ndarray, ...])."""
    buf = np.ascontiguousarray(np.asarray(arr, dtype=np.uint8)).tobytes()
    hlen = int.from_bytes(buf[:8], "little")
    head = json.loads(buf[8:8 + hlen].decode("utf-8"))
    out, off = [], 8 + hlen
    for spec in head["arrays"]:
        dt = np.dtype(spec["dtype"])
        shape = tuple(spec["shape"])
        n = dt.itemsize * int(np.prod(shape, dtype=np.int64)) \
            if shape else dt.itemsize
        out.append(np.frombuffer(buf[off:off + n], dtype=dt)
                   .reshape(shape).copy())
        off += n
    return head["meta"], out
